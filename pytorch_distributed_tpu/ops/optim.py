"""Optimizers.

Replaces ``torch.optim.SGD(params, lr, momentum=0.9, weight_decay=1e-4)``
(``resnet_single_gpu.py:108``, ``restnet_ddp.py:122``) with an optax chain
that reproduces torch's exact update rule:

    g = g + wd * p            (decoupled *into* the gradient, torch-style)
    buf = mu * buf + g        (dampening 0, nesterov False — torch defaults)
    p = p - lr * buf

i.e. ``add_decayed_weights`` *before* the momentum trace, and optax's
``trace`` (not ``sgd``'s scaled variant) so the momentum buffer matches
torch's bit-for-bit given the same inputs — verified against torch CPU in
tests/test_ops.py.
"""

from __future__ import annotations

from typing import Callable, Union

import jax
import jax.numpy as jnp
import optax

ScalarOrSchedule = Union[float, Callable]


def spec_axes(spec) -> tuple:
    """Mesh axis names a PartitionSpec shards over (order-preserving).
    The one shared extraction for every 'reduce over the axes this leaf
    is / is not sharded on' site (here and train/lm.py's grad combine)."""
    named: list = []
    if spec is None:
        return ()
    for part in spec:
        if part is None:
            continue
        for a in part if isinstance(part, tuple) else (part,):
            if a not in named:
                named.append(a)
    return tuple(named)


def sharded_global_norm(tree, specs=None) -> jnp.ndarray:
    """Global L2 norm of a gradient pytree, correct INSIDE ``shard_map``.

    The subtlety the reference never faced (SGD ResNet needed no clipping,
    ``restnet_ddp.py:122``): under this framework's shard_map steps, a
    leaf's gradient is complete-but-LOCAL for the mesh axes its
    PartitionSpec names (TP's Megatron shards over ``model``, FSDP's
    scatter over ``data``, PP's stage stacks over ``stage``) and
    replicated over the rest. So each leaf's local square-sum is psum'd
    over exactly the axes its spec names — sharded leaves recombine,
    replicated leaves contribute once — and every device agrees on the
    result. With ``specs=None`` (fully-replicated grads, or outside
    shard_map) this reduces to the plain ``optax.global_norm``.

    Accumulates in float32 regardless of gradient dtype. Square-sums are
    BUCKETED by sharded-axis set before reducing — one scalar psum per
    distinct axis set (typically <=3), not one per leaf (XLA only merges
    collectives with identical replica groups, so per-leaf scalar psums
    would stay separate in the hot step).
    """
    buckets: dict = {}

    def add(g, spec):
        ax = spec_axes(spec)
        sq = jnp.sum(jnp.square(g.astype(jnp.float32)))  # jaxlint: disable=precision-cast -- global-norm square-sums accumulate in fp32 for every policy
        buckets[ax] = buckets.get(ax, jnp.float32(0.0)) + sq

    if specs is None:
        for g in jax.tree.leaves(tree):
            add(g, None)
    else:
        jax.tree.map(add, tree, specs)
    total = jnp.float32(0.0)
    for ax, sq in buckets.items():
        total = total + (jax.lax.psum(sq, ax) if ax else sq)
    return jnp.sqrt(total)


def clip_grads_by_global_norm(grads, max_norm: float, specs=None):
    """Clip a gradient pytree to ``max_norm`` global L2 norm (sharding-
    aware; see ``sharded_global_norm``). Returns ``(clipped, pre_norm)``.
    Same semantics as ``optax.clip_by_global_norm``:
    ``g * max_norm / max(norm, max_norm)`` — identity when under the
    threshold, never up-scales."""
    gnorm = sharded_global_norm(grads, specs)
    scale = max_norm / jnp.maximum(gnorm, max_norm)
    clipped = jax.tree.map(lambda g: (g * scale).astype(g.dtype), grads)
    return clipped, gnorm


def clip_by_global_norm(
    max_norm: float, param_specs=None
) -> optax.GradientTransformation:
    """optax transformation form of ``clip_grads_by_global_norm`` for use
    in chains. ``param_specs``: params-shaped PartitionSpec tree when the
    chain runs inside shard_map on sharded gradients; None for replicated
    /pjit use. Stateless — adding it to a chain does not change the
    optimizer state's structure."""

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        del params
        clipped, _ = clip_grads_by_global_norm(updates, max_norm, param_specs)
        return clipped, state

    return optax.GradientTransformation(init_fn, update_fn)


def sgd_with_weight_decay(
    learning_rate: ScalarOrSchedule,
    momentum: float = 0.9,
    weight_decay: float = 1e-4,
    nesterov: bool = False,
) -> optax.GradientTransformation:
    """torch.optim.SGD-equivalent update rule (see module docstring)."""
    parts = []
    if weight_decay:
        parts.append(optax.add_decayed_weights(weight_decay))
    if momentum:
        parts.append(optax.trace(decay=momentum, nesterov=nesterov))
    parts.append(optax.scale_by_learning_rate(learning_rate))
    return optax.chain(*parts)


_REGISTRY = {}


def register_optimizer(name: str):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn

    return deco


register_optimizer("sgd")(sgd_with_weight_decay)
register_optimizer("adamw")(
    lambda learning_rate, weight_decay=1e-4, **kw: optax.adamw(
        learning_rate, weight_decay=weight_decay, **kw
    )
)


def build_optimizer(name: str, learning_rate: ScalarOrSchedule, **kwargs):
    """Construct a registered optimizer by name (config-driven entry point)."""
    try:
        factory = _REGISTRY[name]
    except KeyError:
        raise ValueError(f"unknown optimizer {name!r}; known: {sorted(_REGISTRY)}")
    return factory(learning_rate, **kwargs)
