"""Optimizers.

Replaces ``torch.optim.SGD(params, lr, momentum=0.9, weight_decay=1e-4)``
(``resnet_single_gpu.py:108``, ``restnet_ddp.py:122``) with an optax chain
that reproduces torch's exact update rule:

    g = g + wd * p            (decoupled *into* the gradient, torch-style)
    buf = mu * buf + g        (dampening 0, nesterov False — torch defaults)
    p = p - lr * buf

i.e. ``add_decayed_weights`` *before* the momentum trace, and optax's
``trace`` (not ``sgd``'s scaled variant) so the momentum buffer matches
torch's bit-for-bit given the same inputs — verified against torch CPU in
tests/test_ops.py.
"""

from __future__ import annotations

from typing import Callable, Union

import optax

ScalarOrSchedule = Union[float, Callable]


def sgd_with_weight_decay(
    learning_rate: ScalarOrSchedule,
    momentum: float = 0.9,
    weight_decay: float = 1e-4,
    nesterov: bool = False,
) -> optax.GradientTransformation:
    """torch.optim.SGD-equivalent update rule (see module docstring)."""
    parts = []
    if weight_decay:
        parts.append(optax.add_decayed_weights(weight_decay))
    if momentum:
        parts.append(optax.trace(decay=momentum, nesterov=nesterov))
    parts.append(optax.scale_by_learning_rate(learning_rate))
    return optax.chain(*parts)


_REGISTRY = {}


def register_optimizer(name: str):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn

    return deco


register_optimizer("sgd")(sgd_with_weight_decay)
register_optimizer("adamw")(
    lambda learning_rate, weight_decay=1e-4, **kw: optax.adamw(
        learning_rate, weight_decay=weight_decay, **kw
    )
)


def build_optimizer(name: str, learning_rate: ScalarOrSchedule, **kwargs):
    """Construct a registered optimizer by name (config-driven entry point)."""
    try:
        factory = _REGISTRY[name]
    except KeyError:
        raise ValueError(f"unknown optimizer {name!r}; known: {sorted(_REGISTRY)}")
    return factory(learning_rate, **kwargs)
