"""Fused paged-attention Pallas kernel: flash-decode over a block-pooled
KV cache, reading the block tables directly from SMEM.

This is the ``gather_impl="pallas"`` spelling of
``ops.attention.paged_attention`` (the serving read path). The dense
spelling gathers every request's block chain back into a logical
``[B, W·block_len, H_kv, D]`` sequence with ``jnp.take`` — materializing
the full gathered KV in HBM on every decode tick, the exact cost
PagedAttention (Kwon et al., SOSP 2023 — PAPERS.md) exists to avoid.
Here the gather never materializes: the block table rides in as a
scalar-prefetch operand (SMEM), and each KV block's BlockSpec *index
map* resolves ``tables[b, j]`` — so the pipeline DMAs pool blocks
HBM→VMEM in chain order directly, touching only the chain's blocks.

Structure (per the in-tree FlashAttention kernel,
``ops/flash_attention.py``, and the TPU Pallas playbook
``/opt/skills/guides/pallas_guide.md``):

- grid ``(B, H_kv, W)`` with the block-chain sweep innermost and
  sequential ("arbitrary" semantics — it carries the online-softmax
  recurrence); the running (m, l, acc) state lives in VMEM scratch,
  persisting across the chain for each (batch row, narrow head);
- GQA is folded into the row dimension: queries regroup to
  ``[B, H_kv, G·C, D]`` so each narrow head's whole query group shares
  one staged KV block — the widened K/V never exists, mirroring the
  dense spelling's grouped einsum. ``C == 1`` (decode tick) and
  ``C == chunk`` (chunked prefill) are the same kernel at different row
  counts;
- causal/frontier masking ``k_pos <= q_position`` per row; table
  entries past a request's allocation point at the trash block, whose
  logical positions exceed every live query position, so they mask out
  exactly like the dense spelling. Blocks entirely past the batch row's
  query frontier are skipped with ``pl.when`` (no FLOPs, no dequant);
- softmax statistics in fp32 regardless of pool/compute dtype;
- quantized pools (int8 or fp8) dequantize INSIDE the kernel: per-
  (block, slot, head) scale siblings (``serving.kv_pool.quantize_kv``)
  ride the same index maps as their pool, so the f32 K/V rows exist
  only in VMEM, block by block — HBM holds 1-byte values + scales (the
  2D/(D+4) int8 / 2D/(D+1) fp8 pool-capacity win). fp8 scale siblings
  are int8 power-of-two exponents: the in-VMEM multiplier is ``2**e``
  (exact), so the fp8 cast is the whole error budget;
- flash-decoding (round 20; FlashAttention-2's work partitioning,
  PAPERS.md §2, applied to decode): ``split_s`` > 1 splits the chain
  sweep across S grid workers, each owning ``ceil(W/S)`` chain blocks
  with its own (m, l, acc) VMEM partials, and a second-stage cross-
  worker log-sum-exp merge (fp32, outside the kernel) combines them —
  one long-context request (W large, B small) fills the chip instead
  of serializing on the innermost grid axis. ``split_s=None``
  auto-enables via ``auto_split_s`` when W/B crosses the threshold;
  ``pl.when`` frontier skipping applies per worker unchanged;
- the write side has a fused twin: ``paged_quantize_scatter`` computes
  per-row-per-head scales and writes quantized rows + scale siblings
  inside the scatter (``input_output_aliases`` keeps unvisited pool
  blocks in place), sharing ``serving.kv_pool.quantize_rows`` with the
  jnp spelling so the two are bit-equivalent by construction;
- ``interpret=None`` auto-detects non-TPU backends and runs the Pallas
  interpreter, so CPU tier-1 executes the same call sites unmodified
  (the ``flash_attention`` convention).

Shapes follow the framework convention: q ``[B, C, H, D]``, pools
``[n_blocks, block_len, H_kv, D]``, tables ``[B, W]``, positions
``[B, C]``.
"""
# jaxlint: disable-file=precision-cast -- the kernel's softmax state (m, l, acc) is fp32 by the attention-path contract and int8 pool blocks dequantize to fp32 in VMEM; every cast here feeds that fp32 recurrence

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from pytorch_distributed_tpu.ops.attention import NEG_INF

# jax 0.4.3x names the param class TPUCompilerParams; newer releases
# CompilerParams (which ops/flash_attention.py uses). Resolve once so the
# non-interpret branch works on either.
_COMPILER_PARAMS = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)


#: flash-decoding auto policy (``split_s=None``): split when one batch
#: row's chain is at least this many blocks per batch row — the shape
#: where the W grid axis serializes a mostly-idle chip.
SPLIT_THRESHOLD = 8
#: auto policy's worker-count cap (forced ``split_s=`` may exceed it)
MAX_SPLIT = 8


def auto_split_s(w: int, b: int, *, threshold: int = SPLIT_THRESHOLD,
                 max_split: int = MAX_SPLIT) -> int:
    """Flash-decoding worker count for a ``[B, W]`` block table: 1 (no
    split) until ``W / B >= threshold`` — few long chains is the shape
    where the sequential chain sweep leaves grid workers idle — then
    ``min(max_split, W)`` so every worker owns at least one block.
    Static shapes in, static count out: the decision is compiled into
    the program, and the registry fingerprint keys it via the config's
    ``split_s`` field."""
    if w // max(b, 1) < threshold:
        return 1
    return min(max_split, w)


def _attend_block(q_ref, qpos, k_ref, v_ref, ks_ref, vs_ref,
                  m_scr, l_scr, acc_scr, *, scale, k_start,
                  quantized, fp8_scales):
    """One chain block's online-softmax update — the shared inner body
    of the single-worker and split-S kernels (one spelling, so the
    split path cannot drift from the sweep it partitions)."""
    # Fold the softmax scale into Q (one [R, D] multiply, the flash
    # kernel's trick), fp32 logits on the MXU.
    q = q_ref[0, 0]  # [R, D]
    k = k_ref[0, :, 0, :]  # [block_len, D]
    v = v_ref[0, :, 0, :]
    if quantized:
        # dequantize THIS block only, in VMEM: per-(slot, head) scale
        # siblings gathered by the same table-driven index map. fp8
        # pools carry int8 exponents — multiplier 2**e, exact in fp32
        # (kv_pool.scale_factors spelling).
        ks = ks_ref[0, :, 0]
        vs = vs_ref[0, :, 0]
        if fp8_scales:
            ks = jnp.exp2(ks.astype(jnp.float32))
            vs = jnp.exp2(vs.astype(jnp.float32))
        k = k.astype(jnp.float32) * ks[:, None]
        v = v.astype(jnp.float32) * vs[:, None]
    s = jax.lax.dot_general(
        q * jnp.asarray(scale, q.dtype), k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [R, block_len]
    k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    # Frontier mask: key position j visible iff j <= the row's query
    # position. Trash-table entries (unallocated tail) carry logical
    # positions past every live frontier → fully masked, exactly the
    # dense spelling's argument. Padding rows (qpos == -1) mask
    # everything → l stays 0 → zeros out, sliced away by the caller.
    mask = k_pos <= qpos[:, None]
    s = jnp.where(mask, s, NEG_INF)
    m_prev = m_scr[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    p = p * mask  # fully-masked rows stay all-zero (l == 0 → out 0)
    corr = jnp.exp(m_prev - m_new)
    l_scr[:] = jnp.broadcast_to(
        l_scr[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True),
        l_scr.shape,
    )
    acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)


def _paged_kernel(
    tables_ref,  # scalar-prefetch [B, W] int32 (SMEM)
    q_ref, qpos_ref, k_ref, v_ref,  # + (ks_ref, vs_ref) when quantized
    *refs,
    scale: float, block_len: int, quantized: bool, fp8_scales: bool,
):
    if quantized:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = refs
    else:
        ks_ref = vs_ref = None
        o_ref, m_scr, l_scr, acc_scr = refs
    j = pl.program_id(2)
    n_w = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    qpos = qpos_ref[0]  # [R] per-row absolute query positions (pad = -1)
    k_start = j * block_len

    def _block():
        _attend_block(q_ref, qpos, k_ref, v_ref, ks_ref, vs_ref,
                      m_scr, l_scr, acc_scr, scale=scale, k_start=k_start,
                      quantized=quantized, fp8_scales=fp8_scales)

    # A chain block entirely past this batch row's query frontier
    # contributes nothing — skip its FLOPs (and its dequant) entirely.
    pl.when(k_start <= jnp.max(qpos))(_block)

    @pl.when(j == n_w - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:, :1], 1e-37)
        o_ref[0, 0] = (acc_scr[:] / l).astype(o_ref.dtype)


def _paged_split_kernel(
    tables_ref,  # scalar-prefetch [B, W] int32 (SMEM)
    q_ref, qpos_ref, k_ref, v_ref,  # + (ks_ref, vs_ref) when quantized
    *refs,
    scale: float, block_len: int, quantized: bool, fp8_scales: bool,
    w: int, wc: int,
):
    """Flash-decoding worker kernel: grid ``(B, H_kv, S, ceil(W/S))``,
    worker s sweeps chain blocks ``[s*wc, min((s+1)*wc, W))`` with its
    own (m, l, acc) partials and emits them UN-normalized — the caller's
    fp32 log-sum-exp merge combines workers. Same ``_attend_block``
    inner body as the single-worker sweep, same ``pl.when`` frontier
    skip per worker (plus the ceil-split tail guard ``j < W``: past-end
    grid steps clamp their index map to a real block and skip)."""
    if quantized:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, m_scr, l_scr, acc_scr = refs
    else:
        ks_ref = vs_ref = None
        o_ref, m_ref, l_ref, m_scr, l_scr, acc_scr = refs
    jj = pl.program_id(3)

    @pl.when(jj == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    qpos = qpos_ref[0]  # [R] per-row absolute query positions (pad = -1)
    j = pl.program_id(2) * wc + jj  # logical chain index of this step
    k_start = j * block_len

    def _block():
        _attend_block(q_ref, qpos, k_ref, v_ref, ks_ref, vs_ref,
                      m_scr, l_scr, acc_scr, scale=scale, k_start=k_start,
                      quantized=quantized, fp8_scales=fp8_scales)

    pl.when((j < w) & (k_start <= jnp.max(qpos)))(_block)

    @pl.when(jj == wc - 1)
    def _finalize():
        o_ref[0, 0, 0] = acc_scr[:]
        m_ref[0, 0, 0] = m_scr[:]
        l_ref[0, 0, 0] = l_scr[:]


def paged_flash_attention(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    block_tables: jax.Array,
    q_positions: jax.Array,
    *,
    scale: Optional[float] = None,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
    split_s: Optional[int] = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused block-gather attention: decode/chunk queries against a
    block-pooled KV cache, no materialized gather.

    Args:
      q: ``[B, C, H, D]`` — C == 1 for a decode tick, C == chunk for
        chunked prefill.
      k_pool, v_pool: ``[n_blocks, block_len, H_kv, D]`` pooled cache
        (``H_kv <= H``, GQA); float dtypes, or int8 with ``k_scale``/
        ``v_scale`` set.
      block_tables: ``[B, W]`` int32 — request b's logical positions
        ``[w·block_len, (w+1)·block_len)`` live in pool block
        ``block_tables[b, w]``.
      q_positions: ``[B, C]`` int32 absolute positions; key position j
        is visible to query i iff ``j <= q_positions[i]``.
      k_scale, v_scale: ``[n_blocks, block_len, H_kv]`` scale siblings
        for quantized pools (``serving.kv_pool.quantize_kv`` layout:
        fp32 multipliers for int8 pools, int8 power-of-two exponents
        for fp8 pools); None for float pools.
      split_s: flash-decoding worker count for the chain sweep. None
        auto-enables (``auto_split_s``: split when W/B crosses the
        threshold), 1 forces the single-worker sweep, S > 1 splits the
        chain over S workers with un-normalized (m, l, acc) partials
        and a second-stage fp32 log-sum-exp merge. The combine is a
        different (but fp32) reduction order than the single sweep, so
        parity is bounded (≤ 1e-3 on fp32 logits), not bit-equal.
      interpret: force the Pallas interpreter; None auto-detects
        (interpreter on any non-TPU backend, like ``flash_attention``).

    Returns ``[B, C, H, D]`` in q's dtype; softmax statistics fp32.
    """
    from pytorch_distributed_tpu.serving.kv_pool import is_quantized_pool

    b, c, h, d = q.shape
    n_blocks, block_len, h_kv, _ = k_pool.shape
    if h % h_kv:
        raise ValueError(
            f"query heads {h} not a multiple of pool KV heads {h_kv}"
        )
    quantized = is_quantized_pool(k_pool.dtype)
    if quantized != (k_scale is not None):
        raise ValueError(
            "quantized (int8/fp8) pools need k_scale/v_scale and float "
            f"pools must not pass them (pool {k_pool.dtype}, k_scale "
            f"{'set' if k_scale is not None else 'None'})"
        )
    # fp8 pools carry int8 EXPONENT scale siblings (dequant 2**e); int8
    # pools carry fp32 multipliers — the scale dtype picks the spelling
    fp8_scales = bool(
        k_scale is not None and k_scale.dtype == jnp.dtype(jnp.int8)
    )
    if interpret is None:
        # Mosaic compiles only on TPU; every other backend runs the
        # interpreter so CPU tier-1 executes this exact call site.
        interpret = jax.default_backend() != "tpu"
    group = h // h_kv
    w = block_tables.shape[1]
    scale = scale if scale is not None else d ** -0.5
    if split_s is not None and split_s < 1:
        raise ValueError(f"split_s must be >= 1, got {split_s}")
    s_workers = split_s if split_s is not None else auto_split_s(w, b)
    s_workers = min(s_workers, w)  # every worker owns >= 1 chain block

    # GQA fold: query head h = kv·group + g reads narrow head kv, so the
    # per-narrow-head row block is its whole query group × chunk. Rows
    # pad to a sublane multiple; padding rows carry position -1 (every
    # key masked → zero rows, sliced away below).
    r = group * c
    r_pad = -(-r // 8) * 8
    q4 = jnp.moveaxis(q.reshape(b, c, h_kv, group, d), 1, 3)  # [B,Hkv,G,C,D]
    q4 = q4.reshape(b, h_kv, r, d)
    qpos = jnp.broadcast_to(
        q_positions.astype(jnp.int32)[:, None, :], (b, group, c)
    ).reshape(b, r)
    if r_pad != r:
        q4 = jnp.pad(q4, ((0, 0), (0, 0), (0, r_pad - r), (0, 0)))
        qpos = jnp.pad(qpos, ((0, 0), (0, r_pad - r)), constant_values=-1)

    out_dtype = q.dtype
    scratch_shapes = [
        pltpu.VMEM((r_pad, 128), jnp.float32),  # running row max m
        pltpu.VMEM((r_pad, 128), jnp.float32),  # running row sum l
        pltpu.VMEM((r_pad, d), jnp.float32),  # un-normalized output
    ]
    kern_kw = dict(scale=scale, block_len=block_len,
                   quantized=bool(quantized), fp8_scales=fp8_scales)

    if s_workers == 1:
        in_specs = [
            pl.BlockSpec((1, 1, r_pad, d), lambda b, h, j, t: (b, h, 0, 0)),
            pl.BlockSpec((1, r_pad), lambda b, h, j, t: (b, 0)),
            # the fused gather: the block table entry IS the index map —
            # the pipeline DMAs pool block tables[b, j] (this narrow
            # head's slice) straight into VMEM, no gathered copy in HBM
            pl.BlockSpec((1, block_len, 1, d),
                         lambda b, h, j, t: (t[b, j], 0, h, 0)),
            pl.BlockSpec((1, block_len, 1, d),
                         lambda b, h, j, t: (t[b, j], 0, h, 0)),
        ]
        operands = [q4, qpos, k_pool, v_pool]
        if quantized:
            in_specs += [
                pl.BlockSpec((1, block_len, 1),
                             lambda b, h, j, t: (t[b, j], 0, h)),
                pl.BlockSpec((1, block_len, 1),
                             lambda b, h, j, t: (t[b, j], 0, h)),
            ]
            operands += [k_scale, v_scale]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, h_kv, w),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, 1, r_pad, d),
                                   lambda b, h, j, t: (b, h, 0, 0)),
            scratch_shapes=scratch_shapes,
        )
        kwargs = {}
        if not interpret:
            kwargs["compiler_params"] = _COMPILER_PARAMS(
                dimension_semantics=("parallel", "parallel", "arbitrary")
            )
        out4 = pl.pallas_call(
            functools.partial(_paged_kernel, **kern_kw),
            out_shape=jax.ShapeDtypeStruct((b, h_kv, r_pad, d), out_dtype),
            grid_spec=grid_spec,
            interpret=interpret,
            **kwargs,
        )(block_tables.astype(jnp.int32), *operands)
        out4 = out4[:, :, :r]  # drop row padding
        return jnp.moveaxis(
            out4.reshape(b, h_kv, group, c, d), 3, 1
        ).reshape(b, c, h, d)

    # ---- flash-decoding split: S workers over the chain, LSE merge ----
    wc = -(-w // s_workers)  # chain blocks per worker (ceil split)

    def _kj(s, jj):
        # ceil-split tail: grid steps past the real chain clamp to the
        # last block — the kernel's ``j < w`` guard skips them, so the
        # clamped DMA target is never read into the statistics
        return jnp.minimum(s * wc + jj, w - 1)

    in_specs = [
        pl.BlockSpec((1, 1, r_pad, d), lambda b, h, s, j, t: (b, h, 0, 0)),
        pl.BlockSpec((1, r_pad), lambda b, h, s, j, t: (b, 0)),
        pl.BlockSpec((1, block_len, 1, d),
                     lambda b, h, s, j, t: (t[b, _kj(s, j)], 0, h, 0)),
        pl.BlockSpec((1, block_len, 1, d),
                     lambda b, h, s, j, t: (t[b, _kj(s, j)], 0, h, 0)),
    ]
    operands = [q4, qpos, k_pool, v_pool]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, block_len, 1),
                         lambda b, h, s, j, t: (t[b, _kj(s, j)], 0, h)),
            pl.BlockSpec((1, block_len, 1),
                         lambda b, h, s, j, t: (t[b, _kj(s, j)], 0, h)),
        ]
        operands += [k_scale, v_scale]
    part_spec = pl.BlockSpec((1, 1, 1, r_pad, d),
                             lambda b, h, s, j, t: (b, h, s, 0, 0))
    stat_spec = pl.BlockSpec((1, 1, 1, r_pad, 128),
                             lambda b, h, s, j, t: (b, h, s, 0, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, h_kv, s_workers, wc),
        in_specs=in_specs,
        out_specs=[part_spec, stat_spec, stat_spec],
        scratch_shapes=scratch_shapes,
    )
    kwargs = {}
    if not interpret:
        kwargs["compiler_params"] = _COMPILER_PARAMS(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")
        )
    acc_p, m_p, l_p = pl.pallas_call(
        functools.partial(_paged_split_kernel, w=w, wc=wc, **kern_kw),
        out_shape=[
            jax.ShapeDtypeStruct((b, h_kv, s_workers, r_pad, d),
                                 jnp.float32),
            jax.ShapeDtypeStruct((b, h_kv, s_workers, r_pad, 128),
                                 jnp.float32),
            jax.ShapeDtypeStruct((b, h_kv, s_workers, r_pad, 128),
                                 jnp.float32),
        ],
        grid_spec=grid_spec,
        interpret=interpret,
        **kwargs,
    )(block_tables.astype(jnp.int32), *operands)
    # Second stage: cross-worker log-sum-exp merge, fp32. A worker whose
    # every block was masked/skipped holds (m=NEG_INF, l=0, acc=0):
    # NEG_INF is finite, so exp(m - m_star) is exp(0)=1 at worst and its
    # zero l/acc contribute nothing — all-masked rows (padding) keep the
    # single-sweep convention l=0 → out 0 via the epsilon.
    m_w = m_p[..., 0]  # [B, H_kv, S, R] (broadcast columns, take one)
    l_w = l_p[..., 0]
    m_star = jnp.max(m_w, axis=2)
    alpha = jnp.exp(m_w - m_star[:, :, None])  # [B, H_kv, S, R]
    l_tot = jnp.sum(l_w * alpha, axis=2)  # [B, H_kv, R]
    acc = jnp.sum(acc_p * alpha[..., None], axis=2)  # [B, H_kv, R, D]
    out4 = (acc / jnp.maximum(l_tot, 1e-37)[..., None]).astype(out_dtype)
    out4 = out4[:, :, :r]  # drop row padding
    return jnp.moveaxis(
        out4.reshape(b, h_kv, group, c, d), 3, 1
    ).reshape(b, c, h, d)


def paged_quantize_scatter(
    k: jax.Array,
    v: jax.Array,
    blk: jax.Array,
    off: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    k_scale: jax.Array,
    v_scale: jax.Array,
    *,
    interpret: bool | None = None,
):
    """Fused quantize-on-scatter: write a chunk's KV rows into a
    quantized pool, computing each row's per-head scale and casting to
    the pool dtype INSIDE the scatter — the write-side twin of the
    fused gather above. The jnp spelling (``serving.kv_pool.
    quantize_kv`` + four ``.at[rows].set``) stays the dense/interpret
    reference; both call ``kv_pool.quantize_rows`` for the row math, so
    the two spellings produce bit-identical pools and greedy streams
    cannot diverge across the scatter implementation.

    Grid ``(B·L,)``: one step per written row. The (block, offset)
    destination pair rides in as a scalar-prefetch operand and the pool
    OUTPUT BlockSpec index map resolves it — the scatter analogue of the
    gather's table-driven index map. ``input_output_aliases`` pins each
    pool/scale output to its input buffer, so the write is in place and
    unvisited blocks keep their rows (required for correctness, not
    just speed — the pools are donated engine state). Duplicate
    destinations exist only for trash-block writes (inactive lanes),
    where any write order is harmless garbage.

    Args:
      k, v: ``[B, L, H_kv, D]`` rows to write (post-RoPE, compute
        dtype).
      blk, off: ``[B, L]`` int32 destination block ids / in-block
        offsets (``models.transformer.Attention`` derives them from the
        block table and ``position_offset``).
      k_pool, v_pool: ``[n_blocks, block_len, H_kv, D]`` quantized
        pools (int8 or fp8).
      k_scale, v_scale: ``[n_blocks, block_len, H_kv]`` scale siblings
        (fp32 multipliers for int8, int8 exponents for fp8 —
        ``kv_pool.pool_scale_dtype``).
      interpret: force the Pallas interpreter; None auto-detects.

    Returns the updated ``(k_pool, v_pool, k_scale, v_scale)``.
    """
    from pytorch_distributed_tpu.serving.kv_pool import (
        is_quantized_pool,
        quantize_rows,
    )

    if not is_quantized_pool(k_pool.dtype):
        raise ValueError(
            "paged_quantize_scatter writes quantized pools (int8/fp8); "
            f"got pool dtype {k_pool.dtype} — raw pools scatter with a "
            "plain .at[].set, there is nothing to fuse"
        )
    b, l, h_kv, d = k.shape
    n = b * l
    pool_dt = k_pool.dtype
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # one [2, N] scalar-prefetch operand: row i writes pool block
    # idx[0, i] at in-block offset idx[1, i]
    idx = jnp.stack(
        [blk.reshape(-1), off.reshape(-1)]
    ).astype(jnp.int32)
    kf = k.reshape(n, h_kv, d)
    vf = v.reshape(n, h_kv, d)

    def _kernel(idx_ref, k_ref, v_ref, kp_in, vp_in, ks_in, vs_in,
                kp_out, vp_out, ks_out, vs_out):
        del idx_ref, kp_in, vp_in, ks_in, vs_in  # aliased with outputs
        qk, sk = quantize_rows(k_ref[0].astype(jnp.float32), pool_dt)
        qv, sv = quantize_rows(v_ref[0].astype(jnp.float32), pool_dt)
        kp_out[0, 0] = qk
        vp_out[0, 0] = qv
        ks_out[0, 0] = sk
        vs_out[0, 0] = sv

    row_spec = pl.BlockSpec((1, h_kv, d), lambda i, idx: (i, 0, 0))
    pool_spec = pl.BlockSpec(
        (1, 1, h_kv, d), lambda i, idx: (idx[0, i], idx[1, i], 0, 0)
    )
    sc_spec = pl.BlockSpec(
        (1, 1, h_kv), lambda i, idx: (idx[0, i], idx[1, i], 0)
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n,),
        in_specs=[row_spec, row_spec,
                  pool_spec, pool_spec, sc_spec, sc_spec],
        out_specs=[pool_spec, pool_spec, sc_spec, sc_spec],
    )
    kwargs = {}
    if not interpret:
        # trash-block duplicates make write order observable in garbage
        # only; still, "arbitrary" keeps the sweep sequential
        kwargs["compiler_params"] = _COMPILER_PARAMS(
            dimension_semantics=("arbitrary",)
        )
    return pl.pallas_call(
        _kernel,
        out_shape=[
            jax.ShapeDtypeStruct(k_pool.shape, k_pool.dtype),
            jax.ShapeDtypeStruct(v_pool.shape, v_pool.dtype),
            jax.ShapeDtypeStruct(k_scale.shape, k_scale.dtype),
            jax.ShapeDtypeStruct(v_scale.shape, v_scale.dtype),
        ],
        grid_spec=grid_spec,
        # operand index space includes the scalar-prefetch arg: 0=idx,
        # 1=k rows, 2=v rows, 3..6=the four pools -> outputs 0..3
        input_output_aliases={3: 0, 4: 1, 5: 2, 6: 3},
        interpret=interpret,
        **kwargs,
    )(idx, kf, vf, k_pool, v_pool, k_scale, v_scale)
