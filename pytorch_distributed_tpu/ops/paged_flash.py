"""Fused paged-attention Pallas kernel: flash-decode over a block-pooled
KV cache, reading the block tables directly from SMEM.

This is the ``gather_impl="pallas"`` spelling of
``ops.attention.paged_attention`` (the serving read path). The dense
spelling gathers every request's block chain back into a logical
``[B, W·block_len, H_kv, D]`` sequence with ``jnp.take`` — materializing
the full gathered KV in HBM on every decode tick, the exact cost
PagedAttention (Kwon et al., SOSP 2023 — PAPERS.md) exists to avoid.
Here the gather never materializes: the block table rides in as a
scalar-prefetch operand (SMEM), and each KV block's BlockSpec *index
map* resolves ``tables[b, j]`` — so the pipeline DMAs pool blocks
HBM→VMEM in chain order directly, touching only the chain's blocks.

Structure (per the in-tree FlashAttention kernel,
``ops/flash_attention.py``, and the TPU Pallas playbook
``/opt/skills/guides/pallas_guide.md``):

- grid ``(B, H_kv, W)`` with the block-chain sweep innermost and
  sequential ("arbitrary" semantics — it carries the online-softmax
  recurrence); the running (m, l, acc) state lives in VMEM scratch,
  persisting across the chain for each (batch row, narrow head);
- GQA is folded into the row dimension: queries regroup to
  ``[B, H_kv, G·C, D]`` so each narrow head's whole query group shares
  one staged KV block — the widened K/V never exists, mirroring the
  dense spelling's grouped einsum. ``C == 1`` (decode tick) and
  ``C == chunk`` (chunked prefill) are the same kernel at different row
  counts;
- causal/frontier masking ``k_pos <= q_position`` per row; table
  entries past a request's allocation point at the trash block, whose
  logical positions exceed every live query position, so they mask out
  exactly like the dense spelling. Blocks entirely past the batch row's
  query frontier are skipped with ``pl.when`` (no FLOPs, no dequant);
- softmax statistics in fp32 regardless of pool/compute dtype;
- int8 pools dequantize INSIDE the kernel: per-(block, slot, head)
  scales (``serving.kv_pool.quantize_kv``) ride the same index maps as
  their pool, so the f32 K/V rows exist only in VMEM, block by block —
  HBM holds int8 + scales (the ~2x pool-capacity win);
- ``interpret=None`` auto-detects non-TPU backends and runs the Pallas
  interpreter, so CPU tier-1 executes the same call sites unmodified
  (the ``flash_attention`` convention).

Shapes follow the framework convention: q ``[B, C, H, D]``, pools
``[n_blocks, block_len, H_kv, D]``, tables ``[B, W]``, positions
``[B, C]``.
"""
# jaxlint: disable-file=precision-cast -- the kernel's softmax state (m, l, acc) is fp32 by the attention-path contract and int8 pool blocks dequantize to fp32 in VMEM; every cast here feeds that fp32 recurrence

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from pytorch_distributed_tpu.ops.attention import NEG_INF

# jax 0.4.3x names the param class TPUCompilerParams; newer releases
# CompilerParams (which ops/flash_attention.py uses). Resolve once so the
# non-interpret branch works on either.
_COMPILER_PARAMS = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)


def _paged_kernel(
    tables_ref,  # scalar-prefetch [B, W] int32 (SMEM)
    q_ref, qpos_ref, k_ref, v_ref,  # + (ks_ref, vs_ref) when quantized
    *refs,
    scale: float, block_len: int, quantized: bool,
):
    if quantized:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = refs
    else:
        o_ref, m_scr, l_scr, acc_scr = refs
    j = pl.program_id(2)
    n_w = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    qpos = qpos_ref[0]  # [R] per-row absolute query positions (pad = -1)
    k_start = j * block_len

    def _block():
        # Fold the softmax scale into Q (one [R, D] multiply, the flash
        # kernel's trick), fp32 logits on the MXU.
        q = q_ref[0, 0]  # [R, D]
        k = k_ref[0, :, 0, :]  # [block_len, D]
        v = v_ref[0, :, 0, :]
        if quantized:
            # dequantize THIS block only, in VMEM: per-(slot, head)
            # scales gathered by the same table-driven index map
            k = k.astype(jnp.float32) * ks_ref[0, :, 0][:, None]
            v = v.astype(jnp.float32) * vs_ref[0, :, 0][:, None]
        s = jax.lax.dot_general(
            q * jnp.asarray(scale, q.dtype), k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [R, block_len]
        k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        # Frontier mask: key position j visible iff j <= the row's query
        # position. Trash-table entries (unallocated tail) carry logical
        # positions past every live frontier → fully masked, exactly the
        # dense spelling's argument. Padding rows (qpos == -1) mask
        # everything → l stays 0 → zeros out, sliced away by the caller.
        mask = k_pos <= qpos[:, None]
        s = jnp.where(mask, s, NEG_INF)
        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = p * mask  # fully-masked rows stay all-zero (l == 0 → out 0)
        corr = jnp.exp(m_prev - m_new)
        l_scr[:] = jnp.broadcast_to(
            l_scr[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True),
            l_scr.shape,
        )
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    # A chain block entirely past this batch row's query frontier
    # contributes nothing — skip its FLOPs (and its dequant) entirely.
    pl.when(k_start <= jnp.max(qpos))(_block)

    @pl.when(j == n_w - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:, :1], 1e-37)
        o_ref[0, 0] = (acc_scr[:] / l).astype(o_ref.dtype)


def paged_flash_attention(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    block_tables: jax.Array,
    q_positions: jax.Array,
    *,
    scale: Optional[float] = None,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused block-gather attention: decode/chunk queries against a
    block-pooled KV cache, no materialized gather.

    Args:
      q: ``[B, C, H, D]`` — C == 1 for a decode tick, C == chunk for
        chunked prefill.
      k_pool, v_pool: ``[n_blocks, block_len, H_kv, D]`` pooled cache
        (``H_kv <= H``, GQA); float dtypes, or int8 with ``k_scale``/
        ``v_scale`` set.
      block_tables: ``[B, W]`` int32 — request b's logical positions
        ``[w·block_len, (w+1)·block_len)`` live in pool block
        ``block_tables[b, w]``.
      q_positions: ``[B, C]`` int32 absolute positions; key position j
        is visible to query i iff ``j <= q_positions[i]``.
      k_scale, v_scale: ``[n_blocks, block_len, H_kv]`` fp32
        dequantization scales for int8 pools
        (``serving.kv_pool.quantize_kv`` layout); None for float pools.
      interpret: force the Pallas interpreter; None auto-detects
        (interpreter on any non-TPU backend, like ``flash_attention``).

    Returns ``[B, C, H, D]`` in q's dtype; softmax statistics fp32.
    """
    b, c, h, d = q.shape
    n_blocks, block_len, h_kv, _ = k_pool.shape
    if h % h_kv:
        raise ValueError(
            f"query heads {h} not a multiple of pool KV heads {h_kv}"
        )
    quantized = jnp.issubdtype(k_pool.dtype, jnp.integer)
    if quantized != (k_scale is not None):
        raise ValueError(
            "int8 pools need k_scale/v_scale and float pools must not "
            f"pass them (pool {k_pool.dtype}, k_scale "
            f"{'set' if k_scale is not None else 'None'})"
        )
    if interpret is None:
        # Mosaic compiles only on TPU; every other backend runs the
        # interpreter so CPU tier-1 executes this exact call site.
        interpret = jax.default_backend() != "tpu"
    group = h // h_kv
    w = block_tables.shape[1]
    scale = scale if scale is not None else d ** -0.5

    # GQA fold: query head h = kv·group + g reads narrow head kv, so the
    # per-narrow-head row block is its whole query group × chunk. Rows
    # pad to a sublane multiple; padding rows carry position -1 (every
    # key masked → zero rows, sliced away below).
    r = group * c
    r_pad = -(-r // 8) * 8
    q4 = jnp.moveaxis(q.reshape(b, c, h_kv, group, d), 1, 3)  # [B,Hkv,G,C,D]
    q4 = q4.reshape(b, h_kv, r, d)
    qpos = jnp.broadcast_to(
        q_positions.astype(jnp.int32)[:, None, :], (b, group, c)
    ).reshape(b, r)
    if r_pad != r:
        q4 = jnp.pad(q4, ((0, 0), (0, 0), (0, r_pad - r), (0, 0)))
        qpos = jnp.pad(qpos, ((0, 0), (0, r_pad - r)), constant_values=-1)

    in_specs = [
        pl.BlockSpec((1, 1, r_pad, d), lambda b, h, j, t: (b, h, 0, 0)),
        pl.BlockSpec((1, r_pad), lambda b, h, j, t: (b, 0)),
        # the fused gather: the block table entry IS the index map — the
        # pipeline DMAs pool block tables[b, j] (this narrow head's
        # slice) straight into VMEM, no gathered copy in HBM
        pl.BlockSpec((1, block_len, 1, d),
                     lambda b, h, j, t: (t[b, j], 0, h, 0)),
        pl.BlockSpec((1, block_len, 1, d),
                     lambda b, h, j, t: (t[b, j], 0, h, 0)),
    ]
    operands = [q4, qpos, k_pool, v_pool]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, block_len, 1),
                         lambda b, h, j, t: (t[b, j], 0, h)),
            pl.BlockSpec((1, block_len, 1),
                         lambda b, h, j, t: (t[b, j], 0, h)),
        ]
        operands += [k_scale, v_scale]
    out_dtype = q.dtype
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, h_kv, w),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, r_pad, d),
                               lambda b, h, j, t: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((r_pad, 128), jnp.float32),  # running row max m
            pltpu.VMEM((r_pad, 128), jnp.float32),  # running row sum l
            pltpu.VMEM((r_pad, d), jnp.float32),  # un-normalized output
        ],
    )
    kwargs = {}
    if not interpret:
        kwargs["compiler_params"] = _COMPILER_PARAMS(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        )
    out4 = pl.pallas_call(
        functools.partial(
            _paged_kernel, scale=scale, block_len=block_len,
            quantized=bool(quantized),
        ),
        out_shape=jax.ShapeDtypeStruct((b, h_kv, r_pad, d), out_dtype),
        grid_spec=grid_spec,
        interpret=interpret,
        **kwargs,
    )(block_tables.astype(jnp.int32), *operands)
    out4 = out4[:, :, :r]  # drop row padding
    return jnp.moveaxis(
        out4.reshape(b, h_kv, group, c, d), 3, 1
    ).reshape(b, c, h, d)
