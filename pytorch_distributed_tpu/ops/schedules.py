"""Learning-rate schedules as pure functions of the global step.

Replaces ``torch.optim.lr_scheduler.StepLR(optimizer, step_size=30,
gamma=0.1)`` (``resnet_single_gpu.py:109``, ``restnet_ddp.py:123``). The
torch scheduler is stateful (``scheduler.step()`` per epoch,
``state_dict`` checkpointed); here the schedule is a pure function of the
step counter, so checkpointing the step *is* checkpointing the scheduler —
one less thing to restore (ref resume path ``restnet_ddp.py:127-132``).
"""

from __future__ import annotations

import jax.numpy as jnp


def step_lr(
    base_lr: float,
    steps_per_epoch: int,
    step_size_epochs: int = 30,
    gamma: float = 0.1,
):
    """lr = base * gamma ** (epoch // step_size_epochs), epoch derived from step."""

    def schedule(step):
        epoch = jnp.asarray(step, jnp.float32) // float(max(steps_per_epoch, 1))  # jaxlint: disable=precision-cast -- LR math on the step counter is fp32 scalar arithmetic
        exponent = jnp.floor(epoch / float(step_size_epochs))
        return base_lr * jnp.power(gamma, exponent)

    return schedule


def warmup_cosine(
    base_lr: float,
    total_steps: int,
    warmup_steps: int = 0,
    final_lr: float = 0.0,
):
    """Linear warmup then cosine decay — the modern large-batch recipe the
    reference lacks; provided because TPU pods favor bigger global batches
    than bs-400-per-replica SGD+StepLR was tuned for."""

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)  # jaxlint: disable=precision-cast -- LR math on the step counter is fp32 scalar arithmetic
        warm = base_lr * step / jnp.maximum(float(warmup_steps), 1.0)
        progress = (step - warmup_steps) / jnp.maximum(
            float(total_steps - warmup_steps), 1.0
        )
        progress = jnp.clip(progress, 0.0, 1.0)
        cos = final_lr + 0.5 * (base_lr - final_lr) * (1 + jnp.cos(jnp.pi * progress))
        return jnp.where(step < warmup_steps, warm, cos)

    return schedule
