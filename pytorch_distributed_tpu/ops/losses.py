"""Classification losses.

Replaces ``torch.nn.CrossEntropyLoss()`` as used by every reference recipe
(``resnet_single_gpu.py:107``, ``restnet_ddp.py:121``): softmax
cross-entropy over integer labels with mean reduction. Computed via
``log_softmax`` in fp32 so it is safe directly on bf16-produced logits; XLA
fuses the whole thing into the surrounding step program.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def cross_entropy_loss(
    logits: jax.Array,
    labels: jax.Array,
    label_smoothing: float = 0.0,
    reduction: str = "mean",
) -> jax.Array:
    """Softmax cross-entropy with integer class labels.

    Args:
      logits: [batch, num_classes] unnormalized scores.
      labels: [batch] int class indices.
      label_smoothing: optional epsilon-smoothing (0.0 matches the reference).
      reduction: 'mean' | 'sum' | 'none'.
    """
    logits = logits.astype(jnp.float32)  # jaxlint: disable=precision-cast -- CE softmax always fp32 (the Policy.output_dtype contract)
    num_classes = logits.shape[-1]
    log_probs = jax.nn.log_softmax(logits, axis=-1)
    if label_smoothing > 0.0:
        # torch convention: target = (1-eps) * one_hot + eps/K uniform.
        off = label_smoothing / num_classes
        targets = jax.nn.one_hot(labels, num_classes) * (1.0 - label_smoothing) + off
        per_example = -jnp.sum(targets * log_probs, axis=-1)
    else:
        per_example = -jnp.take_along_axis(
            log_probs, labels[:, None].astype(jnp.int32), axis=-1
        )[:, 0]
    if reduction == "mean":
        return jnp.mean(per_example)
    if reduction == "sum":
        return jnp.sum(per_example)
    if reduction == "none":
        return per_example
    raise ValueError(f"unknown reduction {reduction!r}")
