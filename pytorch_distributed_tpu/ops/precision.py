"""Mixed-precision policy and loss scaling.

Replaces the reference's AMP stack (``torch.cuda.amp.autocast`` +
``GradScaler``, ``resnet_ddp_apex.py:27-33,107``) with the TPU-native
design:

- ``Policy``: params in fp32, compute (convs/matmuls/activations) in bf16.
  TPU bf16 keeps fp32's exponent range, so gradients cannot underflow the
  way fp16 ones do on GPU — **no loss scaler is needed** on the default
  path. The MXU natively consumes bf16, so this is also the fast path.
- ``DynamicLossScaler``: a real, working implementation of torch
  ``GradScaler``'s algorithm (scale loss → unscale grads → skip step on
  non-finite → grow/shrink scale) for the rare fp16 / debugging use case and
  for capability parity. ``NoOpLossScaler`` is the default bf16 policy
  object: same API, compiles away to nothing.

Both scalers are immutable pytrees whose ``update`` runs inside the jitted
step — no host round-trip per step (the torch scaler syncs the inf-check to
host; here ``lax.cond``-free ``jnp.where`` keeps the program static).
"""

from __future__ import annotations

from typing import Any

import flax.struct
import jax
import jax.numpy as jnp


@flax.struct.dataclass
class Policy:
    """What dtype each tensor class lives in.

    ``param_dtype``: master weights (fp32). ``compute_dtype``: forward/
    backward math (bf16 on TPU for AMP parity, fp32 for the baseline
    recipes). ``output_dtype``: logits/loss (fp32 always).
    """

    param_dtype: Any = flax.struct.field(pytree_node=False, default=jnp.float32)
    compute_dtype: Any = flax.struct.field(pytree_node=False, default=jnp.float32)
    output_dtype: Any = flax.struct.field(pytree_node=False, default=jnp.float32)

    def cast_to_compute(self, tree):
        return jax.tree.map(
            lambda x: x.astype(self.compute_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating)
            else x,
            tree,
        )

    def cast_to_param(self, tree):
        return jax.tree.map(
            lambda x: x.astype(self.param_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating)
            else x,
            tree,
        )


def fp32_policy() -> Policy:
    """Baseline fp32 (ref ``resnet_single_gpu.py`` / ``restnet_ddp.py``)."""
    return Policy()


def bf16_policy() -> Policy:
    """TPU mixed precision (ref AMP recipe ``resnet_ddp_apex.py``)."""
    return Policy(compute_dtype=jnp.bfloat16)


def all_finite(tree) -> jax.Array:
    """True iff every float leaf is finite (ref: the GradScaler inf-check
    kernel ``_amp_foreach_non_finite_check_and_unscale_``)."""
    leaves = [
        jnp.all(jnp.isfinite(x))
        for x in jax.tree.leaves(tree)
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
    ]
    if not leaves:
        return jnp.asarray(True)
    return jnp.stack(leaves).all()


@flax.struct.dataclass
class DynamicLossScaler:
    """torch.cuda.amp.GradScaler's algorithm as an immutable pytree.

    scale(loss) → backward → unscale(grads) → ``update(grads_finite)``:
    on non-finite grads halve the scale and signal the caller to skip the
    parameter update (ref ``loss_scaler.step/update``,
    ``resnet_ddp_apex.py:30-33``); after ``growth_interval`` consecutive
    finite steps, double it.
    """

    scale: jax.Array
    growth_tracker: jax.Array
    growth_factor: float = flax.struct.field(pytree_node=False, default=2.0)
    backoff_factor: float = flax.struct.field(pytree_node=False, default=0.5)
    growth_interval: int = flax.struct.field(pytree_node=False, default=2000)

    @classmethod
    def create(cls, init_scale: float = 2.0**16, **kwargs) -> "DynamicLossScaler":
        return cls(
            scale=jnp.asarray(init_scale, jnp.float32),
            growth_tracker=jnp.zeros((), jnp.int32),
            **kwargs,
        )

    def scale_loss(self, loss: jax.Array) -> jax.Array:
        return loss * self.scale.astype(loss.dtype)

    def unscale_grads(self, grads):
        inv = 1.0 / self.scale
        return jax.tree.map(lambda g: g * inv.astype(g.dtype), grads)

    def update(self, grads_finite: jax.Array) -> "DynamicLossScaler":
        grew = self.growth_tracker + 1 >= self.growth_interval
        new_scale = jnp.where(
            grads_finite,
            jnp.where(grew, self.scale * self.growth_factor, self.scale),
            self.scale * self.backoff_factor,
        )
        new_tracker = jnp.where(
            grads_finite, jnp.where(grew, 0, self.growth_tracker + 1), 0
        )
        return self.replace(scale=new_scale, growth_tracker=new_tracker)


@flax.struct.dataclass
class NoOpLossScaler:
    """bf16 default: same API as DynamicLossScaler, compiles to nothing.

    TPU bf16 has an fp32-range exponent, so there is no underflow for a
    scaler to fix — this object exists for API parity with the reference's
    AMP recipe only.
    """

    @classmethod
    def create(cls) -> "NoOpLossScaler":
        return cls()

    @property
    def scale(self) -> jax.Array:
        return jnp.ones((), jnp.float32)

    def scale_loss(self, loss):
        return loss

    def unscale_grads(self, grads):
        return grads

    def update(self, grads_finite):
        del grads_finite
        return self
