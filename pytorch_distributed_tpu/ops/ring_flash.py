"""Ring attention at flash speed: sequence parallelism over the Pallas
kernels.

``parallel.sequence.ring_attention`` folds visiting KV shards with the XLA
online-softmax block (exact, but ~2.6x slower end-to-end than the Pallas
kernels at long L — BENCH_LM.md). This module runs the SAME ring schedule
with the flash kernels doing the per-shard work, made exact by a
ring-level ``jax.custom_vjp``:

Forward (one ring pass):
  each visiting shard is processed by the flash FORWARD kernel, which
  returns its block output and row logsumexp; blocks merge by the standard
  LSE combine ((m, l, acc) running state — mathematically the same
  recurrence the kernel runs internally, applied shard-wise). Causal runs
  use the contiguous-shard structure: a shard from a later ring position is
  fully masked (skipped — no FLOPs), an earlier one is fully visible
  (non-causal kernel), the diagonal runs the causal kernel.

Backward (a second ring pass; this is why the custom_vjp exists — the
merge weights depend on the per-shard LSEs, and differentiating through
them naively would need an lse-cotangent rule the kernel doesn't define):
  with the FINAL output O and GLOBAL row LSE saved as residuals, the
  FlashAttention-2 decomposition applies per KV shard independently:
  Δ = rowsum(dO ⊙ O) once, then each visiting shard's (dQ-contribution,
  dK, dV) comes from the flash BACKWARD kernels with the global LSE. dQ
  accumulates locally; dK/dV accumulators TRAVEL WITH their shard around
  the ring, so after a full circle every shard's gradients are complete
  and home (one collective permutation per step, same overlap story as
  the forward).

Exactness: values match ``ring_attention``/dense to fp accumulation order;
gradients match dense attention's (tests/test_ring_flash.py, values and
all three grads). Requires equal-length shards with L_local a multiple of
the block sizes (the LM's standard configuration); anything else should
use ``ring_attention``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from pytorch_distributed_tpu.ops.attention import NEG_INF
from pytorch_distributed_tpu.ops.flash_attention import (
    _flash_bwd,
    _flash_fwd,
    _from3,
    _to3,
    compute_delta,
)
from pytorch_distributed_tpu.parallel.mesh import SEQ_AXIS


def _shard_fwd(q3, k3, v3, scale, causal_block, block_q, block_k, interpret):
    """Flash forward on one visiting shard → (o3, lse [BH, L, 1])."""
    o3, lse3 = _flash_fwd(
        q3, k3, v3, scale, causal_block, block_q, block_k, k3.shape[1],
        interpret,
    )
    return o3, lse3[:, :, :1]


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8)
)
def _ring_flash(q, k, v, axis, causal, scale, block_q, block_k, interpret):
    out, _ = _ring_flash_fwd(
        q, k, v, axis, causal, scale, block_q, block_k, interpret
    )
    return out


def _ring_flash_fwd(q, k, v, axis, causal, scale, block_q, block_k, interpret):
    s = jax.lax.psum(1, axis)
    my = jax.lax.axis_index(axis)
    b, lq, h, d = q.shape
    q3, k3, v3 = _to3(q), _to3(k), _to3(v)
    bh = q3.shape[0]
    perm = [(i, (i + 1) % s) for i in range(s)]

    def fold(carry_state, k_cur, v_cur, step):
        m, l, acc = carry_state
        src = jax.lax.rem(my - step + s, s)

        def merge(o3, lse):
            m_new = jnp.maximum(m, lse)
            corr = jnp.exp(m - m_new)
            w = jnp.exp(lse - m_new)
            return (
                m_new,
                l * corr + w,
                acc * corr + o3.astype(jnp.float32) * w,
            )

        def diag(_):
            return merge(*_shard_fwd(q3, k_cur, v_cur, scale, True,
                                     block_q, block_k, interpret))

        def full(_):
            return merge(*_shard_fwd(q3, k_cur, v_cur, scale, False,
                                     block_q, block_k, interpret))

        def skip(_):
            return (m, l, acc)

        if not causal:
            return full(None)
        # contiguous equal shards: src>my fully masked, src<my fully
        # visible, src==my the causal diagonal
        return jax.lax.cond(
            src > my, skip,
            lambda x: jax.lax.cond(src == my, diag, full, x),
            None,
        )

    def body(carry, step):
        state, (k_cur, v_cur) = carry
        k_nxt, v_nxt = jax.lax.ppermute((k_cur, v_cur), axis, perm)
        state = fold(state, k_cur, v_cur, step)
        return (state, (k_nxt, v_nxt)), None

    init_state = (
        jnp.full((bh, lq, 1), NEG_INF, jnp.float32),
        jnp.zeros((bh, lq, 1), jnp.float32),
        jnp.zeros((bh, lq, d), jnp.float32),
    )
    if s > 1:
        (state, (k_last, v_last)), _ = jax.lax.scan(
            body, (init_state, (k3, v3)), jnp.arange(s - 1)
        )
    else:
        state, (k_last, v_last) = init_state, (k3, v3)
    m, l, acc = fold(state, k_last, v_last, s - 1)

    l_safe = jnp.maximum(l, 1e-37)
    o3 = (acc / l_safe).astype(q.dtype)
    lse = jnp.where(l > 0.0, m + jnp.log(l_safe), NEG_INF)  # [BH, L, 1]
    return _from3(o3, b, h), (q, k, v, o3, lse)


def _ring_flash_bwd(axis, causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, o3, lse = res
    b, lq, h, d = q.shape
    s = jax.lax.psum(1, axis)
    my = jax.lax.axis_index(axis)
    q3, k3, v3, do3 = _to3(q), _to3(k), _to3(v), _to3(g.astype(q.dtype))
    bh = q3.shape[0]
    lse3 = jnp.broadcast_to(lse, (bh, lq, 128))
    delta3 = compute_delta(do3, o3)  # shard-invariant: once, not per step
    perm = [(i, (i + 1) % s) for i in range(s)]

    def shard_bwd(k_cur, v_cur, causal_block):
        return _flash_bwd(
            q3, k_cur, v_cur, o3, lse3, do3, scale, causal_block,
            block_q, block_k, k_cur.shape[1], interpret, delta3=delta3,
        )

    def fold(dq_acc, dk_cur, dv_cur, k_cur, v_cur, step):
        src = jax.lax.rem(my - step + s, s)

        def run(causal_block, _):
            dq3, dk3, dv3 = shard_bwd(k_cur, v_cur, causal_block)
            return (
                dq_acc + dq3.astype(jnp.float32),
                dk_cur + dk3.astype(jnp.float32),
                dv_cur + dv3.astype(jnp.float32),
            )

        if not causal:
            return run(False, None)
        return jax.lax.cond(
            src > my,
            lambda _: (dq_acc, dk_cur, dv_cur),  # fully masked: no grads
            lambda x: jax.lax.cond(
                src == my, functools.partial(run, True),
                functools.partial(run, False), x,
            ),
            None,
        )

    def body(carry, step):
        dq_acc, (k_cur, v_cur, dk_cur, dv_cur) = carry
        # k/v rotate from their pre-fold values (the fold consumes k_cur);
        # the gradient accumulators rotate AFTER the fold so each shard's
        # dk/dv travels with it carrying this device's contribution
        k_nxt, v_nxt = jax.lax.ppermute((k_cur, v_cur), axis, perm)
        dq_acc, dk_new, dv_new = fold(dq_acc, dk_cur, dv_cur, k_cur, v_cur,
                                      step)
        dk_nxt, dv_nxt = jax.lax.ppermute((dk_new, dv_new), axis, perm)
        return (dq_acc, (k_nxt, v_nxt, dk_nxt, dv_nxt)), None

    zeros_kv = jnp.zeros((bh, k3.shape[1], d), jnp.float32)
    init = (jnp.zeros((bh, lq, d), jnp.float32), (k3, v3, zeros_kv, zeros_kv))
    if s > 1:
        (dq_acc, (k_last, v_last, dk_last, dv_last)), _ = jax.lax.scan(
            body, init, jnp.arange(s - 1)
        )
    else:
        dq_acc, (k_last, v_last, dk_last, dv_last) = init
    # final fold (no trailing rotation needed after it...) — the shard held
    # now is the one that must end at THIS device: after s-1 rotations each
    # device holds the shard originated at (my+1) mod s; one more rotation
    # inside the last fold step would complete the circle. Fold first, then
    # rotate once so every accumulator lands on its owner.
    dq_acc, dk_new, dv_new = fold(dq_acc, dk_last, dv_last, k_last, v_last,
                                  s - 1)
    dk_home, dv_home = jax.lax.ppermute((dk_new, dv_new), axis, perm)

    return (
        _from3(dq_acc.astype(q.dtype), b, h),
        _from3(dk_home.astype(k.dtype), b, h),
        _from3(dv_home.astype(v.dtype), b, h),
    )


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis: str = SEQ_AXIS,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 256,
    block_k: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """Ring attention with Pallas flash kernels per visiting shard (call
    under shard_map; same contract as ``parallel.sequence.ring_attention``:
    ``[B, L_local, H, D]`` shards of a contiguously-sharded sequence).

    Requires equal-length shards with L_local a multiple of the clamped
    block sizes; use ``ring_attention`` for anything irregular. Note
    ``base_offset`` is unsupported (the causal structure is derived from
    ring positions, which already encode absolute order).
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    lq, lk = q.shape[1], k.shape[1]
    if lq != lk:
        raise ValueError(
            f"ring flash needs equal Q/KV shard lengths, got {lq} vs {lk}"
        )
    block_q = min(block_q, lq)
    block_k = min(block_k, lk)
    if lq % block_q or lk % block_k:
        raise ValueError(
            f"shard length {lq} must be a multiple of the block sizes "
            f"({block_q}, {block_k}); pad the sequence or use ring_attention"
        )
    return _ring_flash(q, k, v, axis, causal, scale, block_q, block_k,
                       interpret)
