"""Ring attention at flash speed: sequence parallelism over the Pallas
kernels.

``parallel.sequence.ring_attention`` folds visiting KV shards with the XLA
online-softmax block (exact, but ~2.6x slower end-to-end than the Pallas
kernels at long L — BENCH_LM.md). This module runs the SAME ring schedule
with the flash kernels doing the per-shard work, made exact by a
ring-level ``jax.custom_vjp``:

Forward (one ring pass):
  each visiting shard is processed by the flash FORWARD kernel, which
  returns its block output and row logsumexp; blocks merge by the standard
  LSE combine ((m, l, acc) running state — mathematically the same
  recurrence the kernel runs internally, applied shard-wise). Causal runs
  use the contiguous-shard structure: a shard from a later ring position is
  fully masked (skipped — no FLOPs), an earlier one is fully visible
  (non-causal kernel), the diagonal runs the causal kernel.

Backward (a second ring pass; this is why the custom_vjp exists — the
merge weights depend on the per-shard LSEs, and differentiating through
them naively would need an lse-cotangent rule the kernel doesn't define):
  with the FINAL output O and GLOBAL row LSE saved as residuals, the
  FlashAttention-2 decomposition applies per KV shard independently:
  Δ = rowsum(dO ⊙ O) once, then each visiting shard's (dQ-contribution,
  dK, dV) comes from the flash BACKWARD kernels with the global LSE. dQ
  accumulates locally; dK/dV accumulators TRAVEL WITH their shard around
  the ring, so after a full circle every shard's gradients are complete
  and home (one collective permutation per step, same overlap story as
  the forward).

The whole file accumulates in fp32 by construction — the ring merge state
(m, l, acc) and the travelling dq/dk/dv accumulators exist to keep bf16
block results exact across shards; every ``.astype(jnp.float32)`` here IS
the numerics contract, not a policy override (burned down from the lint
baseline into the file-level suppression below, PR 9).

Exactness: values match ``ring_attention``/dense to fp accumulation order;
gradients match dense attention's (tests/test_ring_flash.py, values and
all three grads). Requires equal-length shards with L_local a multiple of
the block sizes (the LM's standard configuration); anything else should
use ``ring_attention``.
"""

# jaxlint: disable-file=precision-cast -- ring kernel accumulators (o/dq/dk/dv, LSE merge state) are fp32 by construction; every cast merges bf16 block results into them

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from pytorch_distributed_tpu.ops.attention import NEG_INF
from pytorch_distributed_tpu.ops.flash_attention import (
    _flash_bwd,
    _flash_bwd_fused,
    _flash_fwd,
    _from3,
    _to3,
    compute_delta,
)
from pytorch_distributed_tpu.parallel.mesh import SEQ_AXIS


def _visit_bwd(q3, k_cur, v_cur, o3, lse3, do3, scale, causal_block,
               block_q, block_k, interpret, delta3, bwd_impl):
    """One visiting shard's (dQ-contribution, dK, dV) — the r5 fused
    single-pass kernel by default (5 big matmuls + one input pass per
    visit vs the split kernels' 7 and two; +20-29% measured standalone,
    BENCH_ATTENTION.md r5), the split pair via bwd_impl='split'."""
    if bwd_impl == "fused":
        return _flash_bwd_fused(
            q3, k_cur, v_cur, o3, lse3, do3, scale, causal_block,
            (block_q, block_k), k_cur.shape[1], interpret, delta3=delta3,
        )
    return _flash_bwd(
        q3, k_cur, v_cur, o3, lse3, do3, scale, causal_block,
        (block_q, block_k), (block_q, block_k), k_cur.shape[1],
        interpret, delta3=delta3,
    )


def _fit_block(requested: int, length: int) -> int:
    """Largest block <= requested that divides ``length`` (the ring path
    has no padding, so blocks must divide the shard exactly). Prefers
    128-multiples (lane alignment); falls back to any divisor, then to the
    shard itself — raising the tuned defaults must never make a
    previously-valid call fail."""
    cap = min(requested, length)
    if length % cap == 0:
        return cap
    for c in range(cap - cap % 128, 0, -128):
        if length % c == 0:
            return c
    for c in range(cap, 0, -1):
        if length % c == 0:
            return c
    return length


def _shard_fwd(q3, k3, v3, scale, causal_block, block_q, block_k, interpret):
    """Flash forward on one visiting shard → (o3, lse [BH, L, 1])."""
    o3, lse3 = _flash_fwd(
        q3, k3, v3, scale, causal_block, block_q, block_k, k3.shape[1],
        interpret,
    )
    return o3, lse3[:, :, :1]


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10)
)
def _ring_flash(q, k, v, axis, causal, scale, block_q, block_k, interpret,
                layout, bwd_impl):
    out, _ = _ring_flash_fwd(
        q, k, v, axis, causal, scale, block_q, block_k, interpret, layout,
        bwd_impl,
    )
    return out


def _ring_flash_fwd(q, k, v, axis, causal, scale, block_q, block_k, interpret,
                    layout, bwd_impl):
    if layout == "zigzag":
        return _ring_flash_zigzag_fwd(
            q, k, v, axis, scale, block_q, block_k, interpret
        )
    s = jax.lax.psum(1, axis)
    my = jax.lax.axis_index(axis)
    b, lq, h, d = q.shape
    q3, k3, v3 = _to3(q), _to3(k), _to3(v)
    bh = q3.shape[0]
    perm = [(i, (i + 1) % s) for i in range(s)]

    def fold(carry_state, k_cur, v_cur, step):
        m, l, acc = carry_state
        src = jax.lax.rem(my - step + s, s)

        def merge(o3, lse):
            m_new = jnp.maximum(m, lse)
            corr = jnp.exp(m - m_new)
            w = jnp.exp(lse - m_new)
            return (
                m_new,
                l * corr + w,
                acc * corr + o3.astype(jnp.float32) * w,
            )

        def diag(_):
            return merge(*_shard_fwd(q3, k_cur, v_cur, scale, True,
                                     block_q, block_k, interpret))

        def full(_):
            return merge(*_shard_fwd(q3, k_cur, v_cur, scale, False,
                                     block_q, block_k, interpret))

        def skip(_):
            return (m, l, acc)

        if not causal:
            return full(None)
        # contiguous equal shards: src>my fully masked, src<my fully
        # visible, src==my the causal diagonal
        return jax.lax.cond(
            src > my, skip,
            lambda x: jax.lax.cond(src == my, diag, full, x),
            None,
        )

    def body(carry, step):
        state, (k_cur, v_cur) = carry
        k_nxt, v_nxt = jax.lax.ppermute((k_cur, v_cur), axis, perm)
        state = fold(state, k_cur, v_cur, step)
        return (state, (k_nxt, v_nxt)), None

    init_state = (
        jnp.full((bh, lq, 1), NEG_INF, jnp.float32),
        jnp.zeros((bh, lq, 1), jnp.float32),
        jnp.zeros((bh, lq, d), jnp.float32),
    )
    if s > 1:
        (state, (k_last, v_last)), _ = jax.lax.scan(
            body, (init_state, (k3, v3)), jnp.arange(s - 1)
        )
    else:
        state, (k_last, v_last) = init_state, (k3, v3)
    m, l, acc = fold(state, k_last, v_last, s - 1)

    l_safe = jnp.maximum(l, 1e-37)
    o3 = (acc / l_safe).astype(q.dtype)
    lse = jnp.where(l > 0.0, m + jnp.log(l_safe), NEG_INF)  # [BH, L, 1]
    return _from3(o3, b, h), (q, k, v, o3, lse)


def _ring_flash_bwd(axis, causal, scale, block_q, block_k, interpret, layout,
                    bwd_impl, res, g):
    if layout == "zigzag":
        return _ring_flash_zigzag_bwd(
            axis, scale, block_q, block_k, interpret, res, g, bwd_impl
        )
    q, k, v, o3, lse = res
    b, lq, h, d = q.shape
    s = jax.lax.psum(1, axis)
    my = jax.lax.axis_index(axis)
    q3, k3, v3, do3 = _to3(q), _to3(k), _to3(v), _to3(g.astype(q.dtype))
    bh = q3.shape[0]
    lse3 = jnp.broadcast_to(lse, (bh, lq, 128))
    delta3 = compute_delta(do3, o3)  # shard-invariant: once, not per step
    perm = [(i, (i + 1) % s) for i in range(s)]

    def shard_bwd(k_cur, v_cur, causal_block):
        return _visit_bwd(
            q3, k_cur, v_cur, o3, lse3, do3, scale, causal_block,
            block_q, block_k, interpret, delta3, bwd_impl,
        )

    def fold(dq_acc, dk_cur, dv_cur, k_cur, v_cur, step):
        src = jax.lax.rem(my - step + s, s)

        def run(causal_block, _):
            dq3, dk3, dv3 = shard_bwd(k_cur, v_cur, causal_block)
            return (
                dq_acc + dq3.astype(jnp.float32),
                dk_cur + dk3.astype(jnp.float32),
                dv_cur + dv3.astype(jnp.float32),
            )

        if not causal:
            return run(False, None)
        return jax.lax.cond(
            src > my,
            lambda _: (dq_acc, dk_cur, dv_cur),  # fully masked: no grads
            lambda x: jax.lax.cond(
                src == my, functools.partial(run, True),
                functools.partial(run, False), x,
            ),
            None,
        )

    def body(carry, step):
        dq_acc, (k_cur, v_cur, dk_cur, dv_cur) = carry
        # k/v rotate from their pre-fold values (the fold consumes k_cur);
        # the gradient accumulators rotate AFTER the fold so each shard's
        # dk/dv travels with it carrying this device's contribution
        k_nxt, v_nxt = jax.lax.ppermute((k_cur, v_cur), axis, perm)
        dq_acc, dk_new, dv_new = fold(dq_acc, dk_cur, dv_cur, k_cur, v_cur,
                                      step)
        dk_nxt, dv_nxt = jax.lax.ppermute((dk_new, dv_new), axis, perm)
        return (dq_acc, (k_nxt, v_nxt, dk_nxt, dv_nxt)), None

    zeros_kv = jnp.zeros((bh, k3.shape[1], d), jnp.float32)
    init = (jnp.zeros((bh, lq, d), jnp.float32), (k3, v3, zeros_kv, zeros_kv))
    if s > 1:
        (dq_acc, (k_last, v_last, dk_last, dv_last)), _ = jax.lax.scan(
            body, init, jnp.arange(s - 1)
        )
    else:
        dq_acc, (k_last, v_last, dk_last, dv_last) = init
    # final fold (no trailing rotation needed after it...) — the shard held
    # now is the one that must end at THIS device: after s-1 rotations each
    # device holds the shard originated at (my+1) mod s; one more rotation
    # inside the last fold step would complete the circle. Fold first, then
    # rotate once so every accumulator lands on its owner.
    dq_acc, dk_new, dv_new = fold(dq_acc, dk_last, dv_last, k_last, v_last,
                                  s - 1)
    dk_home, dv_home = jax.lax.ppermute((dk_new, dv_new), axis, perm)

    return (
        _from3(dq_acc.astype(q.dtype), b, h),
        _from3(dk_home.astype(k.dtype), b, h),
        _from3(dv_home.astype(v.dtype), b, h),
    )


def _ring_flash_zigzag_fwd(q, k, v, axis, scale, block_q, block_k, interpret):
    """Causal forward on the zigzag layout: rank r holds chunks
    (r, 2s-1-r); of the four (q-chunk, kv-chunk) pairs per visiting shard
    one is always visible, one never (omitted), and the two chunk-diagonal
    pairs carry runtime conds — every rank runs ~2 chunk kernels per step
    (the balance argument: parallel/sequence.py `_ring_attention_zigzag`)."""
    s = jax.lax.psum(1, axis)
    my = jax.lax.axis_index(axis)
    b, lq, h, d = q.shape
    c = lq // 2
    q3, k3, v3 = _to3(q), _to3(k), _to3(v)
    bh = q3.shape[0]
    q_lo, q_hi = q3[:, :c], q3[:, c:]
    perm = [(i, (i + 1) % s) for i in range(s)]

    def merge(state, o3, lse):
        m, l, acc = state
        m_new = jnp.maximum(m, lse)
        corr = jnp.exp(m - m_new)
        w = jnp.exp(lse - m_new)
        return (m_new, l * corr + w,
                acc * corr + o3.astype(jnp.float32) * w)

    def pair(state, qc, kc, vc, causal_block):
        return merge(state, *_shard_fwd(qc, kc, vc, scale, causal_block,
                                        block_q, block_k, interpret))

    def fold(states, k_cur, v_cur, step):
        st_lo, st_hi = states
        src = jax.lax.rem(my - step + s, s)
        k_lo, k_hi = k_cur[:, :c], k_cur[:, c:]
        v_lo, v_hi = v_cur[:, :c], v_cur[:, c:]
        # (q_lo, kv_lo): diag at src==my, full at src<my, masked after
        st_lo = jax.lax.cond(
            src > my, lambda st: st,
            lambda st: jax.lax.cond(
                src == my,
                lambda st2: pair(st2, q_lo, k_lo, v_lo, True),
                lambda st2: pair(st2, q_lo, k_lo, v_lo, False),
                st,
            ),
            st_lo,
        )
        # (q_hi, kv_lo): always fully visible
        st_hi = pair(st_hi, q_hi, k_lo, v_lo, False)
        # (q_hi, kv_hi): diag at src==my, full at src>my, masked before
        st_hi = jax.lax.cond(
            src < my, lambda st: st,
            lambda st: jax.lax.cond(
                src == my,
                lambda st2: pair(st2, q_hi, k_hi, v_hi, True),
                lambda st2: pair(st2, q_hi, k_hi, v_hi, False),
                st,
            ),
            st_hi,
        )
        return (st_lo, st_hi)

    def body(carry, step):
        states, (k_cur, v_cur) = carry
        k_nxt, v_nxt = jax.lax.ppermute((k_cur, v_cur), axis, perm)
        states = fold(states, k_cur, v_cur, step)
        return (states, (k_nxt, v_nxt)), None

    def zero_state():
        return (
            jnp.full((bh, c, 1), NEG_INF, jnp.float32),
            jnp.zeros((bh, c, 1), jnp.float32),
            jnp.zeros((bh, c, d), jnp.float32),
        )

    init = ((zero_state(), zero_state()), (k3, v3))
    if s > 1:
        (states, (k_last, v_last)), _ = jax.lax.scan(
            body, init, jnp.arange(s - 1)
        )
    else:
        states, (k_last, v_last) = init
    st_lo, st_hi = fold(states, k_last, v_last, s - 1)

    def finalize(state):
        m, l, acc = state
        l_safe = jnp.maximum(l, 1e-37)
        o3 = (acc / l_safe).astype(q.dtype)
        lse = jnp.where(l > 0.0, m + jnp.log(l_safe), NEG_INF)
        return o3, lse

    o_lo, lse_lo = finalize(st_lo)
    o_hi, lse_hi = finalize(st_hi)
    o3 = jnp.concatenate([o_lo, o_hi], axis=1)
    lse = jnp.concatenate([lse_lo, lse_hi], axis=1)
    return _from3(o3, b, h), (q, k, v, o3, lse)


def _ring_flash_zigzag_bwd(axis, scale, block_q, block_k, interpret, res, g,
                           bwd_impl):
    """Zigzag backward: per-pair FlashAttention-2 kernels with the global
    LSE; dq accumulates per local q chunk, dk/dv accumulators travel with
    their shard (same traveling scheme as the contiguous backward) with
    per-chunk slice updates."""
    q, k, v, o3, lse = res
    b, lq, h, d = q.shape
    c = lq // 2
    s = jax.lax.psum(1, axis)
    my = jax.lax.axis_index(axis)
    q3, k3, v3, do3 = _to3(q), _to3(k), _to3(v), _to3(g.astype(q.dtype))
    bh = q3.shape[0]
    lse3 = jnp.broadcast_to(lse, (bh, lq, 128))
    delta3 = compute_delta(do3, o3)
    perm = [(i, (i + 1) % s) for i in range(s)]

    chunks = {
        "lo": (q3[:, :c], o3[:, :c], lse3[:, :c], do3[:, :c], delta3[:, :c]),
        "hi": (q3[:, c:], o3[:, c:], lse3[:, c:], do3[:, c:], delta3[:, c:]),
    }

    def pair_bwd(which, kc, vc, causal_block):
        qc, oc, lsec, doc, dc = chunks[which]
        return _visit_bwd(
            qc, kc, vc, oc, lsec, doc, scale, causal_block,
            block_q, block_k, interpret, dc, bwd_impl,
        )

    def fold(dq_acc, dkv_cur, k_cur, v_cur, step):
        src = jax.lax.rem(my - step + s, s)
        k_lo, k_hi = k_cur[:, :c], k_cur[:, c:]
        v_lo, v_hi = v_cur[:, :c], v_cur[:, c:]
        dq_lo, dq_hi = dq_acc
        dk_cur, dv_cur = dkv_cur

        def add_lo(dk, dkc):
            return dk.at[:, :c].add(dkc.astype(jnp.float32))

        def add_hi(dk, dkc):
            return dk.at[:, c:].add(dkc.astype(jnp.float32))

        # (q_lo, kv_lo)
        def run_ll(args, causal_block):
            dq_lo, dk_cur, dv_cur = args
            dq3, dk3, dv3 = pair_bwd("lo", k_lo, v_lo, causal_block)
            return (dq_lo + dq3.astype(jnp.float32), add_lo(dk_cur, dk3),
                    add_lo(dv_cur, dv3))

        dq_lo, dk_cur, dv_cur = jax.lax.cond(
            src > my, lambda a: a,
            lambda a: jax.lax.cond(
                src == my, functools.partial(run_ll, causal_block=True),
                functools.partial(run_ll, causal_block=False), a,
            ),
            (dq_lo, dk_cur, dv_cur),
        )
        # (q_hi, kv_lo): always runs
        dq3, dk3, dv3 = pair_bwd("hi", k_lo, v_lo, False)
        dq_hi = dq_hi + dq3.astype(jnp.float32)
        dk_cur, dv_cur = add_lo(dk_cur, dk3), add_lo(dv_cur, dv3)

        # (q_hi, kv_hi)
        def run_hh(args, causal_block):
            dq_hi, dk_cur, dv_cur = args
            dq3, dk3, dv3 = pair_bwd("hi", k_hi, v_hi, causal_block)
            return (dq_hi + dq3.astype(jnp.float32), add_hi(dk_cur, dk3),
                    add_hi(dv_cur, dv3))

        dq_hi, dk_cur, dv_cur = jax.lax.cond(
            src < my, lambda a: a,
            lambda a: jax.lax.cond(
                src == my, functools.partial(run_hh, causal_block=True),
                functools.partial(run_hh, causal_block=False), a,
            ),
            (dq_hi, dk_cur, dv_cur),
        )
        return (dq_lo, dq_hi), (dk_cur, dv_cur)

    def body(carry, step):
        dq_acc, (k_cur, v_cur, dk_cur, dv_cur) = carry
        k_nxt, v_nxt = jax.lax.ppermute((k_cur, v_cur), axis, perm)
        dq_acc, (dk_new, dv_new) = fold(dq_acc, (dk_cur, dv_cur), k_cur,
                                        v_cur, step)
        dk_nxt, dv_nxt = jax.lax.ppermute((dk_new, dv_new), axis, perm)
        return (dq_acc, (k_nxt, v_nxt, dk_nxt, dv_nxt)), None

    zeros_kv = jnp.zeros((bh, lq, d), jnp.float32)
    init = (
        (jnp.zeros((bh, c, d), jnp.float32),
         jnp.zeros((bh, c, d), jnp.float32)),
        (k3, v3, zeros_kv, zeros_kv),
    )
    if s > 1:
        (dq_acc, (k_last, v_last, dk_last, dv_last)), _ = jax.lax.scan(
            body, init, jnp.arange(s - 1)
        )
    else:
        dq_acc, (k_last, v_last, dk_last, dv_last) = init
    dq_acc, (dk_new, dv_new) = fold(dq_acc, (dk_last, dv_last), k_last,
                                    v_last, s - 1)
    # one more rotation lands each accumulator on its shard's home rank
    dk_home, dv_home = jax.lax.ppermute((dk_new, dv_new), axis, perm)

    dq3 = jnp.concatenate(dq_acc, axis=1)
    return (
        _from3(dq3.astype(q.dtype), b, h),
        _from3(dk_home.astype(k.dtype), b, h),
        _from3(dv_home.astype(v.dtype), b, h),
    )


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis: str = SEQ_AXIS,
    causal: bool = False,
    scale: Optional[float] = None,
    # (1024, 1024): the r5 composed on-chip A/B through the ring path —
    # 90.1/106.8 TFLOP/s fwdbwd at L 4096/8192 vs 87.8/103.4 at the old
    # (512, 1024) (both with the fused per-visit backward; the split
    # kernels measured 84-95 on the same harness). _fit_block clamps for
    # small shards.
    block_q: int = 1024,
    block_k: int = 1024,
    interpret: bool | None = None,
    layout: str = "contiguous",
    bwd_impl: str = "fused",
) -> jax.Array:
    """Ring attention with Pallas flash kernels per visiting shard (call
    under shard_map; same contract as ``parallel.sequence.ring_attention``:
    ``[B, L_local, H, D]`` shards of a contiguously-sharded sequence, or —
    with ``layout="zigzag"`` — shards holding chunks (r, 2s-1-r) of the
    2s-chunk decomposition (``parallel.sequence.zigzag_shard``), which
    balances the causal critical path across ranks.

    Requires equal-length shards with L_local (each half-chunk, for
    zigzag) a multiple of the clamped block sizes; use ``ring_attention``
    for anything irregular. Note ``base_offset`` is unsupported (the
    causal structure is derived from ring positions, which already encode
    absolute order).
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"  # see flash_attention
    lq, lk = q.shape[1], k.shape[1]
    if lq != lk:
        raise ValueError(
            f"ring flash needs equal Q/KV shard lengths, got {lq} vs {lk}"
        )
    if layout not in ("contiguous", "zigzag"):
        raise ValueError(f"unknown layout {layout!r}")
    if layout == "zigzag":
        if not causal:
            raise ValueError(
                "zigzag layout only changes causal scheduling; use "
                "layout='contiguous' for non-causal attention"
            )
        if lq % 2:
            raise ValueError(f"zigzag needs an even shard length, got {lq}")
    if bwd_impl not in ("split", "fused"):
        raise ValueError(
            f"bwd_impl {bwd_impl!r} must be 'split' or 'fused'"
        )
    if layout == "zigzag":
        c = lq // 2
        block_q = _fit_block(block_q, c)
        block_k = _fit_block(block_k, c)
        return _ring_flash(q, k, v, axis, True, scale, block_q, block_k,
                           interpret, "zigzag", bwd_impl)
    block_q = _fit_block(block_q, lq)
    block_k = _fit_block(block_k, lk)
    return _ring_flash(q, k, v, axis, causal, scale, block_q, block_k,
                       interpret, "contiguous", bwd_impl)
