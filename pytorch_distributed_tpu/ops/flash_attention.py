"""FlashAttention forward AND backward as Pallas TPU kernels.

The blockwise kernel (``ops.attention.blockwise_attention``) is the XLA-fused
reference; this is the hand-tiled fast path for the same math, built per the
TPU Pallas playbook (/opt/skills/guides/pallas_guide.md):

Forward (``_fwd_kernel``):
- grid (B·H, Lq/block_q, Lk/block_k), KV innermost and sequential
  ("arbitrary" dimension semantics — it carries the online-softmax
  recurrence); Q/K/V blocks staged HBM→VMEM by BlockSpec index maps;
- the running (m, l, acc) state lives in VMEM scratch, persisting across the
  KV sweep for each Q block; everything accumulates in fp32 while inputs can
  be bf16 feeding the MXU (``preferred_element_type=f32``);
- causal masking skips fully-masked KV blocks with ``pl.when`` (no FLOPs
  spent above the diagonal) and applies a multiplicative mask so
  fully-masked rows yield zeros;
- alongside O it emits the row logsumexp (LSE), which is what makes the
  one-pass backward possible.

Backward (FlashAttention-2 decomposition, two kernels — round-2, replacing
the rematerialized blockwise VJP):
  with P = exp(S - LSE),  Δ_i = Σ_j P_ij (dO V^T)_ij = rowsum(dO ⊙ O):
    dV = P^T dO
    dS = P ⊙ (dO V^T − Δ)·scale
    dQ = dS K          (``_bwd_dq_kernel``: per-Q-block, sweeps KV)
    dK = dS^T Q        (``_bwd_dkv_kernel``: per-KV-block, sweeps Q)
  Δ is one fused XLA elementwise pass outside the kernels; no O(L²) tensor
  ever exists in HBM and nothing is rematerialized through the slow path.

Arbitrary lengths: inputs are zero-padded to block multiples and the
kernels mask padded KEY positions explicitly (padded query rows compute
garbage that is sliced away), so any (Lq, Lk) works — the round-1
multiple-of-block restriction is gone.

Shapes follow the framework convention ``[B, L, H, D]``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Pallas is a hard dependency of THIS module only: the ops package exports
# flash_attention lazily, so environments without pallas keep every other
# attention path working and fail loudly only when flash is actually chosen.

from pytorch_distributed_tpu.ops.attention import NEG_INF


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
    *, scale: float, causal: bool, block_q: int, block_k: int, kv_len: int,
):
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    qi = pl.program_id(1)
    q_start = qi * block_q
    k_start = ki * block_k

    def _block():
        # Fold the softmax scale into Q: one [block_q, D] multiply instead
        # of a [block_q, block_k] one on the logits.
        q = (q_ref[0] * jnp.asarray(scale, q_ref.dtype))  # [block_q, D]
        k = k_ref[0]  # [block_k, D]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [block_q, block_k]
        k_pos = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = k_pos < kv_len  # padded keys contribute nothing
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            mask = mask & (k_pos <= q_pos)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:, :1]  # [block_q, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)  # [block_q, block_k]
        p = p * mask  # fully-masked rows stay all-zero (l == 0 → out 0)
        corr = jnp.exp(m_prev - m_new)  # [block_q, 1]
        l_new = l_scr[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    if causal:
        # A KV block strictly above the diagonal contributes nothing — skip
        # its FLOPs entirely.
        pl.when(k_start <= q_start + block_q - 1)(_block)
    else:
        _block()

    @pl.when(ki == n_k - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:, :1], 1e-37)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        # LSE = m + log l; fully-masked rows get a huge negative (their
        # backward P = exp(s - lse) must still be ~0, not inf).
        lse = jnp.where(
            l_scr[:, :1] > 0.0, m_scr[:, :1] + jnp.log(l), NEG_INF
        )
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref[0].shape)


def _flash_fwd(q3, k3, v3, scale, causal, block_q, block_k, kv_len, interpret):
    """[BH, L, D] inputs → ([BH, Lq, D] out, [BH, Lq, 128] lse)."""
    bh, lq, d = q3.shape
    lk = k3.shape[1]
    grid = (bh, lq // block_q, lk // block_k)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, kv_len=kv_len,
    )
    kwargs = {}
    if not interpret:
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        )
    return pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct((bh, lq, d), q3.dtype),
            jax.ShapeDtypeStruct((bh, lq, 128), jnp.float32),
        ],
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, i, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # running row max m
            pltpu.VMEM((block_q, 128), jnp.float32),  # running row sum l
            pltpu.VMEM((block_q, d), jnp.float32),  # un-normalized output
        ],
        interpret=interpret,
        **kwargs,
    )(q3, k3, v3)


def _masked_p_ds(q, k, v, do, lse, delta, *, scale, causal,
                 q_start, k_start, block_q, block_k, kv_len):
    """The ONE masked-softmax-gradient block shared by every backward
    kernel: S = scale·QKᵀ (fp32 accum), the causal+padding mask,
    P = exp(S − LSE) via ``where`` (not ``*``) so a fully-masked row
    (LSE = −inf from the forward) yields 0, not inf·0 = NaN — defends
    offset/cross-attention callers the forward already defends — and
    dS = P ⊙ (dOVᵀ − Δ)·scale. Keeping it in one place means a masking
    or NaN-defense fix cannot diverge between bwd_impl='split' and
    'fused'."""
    sblk = jax.lax.dot_general(
        q * jnp.asarray(scale, q.dtype), k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [block_q, block_k]
    k_pos = k_start + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    mask = k_pos < kv_len
    if causal:
        q_pos = q_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        mask = mask & (k_pos <= q_pos)
    pblk = jnp.where(mask, jnp.exp(sblk - lse), 0.0)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    ds = pblk * (dp - delta) * jnp.asarray(scale, jnp.float32)
    return pblk, ds


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_scr,
    *, scale: float, causal: bool, block_q: int, block_k: int, kv_len: int,
):
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    q_start = pl.program_id(1) * block_q
    k_start = ki * block_k

    def _block():
        k = k_ref[0]
        _p, ds = _masked_p_ds(
            q_ref[0], k, v_ref[0], do_ref[0], lse_ref[0][:, :1],
            delta_ref[0][:, :1], scale=scale, causal=causal,
            q_start=q_start, k_start=k_start, block_q=block_q,
            block_k=block_k, kv_len=kv_len,
        )
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        pl.when(k_start <= q_start + block_q - 1)(_block)
    else:
        _block()

    @pl.when(ki == n_k - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_scr, dv_scr,
    *, scale: float, causal: bool, block_q: int, block_k: int, kv_len: int,
):
    qi = pl.program_id(2)
    n_q = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    k_start = pl.program_id(1) * block_k
    q_start = qi * block_q

    def _block():
        q = q_ref[0]
        do = do_ref[0]
        p, ds = _masked_p_ds(
            q, k_ref[0], v_ref[0], do, lse_ref[0][:, :1],
            delta_ref[0][:, :1], scale=scale, causal=causal,
            q_start=q_start, k_start=k_start, block_q=block_q,
            block_k=block_k, kv_len=kv_len,
        )
        # dV += P^T dO
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # dK += dS^T Q
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        # Q blocks entirely ABOVE the diagonal see this KV block masked out.
        pl.when(q_start + block_q - 1 >= k_start)(_block)
    else:
        _block()

    @pl.when(qi == n_q - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_fused_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    dqp_ref, dk_ref, dv_ref, dk_scr, dv_scr,
    *, scale: float, causal: bool, block_q: int, block_k: int, kv_len: int,
):
    """Single-pass backward (round 5, the r4-named kernel-family exit):
    grid (BH, KV, Q) with Q innermost. Computes S and dP ONCE per
    (q, kv) block and feeds all three products — where the split
    kernels spend 7 big matmuls (dQ pass: S, dP, dQ; dKV pass: S, dV,
    dP, dK) and read Q/K/V/dO twice, this spends the mathematical
    minimum 5 and reads once. dK/dV accumulate in VMEM across the
    inner Q sweep; dQ's cross-KV accumulation cannot live in VMEM in
    this grid order (non-consecutive revisits), so each (kv, q) step
    emits a PARTIAL dQ block to HBM (input dtype — see
    ``_flash_bwd_fused``) and one XLA reduction over the KV axis
    finishes it outside (traffic ≈ n_k · |dQ|, measured against the
    saved matmuls in BENCH_ATTENTION.md r5)."""
    qi = pl.program_id(2)
    n_q = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    k_start = pl.program_id(1) * block_k
    q_start = qi * block_q

    def _block():
        q = q_ref[0]
        k = k_ref[0]
        do = do_ref[0]
        p, ds = _masked_p_ds(
            q, k, v_ref[0], do, lse_ref[0][:, :1], delta_ref[0][:, :1],
            scale=scale, causal=causal, q_start=q_start, k_start=k_start,
            block_q=block_q, block_k=block_k, kv_len=kv_len,
        )
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dsc = ds.astype(q.dtype)
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            dsc, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dqp_ref[0, 0] = jax.lax.dot_general(
            dsc, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(dqp_ref.dtype)

    if causal:
        # fully-above-diagonal (q, kv) blocks contribute nothing — but
        # their dq partial block must still be ZEROED (the out buffer is
        # otherwise uninitialized memory)
        @pl.when(q_start + block_q - 1 < k_start)
        def _skip():
            dqp_ref[0, 0] = jnp.zeros_like(dqp_ref[0, 0])

        pl.when(q_start + block_q - 1 >= k_start)(_block)
    else:
        _block()

    @pl.when(qi == n_q - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_bwd_fused(q3, k3, v3, o3, lse3, do3, scale, causal, blocks,
                     kv_len, interpret, delta3=None, partials_f32=False):
    """One fused kernel + one XLA reduction. ``blocks`` = (block_q,
    block_k) shared by the whole pass."""
    bh, lq, d = q3.shape
    lk = k3.shape[1]
    if delta3 is None:
        delta3 = compute_delta(do3, o3)
    bq, bk = blocks
    n_k = lk // bk
    # dQ partials at the INPUT dtype (default): halves the partial HBM
    # traffic. The same-process A/B (BENCH_ATTENTION.md r5) measured
    # input-dtype partials faster at BOTH 4096 and 8192 (108.6/113.9 vs
    # 104.6/107.4 TFLOP/s) — an earlier cross-run reading that suggested
    # fp32 wins at 4096 was tunnel weather. The cross-partial sum always
    # accumulates in fp32; ``partials_f32`` remains as a sweep/precision
    # knob (each bf16 partial rounds before the sum).
    p_dtype = jnp.float32 if partials_f32 else q3.dtype
    kwargs = {}
    if not interpret:
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        )
    q_spec = pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0))
    row_spec = pl.BlockSpec((1, bq, 128), lambda b, j, i: (b, i, 0))
    kv_spec = pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0))
    dqp3, dk3, dv3 = pl.pallas_call(
        functools.partial(_bwd_fused_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, kv_len=kv_len),
        out_shape=[
            jax.ShapeDtypeStruct((bh, n_k, lq, d), p_dtype),
            jax.ShapeDtypeStruct((bh, lk, d), k3.dtype),
            jax.ShapeDtypeStruct((bh, lk, d), v3.dtype),
        ],
        grid=(bh, n_k, lq // bq),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec],
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b, j, i: (b, j, i, 0)),
            kv_spec,
            kv_spec,
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=interpret,
        **kwargs,
    )(q3, k3, v3, do3, lse3, delta3)
    dq3 = jnp.sum(dqp3.astype(jnp.float32), axis=1).astype(q3.dtype)
    return dq3, dk3, dv3


def compute_delta(do3, o3):
    """Δ = rowsum(dO ⊙ O) broadcast to the [BH, Lq, 128] row layout LSE
    uses — shard-invariant, so ring callers compute it ONCE outside their
    ring loop and pass it in."""
    bh, lq, _ = o3.shape
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32), axis=-1)
    return jnp.broadcast_to(delta[:, :, None], (bh, lq, 128))


def _flash_bwd(q3, k3, v3, o3, lse3, do3, scale, causal, dq_blocks,
               dkv_blocks, kv_len, interpret, delta3=None):
    """Backward kernels with INDEPENDENTLY SPECIFIABLE tilings:
    ``dq_blocks`` / ``dkv_blocks`` are (block_q, block_k) for the dQ and
    dK/dV kernels. NOTE: isolated per-kernel sweeps suggested mixed
    tilings, but those do NOT compose — the composed A/B through the
    real vjp measured the 'per-kernel-optimal' mix 26% WORSE
    (BENCH_ATTENTION.md r4); ``flash_attention`` therefore passes the
    SAME tuple to both, length-selected. The two parameters exist for
    sweeps, not because mixed defaults won."""
    bh, lq, d = q3.shape
    lk = k3.shape[1]
    if delta3 is None:
        delta3 = compute_delta(do3, o3)
    kwargs = {}
    if not interpret:
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        )

    bq, bk = dq_blocks
    q_spec = pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0))
    row_spec = pl.BlockSpec((1, bq, 128), lambda b, i, j: (b, i, 0))
    kv_spec_q = pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0))
    dq3 = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, kv_len=kv_len),
        out_shape=jax.ShapeDtypeStruct((bh, lq, d), q3.dtype),
        grid=(bh, lq // bq, lk // bk),
        in_specs=[q_spec, kv_spec_q, kv_spec_q, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
        **kwargs,
    )(q3, k3, v3, do3, lse3, delta3)

    # dK/dV: grid puts the KV block second, Q innermost (the recurrence).
    bq, bk = dkv_blocks
    q_spec_i = pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0))
    row_spec_i = pl.BlockSpec((1, bq, 128), lambda b, j, i: (b, i, 0))
    kv_spec = pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0))
    dk3, dv3 = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, kv_len=kv_len),
        out_shape=[
            jax.ShapeDtypeStruct((bh, lk, d), k3.dtype),
            jax.ShapeDtypeStruct((bh, lk, d), v3.dtype),
        ],
        grid=(bh, lk // bk, lq // bq),
        in_specs=[q_spec_i, kv_spec, kv_spec, q_spec_i, row_spec_i, row_spec_i],
        out_specs=[kv_spec, kv_spec],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=interpret,
        **kwargs,
    )(q3, k3, v3, do3, lse3, delta3)
    return dq3, dk3, dv3


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10, 11, 12))
def _flash(q, k, v, scale, causal, block_q, block_k, kv_len, interpret,
           dq_blocks=None, dkv_blocks=None, bwd_impl="split",
           partials_f32=False):
    out, _ = _flash_vjp_fwd(
        q, k, v, scale, causal, block_q, block_k, kv_len, interpret,
        dq_blocks, dkv_blocks, bwd_impl, partials_f32,
    )
    return out


def _to3(x):
    b, l, h, d = x.shape
    return jnp.moveaxis(x, 2, 1).reshape(b * h, l, d)


def _from3(x3, b, h):
    bh, l, d = x3.shape
    return jnp.moveaxis(x3.reshape(b, h, l, d), 1, 2)


def _flash_vjp_fwd(q, k, v, scale, causal, block_q, block_k, kv_len,
                   interpret, dq_blocks=None, dkv_blocks=None,
                   bwd_impl="split", partials_f32=False):
    b, lq, h, d = q.shape
    o3, lse3 = _flash_fwd(
        _to3(q), _to3(k), _to3(v), scale, causal, block_q, block_k, kv_len,
        interpret,
    )
    return _from3(o3, b, h), (q, k, v, o3, lse3)


def _flash_vjp_bwd(scale, causal, block_q, block_k, kv_len, interpret,
                   dq_blocks, dkv_blocks, bwd_impl, partials_f32, res, g):
    q, k, v, o3, lse3 = res
    b, lq, h, d = q.shape
    # The backward tiles independently of the forward; flash_attention
    # computes the tuples (None only through direct _flash calls —
    # fall back to the forward tiling).
    dq_blocks = dq_blocks or (block_q, block_k)
    dkv_blocks = dkv_blocks or (block_q, block_k)
    if bwd_impl == "fused":
        dq3, dk3, dv3 = _flash_bwd_fused(
            _to3(q), _to3(k), _to3(v), o3, lse3, _to3(g.astype(q.dtype)),
            scale, causal, dq_blocks, kv_len, interpret,
            partials_f32=partials_f32,
        )
    else:
        dq3, dk3, dv3 = _flash_bwd(
            _to3(q), _to3(k), _to3(v), o3, lse3, _to3(g.astype(q.dtype)),
            scale, causal, dq_blocks, dkv_blocks, kv_len, interpret,
        )
    return _from3(dq3, b, h), _from3(dk3, b, h), _from3(dv3, b, h)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 512,
    block_k: int = 1024,
    bwd_block_q: Optional[int] = None,
    bwd_block_k: Optional[int] = None,
    interpret: bool | None = None,
    bwd_impl: str = "fused",
    partials_f32: bool = False,
) -> jax.Array:
    """FlashAttention: ``softmax(QKᵀ·scale)V`` tiled through VMEM.

    Args:
      q, k, v: ``[B, L, H, D]``; any lengths — inputs are zero-padded to
        block multiples and padded key positions are masked in-kernel
        (round 1 required exact multiples).
      bwd_block_q/bwd_block_k: ONE backward tiling (sweep/debug
        override). When left None the backward auto-tiles: the default
        fused kernel takes (1024, 1024) fit to the padded length at
        EVERY length (the r5 composed winner); the split path keeps its
        r4 rules ((1024, 1024) at padded L >= 4096, the forward tiling
        below). Isolated per-kernel sweeps suggested MIXED tilings —
        measured 26% WORSE composed; see BENCH_ATTENTION.md round-4.
      interpret: run the kernels in the Pallas interpreter (CPU testing).
      bwd_impl: "fused" (default, round 5) — single-pass dQ+dK+dV
        kernel with HBM dQ partials, 61-118 TFLOP/s fwdbwd at 1k-16k vs
        the split kernels' 48-97 (BENCH_ATTENTION.md r5); "split" — the
        r4 two-kernel decomposition (still used per ring visit by
        ops/ring_flash.py). PRECISION NOTE for the fused path: each
        (q, kv) grid step emits a partial dQ block at the INPUT dtype, so
        for bf16 models every partial rounds to bf16 before the fp32
        cross-partial sum — a deliberate precision change from the split
        kernels' pure-fp32 dQ accumulation, measured faster at every
        length and loss-neutral in training (BENCH_ATTENTION.md r5).
      partials_f32: keep the fused backward's dQ partials in fp32
        (doubles their HBM traffic; bitwise matches the split kernels'
        dQ accumulation dtype). Ignored by bwd_impl="split", which is
        always fp32. Exposed for precision sweeps and debugging
        suspected dQ rounding (ADVICE r5 #2).

    Default block sizes come from an on-chip sweep (v5e, causal, D=128,
    scripts/bench_attention.py --sweep): (512, 1024) wins at every length
    1k-8k — 41/50 TFLOP/s fwd/fwdbwd at L=1024 (the r2 defaults (256, 512)
    managed 27/41) and 86/90 at L=8192 (was 49/59). Blocks are clamped to
    the sequence length, so short sequences degrade gracefully.
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    if interpret is None:
        # Mosaic kernels need the Pallas interpreter on ANY non-TPU
        # backend (a GPU backend would otherwise dispatch Mosaic natively
        # and fail to compile); auto-detect so CPU tests/dryruns run the
        # same call sites unmodified.
        interpret = jax.default_backend() != "tpu"
    lq, lk = q.shape[1], k.shape[1]
    block_q = min(block_q, max(lq, 1))
    block_k = min(block_k, max(lk, 1))
    # padded lengths must be multiples of BOTH the fwd and bwd tilings
    # (the bwd kernels read the same padded residuals); with power-of-two
    # blocks the max is the lcm. Explicit bwd overrides are clamped to the
    # FORWARD-padded length (not the raw one): short sequences then
    # degrade gracefully like the unswept path, while a larger override
    # at block-multiple lengths still rounds the padding up to cover it.
    lq_pad0 = lq + ((-lq) % block_q)
    lk_pad0 = lk + ((-lk) % block_k)
    bq_c = min(bwd_block_q, lq_pad0) if bwd_block_q else block_q
    bk_c = min(bwd_block_k, lk_pad0) if bwd_block_k else block_k
    pq_mult = max(block_q, bq_c)
    pk_mult = max(block_k, bk_c)
    if pq_mult % min(block_q, bq_c) or pk_mult % min(block_k, bk_c):
        raise ValueError(
            f"bwd blocks ({bwd_block_q}, {bwd_block_k}) and fwd blocks "
            f"({block_q}, {block_k}) must divide each other pairwise "
            "(shared zero-padding)"
        )
    pad_q = (-lq) % pq_mult
    pad_k = (-lk) % pk_mult
    lq_pad, lk_pad = lq + pad_q, lk + pad_k

    def _fit(cand: int, n: int) -> int:
        # largest block <= cand that divides the padded length (blocks
        # and padded lengths are powers-of-two multiples of each other)
        b = min(cand, n)
        while n % b:
            b //= 2
        return max(b, 1)

    def _fit_pair(bq_cand, bk_cand):
        # auto-tile, guarded (ADVICE r4 #3): odd caller-chosen forward
        # blocks can make _fit land on a sub-lane-aligned size (e.g. a
        # non-multiple-of-8 block at padded L >= 4096) that fails Mosaic
        # compile — fall back to the forward tiling instead.
        bq_f, bk_f = _fit(bq_cand, lq_pad), _fit(bk_cand, lk_pad)
        for bb in (bq_f, bk_f):
            if bb < 128 and bb % 8:
                return (block_q, block_k)
        return (bq_f, bk_f)

    if bwd_block_q or bwd_block_k:
        dq_blocks = dkv_blocks = (min(bq_c, lq_pad), min(bk_c, lk_pad))
    elif bwd_impl == "fused":
        # r5 composed A/B (same-process, scripts/bench_attention.py): the
        # fused single-pass backward at (1024, 1024) beats the split
        # kernels at EVERY length — 61/83/109/114/118 TFLOP/s fwdbwd at
        # 1k/2k/4k/8k/16k vs split's 48/69/90/92/97. Larger blocks fail
        # Mosaic compile (VMEM); _fit clamps short/odd lengths.
        dq_blocks = dkv_blocks = _fit_pair(1024, 1024)
    elif lk_pad >= 4096:
        # r4 sweep THROUGH the real vjp: (1024, 1024) for both backward
        # kernels is the (marginal) winner at L in {4096, 8192} — 89.8 /
        # 99.1 TFLOP/s fwdbwd vs 89.1 / 97.2 at the shared (512, 1024).
        # NOTE the per-kernel standalone sweep suggested mixed tilings
        # (dKV (512, 2048) "1.77x faster") that do NOT compose end-to-end
        # — (512,1024)/(512,2048) measured 65.5 TFLOP/s, far WORSE;
        # standalone pallas_call timings mislead about the composed
        # pipeline. Composed measurements only.
        dq_blocks = dkv_blocks = _fit_pair(1024, 1024)
    else:
        dq_blocks = dkv_blocks = (block_q, block_k)

    if bwd_impl not in ("split", "fused"):
        raise ValueError(
            f"bwd_impl {bwd_impl!r} must be 'split' (two kernels) or "
            "'fused' (single-pass dQ+dK+dV with HBM dQ partials)"
        )
    if pad_q or pad_k:
        padq = lambda x: jnp.pad(x, ((0, 0), (0, pad_q), (0, 0), (0, 0)))
        padk = lambda x: jnp.pad(x, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
        out = _flash(
            padq(q), padk(k), padk(v), scale, causal, block_q, block_k, lk,
            interpret, dq_blocks, dkv_blocks, bwd_impl, partials_f32,
        )
        return out[:, :lq]
    return _flash(q, k, v, scale, causal, block_q, block_k, lk, interpret,
                  dq_blocks, dkv_blocks, bwd_impl, partials_f32)
