"""FlashAttention forward as a Pallas TPU kernel.

The blockwise kernel (``ops.attention.blockwise_attention``) is the XLA-fused
reference; this is the hand-tiled fast path for the same math, built per the
TPU Pallas playbook (/opt/skills/guides/pallas_guide.md):

- grid (B·H, Lq/block_q, Lk/block_k), KV innermost and sequential
  ("arbitrary" dimension semantics — it carries the online-softmax
  recurrence); Q/K/V blocks staged HBM→VMEM by BlockSpec index maps;
- the running (m, l, acc) state lives in VMEM scratch, persisting across the
  KV sweep for each Q block; everything accumulates in fp32 while inputs can
  be bf16 feeding the MXU (``preferred_element_type=f32``);
- causal masking skips fully-masked KV blocks with ``pl.when`` (no FLOPs
  spent above the diagonal — the compute saving the plain ring schedule
  lacks) and applies a multiplicative mask so fully-masked rows yield zeros
  (same contract as ``attend_block``);
- backward differentiates the blockwise jnp path via ``jax.custom_vjp``
  (rematerialized, O(L·block) memory) — a hand-written Pallas backward is
  the natural next step, the seam is already in place.

Shapes follow the framework convention ``[B, L, H, D]``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Pallas is a hard dependency of THIS module only: the ops package exports
# flash_attention lazily, so environments without pallas keep every other
# attention path working and fail loudly only when flash is actually chosen.

from pytorch_distributed_tpu.ops.attention import NEG_INF, blockwise_attention


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr,
    *, scale: float, causal: bool, block_q: int, block_k: int,
):
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    qi = pl.program_id(1)
    q_start = qi * block_q
    k_start = ki * block_k

    def _block():
        # Fold the softmax scale into Q: one [block_q, D] multiply instead
        # of a [block_q, block_k] one on the logits.
        q = (q_ref[0] * jnp.asarray(scale, q_ref.dtype))  # [block_q, D]
        k = k_ref[0]  # [block_k, D]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [block_q, block_k]
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            mask = k_pos <= q_pos
            s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:, :1]  # [block_q, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)  # [block_q, block_k]
        if causal:
            p = p * mask  # fully-masked rows stay all-zero (l == 0 → out 0)
        corr = jnp.exp(m_prev - m_new)  # [block_q, 1]
        l_new = l_scr[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    if causal:
        # A KV block strictly above the diagonal contributes nothing — skip
        # its FLOPs entirely.
        pl.when(k_start <= q_start + block_q - 1)(_block)
    else:
        _block()

    @pl.when(ki == n_k - 1)
    def _finalize():
        denom = jnp.maximum(l_scr[:, :1], 1e-37)
        o_ref[0] = (acc_scr[:] / denom).astype(o_ref.dtype)


def _flash_fwd(
    q3, k3, v3, scale, causal, block_q, block_k, interpret
):
    """[BH, L, D] inputs → [BH, Lq, D]."""
    bh, lq, d = q3.shape
    lk = k3.shape[1]
    grid = (bh, lq // block_q, lk // block_k)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k,
    )
    kwargs = {}
    if not interpret:
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((bh, lq, d), q3.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # running row max m
            pltpu.VMEM((block_q, 128), jnp.float32),  # running row sum l
            pltpu.VMEM((block_q, d), jnp.float32),  # un-normalized output
        ],
        interpret=interpret,
    )(q3, k3, v3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, scale, causal, block_q, block_k, interpret):
    b, lq, h, d = q.shape
    lk = k.shape[1]
    to3 = lambda x, l: jnp.moveaxis(x, 2, 1).reshape(b * h, l, d)
    o3 = _flash_fwd(
        to3(q, lq), to3(k, lk), to3(v, lk), scale, causal, block_q, block_k,
        interpret,
    )
    return jnp.moveaxis(o3.reshape(b, h, lq, d), 1, 2)


def _flash_vjp_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    return _flash(q, k, v, scale, causal, block_q, block_k, interpret), (q, k, v)


def _flash_vjp_bwd(scale, causal, block_q, block_k, interpret, res, g):
    q, k, v = res
    # Rematerialized blockwise backward (bit-matches the forward math up to
    # accumulation order); a Pallas backward kernel slots in here later.
    _, vjp = jax.vjp(
        lambda q, k, v: blockwise_attention(
            q, k, v, causal=causal, scale=scale, block_size=block_k
        ),
        q, k, v,
    )
    return vjp(g)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 256,
    block_k: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """FlashAttention: ``softmax(QKᵀ·scale)V`` tiled through VMEM.

    Args:
      q, k, v: ``[B, L, H, D]``; each L must be a multiple of its block size
        (blocks are clamped to L for short sequences).
      interpret: run the kernel in the Pallas interpreter (CPU testing).
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    lq, lk = q.shape[1], k.shape[1]
    block_q = min(block_q, lq)
    block_k = min(block_k, lk)
    if lq % block_q or lk % block_k:
        raise ValueError(
            f"sequence lengths ({lq}, {lk}) must be multiples of the block "
            f"sizes ({block_q}, {block_k})"
        )
    return _flash(q, k, v, scale, causal, block_q, block_k, interpret)
