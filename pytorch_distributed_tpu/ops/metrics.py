"""Top-k accuracy metrics.

Replaces the reference's inline validation math
(``restnet_ddp.py:51-61``): `outputs.topk(5)` then correct@1 / correct@5 /
total accumulated *on device* so the validation loop never syncs to host per
step. The accumulator pytree is summed across replicas with a single psum at
epoch end (ref ``dist.reduce(x, 0)``, ``restnet_ddp.py:63-64`` — we give
every host the global value, a strict superset of NCCL reduce-to-dst).
"""

from __future__ import annotations

import flax.struct
import jax
import jax.numpy as jnp


def topk_correct(logits: jax.Array, labels: jax.Array, ks=(1, 5)) -> dict:
    """Number of examples whose label is in the top-k predictions, per k.

    Uses ``lax.top_k`` (single pass for the largest k, prefixes give the
    smaller ks) — same semantics as ``outputs.topk(5)`` + prefix compare in
    the reference (``restnet_ddp.py:58-60``).
    """
    num_classes = logits.shape[-1]
    max_k = min(max(ks), num_classes)  # top-k over fewer classes always hits
    _, pred = jax.lax.top_k(logits, max_k)  # [batch, max_k]
    hit = pred == labels[:, None].astype(pred.dtype)  # [batch, max_k]
    return {
        f"correct{k}": jnp.sum(hit[:, : min(k, num_classes)]).astype(jnp.float32)  # jaxlint: disable=precision-cast -- psum'd counters must be fp32: exact integer sums
        for k in ks
    }


@flax.struct.dataclass
class ClassificationMetrics:
    """Device-resident running sums: loss, correct@1, correct@5, count.

    Mirrors ``loss, correct1, correct5, total = torch.zeros(4).cuda()``
    (``restnet_ddp.py:51``) as one immutable pytree that lives inside the
    compiled step.
    """

    loss_sum: jax.Array
    correct1: jax.Array
    correct5: jax.Array
    count: jax.Array

    @classmethod
    def empty(cls) -> "ClassificationMetrics":
        # Four distinct buffers: the eval step donates this pytree, and
        # aliasing one zero array into all fields would donate the same
        # buffer twice (XLA INVALID_ARGUMENT).
        zeros = (jnp.zeros((), jnp.float32) for _ in range(4))
        return cls(*zeros)

    @classmethod
    def from_step(
        cls, loss_sum: jax.Array, logits: jax.Array, labels: jax.Array
    ) -> "ClassificationMetrics":
        correct = topk_correct(logits, labels, ks=(1, 5))
        return cls(
            loss_sum=loss_sum.astype(jnp.float32),  # jaxlint: disable=precision-cast -- psum'd counters must be fp32: exact integer sums
            correct1=correct["correct1"],
            correct5=correct["correct5"],
            count=jnp.asarray(logits.shape[0], jnp.float32),  # jaxlint: disable=precision-cast -- psum'd counters must be fp32: exact integer sums
        )

    def merge(self, other: "ClassificationMetrics") -> "ClassificationMetrics":
        return jax.tree.map(lambda a, b: a + b, self, other)

    def summary(self, num_batches: int | None = None) -> dict:
        """Host-side readout: mean loss, acc1 %, acc5 % (ref ``restnet_ddp.py:66-70``)."""
        count = float(self.count)
        loss_denom = num_batches if num_batches else max(count, 1.0)
        return {
            "loss": float(self.loss_sum) / max(loss_denom, 1.0),
            "acc1": 100.0 * float(self.correct1) / max(count, 1.0),
            "acc5": 100.0 * float(self.correct5) / max(count, 1.0),
            "count": count,
        }
