"""Fused (blockwise) linear + softmax cross-entropy.

The LM's loss tail used to be ``lm_head`` Dense → fp32 ``[B, L, V]`` logits
→ ``log_softmax`` (``models/transformer.py`` + ``ops/losses.py``): at
bs8/L1024/V32k that is a ~1.0 GB fp32 tensor (double it for the backward
cotangent), which capped batch×length (bs8/L4096 failed to compile,
BENCH_LM.md) and spent HBM bandwidth on a tensor whose only purpose is a
per-token scalar. This op computes the SAME weighted loss sum without the
full logits ever existing:

- ``lax.scan`` over token blocks of ``block_n`` rows; each iteration runs
  one ``[block_n, E] × [E, V]`` matmul (bf16 operands on the MXU, fp32
  accumulation via ``preferred_element_type``) and immediately reduces it
  to ``lse`` / label-logit scalars — peak extra HBM is one
  ``[block_n, V]`` fp32 block (~131 MB at block_n=1024/V=32k; halve it
  with block_n=512), O(1) in sequence length;
- a ``custom_vjp`` whose residuals are the inputs plus the per-token
  ``lse``/``z`` vectors (``[N]`` fp32 — kilobytes); the backward recomputes
  each block's logits (one extra matmul pass — the classic recompute
  trade) and feeds ``softmax - onehot`` straight into the ``dx``/``dW``
  matmuls, so the backward's peak is the same single block;
- optional ``vocab_axis``: Megatron vocab-parallel heads pass their LOCAL
  kernel shard ``[E, V/tp]`` and the mesh axis name; the streamed softmax
  statistics combine across shards (pmax of block maxima, psum of the
  shifted exp-sums and of the masked label gather) and ``dx`` is psum'd
  the row-parallel way. Every shard returns the identical global loss sum.

Numerics note: the fused path accumulates the logits matmul in fp32
(``preferred_element_type``) where the unfused path materialized bf16
logits and upcast — the fused loss is therefore slightly MORE accurate
for bf16 models, not less. Parity is tested against ``ops.losses``
at fp32 (tests/test_fused_ce.py).

Reference precedent: none — the reference (583-line torch scripts) has no
LM. This is the "matching-or-beating" bar applied to our own
``transformer.py:548`` (VERDICT r4 next #1).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def _matmul_f32(a, b, cdt):
    """[M, E] x [E, V] with cdt (bf16) operands, fp32 accumulation."""
    return lax.dot_general(
        a.astype(cdt), b,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _fused_ce(block_n: int, cdt, vocab_axis: Optional[str],
              x, kernel, labels, weights):
    total, _ = _fused_ce_fwd(block_n, cdt, vocab_axis, x, kernel,
                             labels, weights)
    return total


def _block_stats(logits, loc_labels, v_local, vocab_axis):
    """(lse, z) for one block's logits [bn, V_local]; collective-combined
    when the vocab dim is sharded."""
    if vocab_axis is None:
        m = jnp.max(logits, axis=-1)
        lse = m + jnp.log(jnp.sum(jnp.exp(logits - m[:, None]), axis=-1))
        z = jnp.take_along_axis(logits, loc_labels[:, None], axis=1)[:, 0]
        return lse, z
    m_l = jnp.max(logits, axis=-1)
    m = lax.pmax(m_l, vocab_axis)
    s = lax.psum(
        jnp.sum(jnp.exp(logits - m[:, None]), axis=-1), vocab_axis
    )
    lse = m + jnp.log(s)
    in_range = (loc_labels >= 0) & (loc_labels < v_local)
    safe = jnp.clip(loc_labels, 0, v_local - 1)
    z_l = jnp.take_along_axis(logits, safe[:, None], axis=1)[:, 0]
    z = lax.psum(jnp.where(in_range, z_l, 0.0), vocab_axis)
    return lse, z


def _local_labels(labels, v_local, vocab_axis):
    """Global vocab ids → this shard's local ids (may be out of range
    under vocab parallelism; ``_block_stats``/``_bwd`` mask)."""
    if vocab_axis is None:
        return labels.astype(jnp.int32)
    off = lax.axis_index(vocab_axis) * v_local
    return labels.astype(jnp.int32) - off


def _fused_ce_fwd(block_n, cdt, vocab_axis, x, kernel, labels, weights):
    n, e = x.shape
    v_local = kernel.shape[1]
    nb = n // block_n
    k_c = kernel.astype(cdt)
    xb = x.reshape(nb, block_n, e)
    lb = _local_labels(labels, v_local, vocab_axis).reshape(nb, block_n)
    wb = weights.astype(jnp.float32).reshape(nb, block_n)

    def body(carry, inp):
        x_i, l_i, w_i = inp
        logits = _matmul_f32(x_i, k_c, cdt)
        lse, z = _block_stats(logits, l_i, v_local, vocab_axis)
        return carry + jnp.sum((lse - z) * w_i), (lse, z)

    total, (lse, z) = lax.scan(
        body, jnp.zeros((), jnp.float32), (xb, lb, wb)
    )
    return total, (x, kernel, labels, weights, lse.reshape(n), z.reshape(n))


def _fused_ce_bwd(block_n, cdt, vocab_axis, res, g):
    x, kernel, labels, weights, lse, z = res
    n, e = x.shape
    v_local = kernel.shape[1]
    nb = n // block_n
    k_c = kernel.astype(cdt)
    xb = x.reshape(nb, block_n, e)
    lb = _local_labels(labels, v_local, vocab_axis).reshape(nb, block_n)
    wb = weights.astype(jnp.float32).reshape(nb, block_n)
    lse_b = lse.reshape(nb, block_n)

    def body(dw, inp):
        x_i, l_i, w_i, lse_i = inp
        logits = _matmul_f32(x_i, k_c, cdt)
        p = jnp.exp(logits - lse_i[:, None])  # this shard's softmax slice
        onehot = (
            l_i[:, None] == jnp.arange(v_local)[None, :]
        ).astype(jnp.float32)  # out-of-range local ids match nothing
        dlogits = (p - onehot) * (w_i * g)[:, None]
        dl = dlogits.astype(cdt)
        # dx = dlogits @ W^T (row-parallel: psum over vocab shards);
        # dW = x^T @ dlogits (stays local to this vocab shard).
        dx_i = lax.dot_general(
            dl, k_c, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if vocab_axis is not None:
            dx_i = lax.psum(dx_i, vocab_axis)
        dw = dw + lax.dot_general(
            x_i.astype(cdt), dl, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dw, dx_i.astype(x.dtype)

    dw, dx = lax.scan(
        body,
        jnp.zeros(kernel.shape, jnp.float32),
        (xb, lb, wb, lse_b),
    )
    # Cotangent dtypes must match the PRIMAL dtypes: weights arrive at
    # whatever dtype the caller passed (the fwd casts a fp32 COPY for the
    # math), and returning a hardcoded fp32 cotangent for e.g. bf16
    # weights fails deep inside the vjp trace with an opaque dtype
    # mismatch (ADVICE r5 #4). The per-token loss (lse - z) stays fp32
    # until this final cast.
    d_weights = ((lse - z) * g).astype(weights.dtype)
    return (
        dx.reshape(n, e),
        dw.astype(kernel.dtype),
        np.zeros(labels.shape, jax.dtypes.float0),
        d_weights,
    )


def _fused_ce_fwd_rule(block_n, cdt, vocab_axis, x, kernel, labels, weights):
    total, res = _fused_ce_fwd(block_n, cdt, vocab_axis, x, kernel,
                               labels, weights)
    return total, res


_fused_ce.defvjp(_fused_ce_fwd_rule, _fused_ce_bwd)


def fused_linear_cross_entropy(
    x: jax.Array,
    kernel: jax.Array,
    labels: jax.Array,
    weights: jax.Array,
    *,
    block_n: int = 512,
    compute_dtype=jnp.bfloat16,
    vocab_axis: Optional[str] = None,
) -> jax.Array:
    """Weighted softmax-CE SUM of ``(x @ kernel)`` against ``labels``.

    Args:
      x: ``[N, E]`` (or ``[B, L, E]``) final hidden states (post-ln_f).
      kernel: ``[E, V]`` lm_head kernel — the LOCAL vocab shard
        ``[E, V/tp]`` when ``vocab_axis`` is set.
      labels: ``[N]``/``[B, L]`` int GLOBAL vocab ids.
      weights: ``[N]``/``[B, L]`` fp32 per-token loss weights (0 masks).
      block_n: token rows per scanned block; peak extra HBM is
        ``block_n * V_local`` fp32.
      compute_dtype: matmul operand dtype (the model's ``cfg.dtype``);
        accumulation is always fp32.
      vocab_axis: mesh axis the vocab dim is sharded over, for
        Megatron-style vocab-parallel heads (must be called inside
        shard_map over that axis).

    Returns the scalar fp32 weighted loss sum — identical (and replicated)
    on every vocab shard. Divide by the global token count outside.
    """
    if x.ndim == 3:
        x = x.reshape(-1, x.shape[-1])
    labels = labels.reshape(-1)
    weights = weights.reshape(-1)
    n = x.shape[0]
    bn = min(block_n, n)
    pad = (-n) % bn
    if pad:
        # zero-weight padding rows: zero loss, zero gradient contribution
        x = jnp.concatenate([x, jnp.zeros((pad, x.shape[1]), x.dtype)])
        labels = jnp.concatenate(
            [labels, jnp.zeros((pad,), labels.dtype)]
        )
        weights = jnp.concatenate(
            [weights, jnp.zeros((pad,), weights.dtype)]
        )
    # kernel is passed at its storage dtype (fp32 params): the bwd
    # accumulates dW in fp32 and returns it at that dtype — pre-casting
    # to bf16 here would bottleneck the weight gradient through bf16.
    cdt = jnp.dtype(compute_dtype)
    return _fused_ce(bn, cdt, vocab_axis, x, kernel, labels, weights)
