"""Pallas kernels for the fused bottleneck expand tail.

The ResNet bottleneck's expand tail — ``relu(bn(conv1x1(z, w)) + r)`` with
moment-derived batch stats (models/resnet.py `_fused_expand_tail`) — is
HBM-bandwidth-bound, and the v5e profiler trace shows XLA running its
reductions as separate ``convert_reduce_fusion`` kernels that each re-read
a wide tensor (17 ms/step across the ResNet-50 train step). These kernels
accumulate every reduction in VMEM **in the same pass** as the matmul or
elementwise work that already touches the tensor:

- ``moments(z)``: one read of z produces Σz AND zᵀz (XLA: a dot plus a
  separate reduce — two reads).
- ``tail_bwd_reduce(z, g, out)``: one read of (z, g, out) produces the
  masked gradient ``gp`` (written once — it IS the residual branch's
  gradient), the weight-gradient/BN-reduction carrier ``P = zᵀ gp``, and
  ``Σgp`` (XLA: materialize gp, then two more full reads).
- ``tail_bwd_dz(gp, z, wa, c, dmn)``: ``dz = gp·wa + z·c + dmn`` — two MXU
  matmuls and the broadcast merged into one output write (XLA: two conv
  kernels each materializing a [*, F] temporary, then an add fusion).

All kernels grid over the batch dim with full-spatial blocks (ResNet-50's
largest row is ~1.6 MB — VMEM-comfortable), accumulate in fp32, and run
in interpret mode off-TPU so CPU tests execute the same code path.
"""

# jaxlint: disable-file=precision-cast -- Pallas reduction scratch accumulates in fp32 regardless of io dtype; the casts feed those accumulators (burned down from the lint baseline, PR 9)

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    # Mosaic needs the interpreter on ANY non-TPU backend, not just CPU
    return jax.default_backend() != "tpu"


def _row(ref):
    """Load a [1, h, w, C] block as [h*w, C]."""
    v = ref[0]
    return v.reshape(v.shape[0] * v.shape[1], v.shape[2])


def _moments_kernel(z_ref, s_ref, m2_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        s_ref[:] = jnp.zeros_like(s_ref)
        m2_ref[:] = jnp.zeros_like(m2_ref)

    z = _row(z_ref)
    s_ref[:] = s_ref[:] + jnp.sum(z.astype(jnp.float32), axis=0,
                                  keepdims=True)
    m2_ref[:] = m2_ref[:] + jax.lax.dot_general(
        z, z, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


@jax.jit
def moments(z: jax.Array):
    """``(Σz, zᵀz)`` over (B,H,W) of NHWC ``z``, one pass, fp32."""
    b, h, w, f = z.shape
    s, m2 = pl.pallas_call(
        _moments_kernel,
        grid=(b,),
        in_specs=[pl.BlockSpec((1, h, w, f), lambda i: (i, 0, 0, 0))],
        out_specs=[
            pl.BlockSpec((1, f), lambda i: (0, 0)),
            pl.BlockSpec((f, f), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, f), jnp.float32),
            jax.ShapeDtypeStruct((f, f), jnp.float32),
        ],
        interpret=_interpret(),
    )(z)
    return s[0], m2


def _bwd_reduce_kernel(z_ref, g_ref, out_ref, gp_ref, p_ref, sb_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        p_ref[:] = jnp.zeros_like(p_ref)
        sb_ref[:] = jnp.zeros_like(sb_ref)

    g = g_ref[0]
    # compare in fp32: Mosaic (v5e) rejects bf16 vector comparisons
    gp = jnp.where(out_ref[0].astype(jnp.float32) > 0, g, jnp.zeros_like(g))
    gp_ref[0] = gp
    gpf = gp.reshape(gp.shape[0] * gp.shape[1], gp.shape[2])
    p_ref[:] = p_ref[:] + jax.lax.dot_general(
        _row(z_ref), gpf, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    sb_ref[:] = sb_ref[:] + jnp.sum(gpf.astype(jnp.float32), axis=0,
                                    keepdims=True)


@jax.jit
def tail_bwd_reduce(z: jax.Array, g: jax.Array, out: jax.Array):
    """One pass over (z, g, out): returns ``(gp, P, Σgp)`` where
    ``gp = g·[out>0]`` (the relu-masked gradient, = the residual grad),
    ``P = zᵀgp`` [F,E] fp32, ``Σgp`` [E] fp32."""
    b, h, w, f = z.shape
    e = g.shape[-1]
    gp, p, sb = pl.pallas_call(
        _bwd_reduce_kernel,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, h, w, f), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((1, h, w, e), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((1, h, w, e), lambda i: (i, 0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, h, w, e), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((f, e), lambda i: (0, 0)),
            pl.BlockSpec((1, e), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(g.shape, g.dtype),
            jax.ShapeDtypeStruct((f, e), jnp.float32),
            jax.ShapeDtypeStruct((1, e), jnp.float32),
        ],
        interpret=_interpret(),
    )(z, g, out)
    return gp, p, sb[0]


def _bwd_dz_kernel(gp_ref, z_ref, wa_ref, c_ref, dmn_ref, dz_ref):
    acc = jax.lax.dot_general(
        _row(gp_ref), wa_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    acc = acc + jax.lax.dot_general(
        _row(z_ref), c_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    acc = acc + dmn_ref[:]
    sh = dz_ref.shape
    dz_ref[0] = acc.astype(dz_ref.dtype).reshape(sh[1], sh[2], sh[3])


@jax.jit
def tail_bwd_dz(gp: jax.Array, z: jax.Array, wa: jax.Array, c: jax.Array,
                dmn: jax.Array):
    """``dz = gp @ wa + z @ c + dmn`` in one output write.

    ``wa = diag(a)·wᵀ`` [E,F] carries the conv backward, ``c = 2·dM`` [F,F]
    the moment path, ``dmn = dm/n`` [1,F] the mean path."""
    b, h, w, f = z.shape
    e = gp.shape[-1]
    return pl.pallas_call(
        _bwd_dz_kernel,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, h, w, e), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((1, h, w, f), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((e, f), lambda i: (0, 0)),
            pl.BlockSpec((f, f), lambda i: (0, 0)),
            pl.BlockSpec((1, f), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, h, w, f), lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(z.shape, z.dtype),
        interpret=_interpret(),
    )(gp, z, wa, c, dmn)
