from pytorch_distributed_tpu.ops.attention import (
    attend_block,
    blockwise_attention,
    dense_attention,
)


def __getattr__(name):
    # Lazy: the pallas kernels pull in pallas/pltpu; environments without
    # them keep every other op usable and fail only when one is chosen.
    if name == "flash_attention":
        from pytorch_distributed_tpu.ops.flash_attention import flash_attention

        return flash_attention
    if name == "paged_flash_attention":
        from pytorch_distributed_tpu.ops.paged_flash import (
            paged_flash_attention,
        )

        return paged_flash_attention
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
from pytorch_distributed_tpu.ops.losses import cross_entropy_loss
from pytorch_distributed_tpu.ops.metrics import topk_correct, ClassificationMetrics
from pytorch_distributed_tpu.ops.optim import (
    sgd_with_weight_decay,
    build_optimizer,
    clip_by_global_norm,
    clip_grads_by_global_norm,
    sharded_global_norm,
)
from pytorch_distributed_tpu.ops.precision import (
    Policy,
    DynamicLossScaler,
    NoOpLossScaler,
)
from pytorch_distributed_tpu.ops.schedules import step_lr, warmup_cosine

__all__ = [
    "attend_block",
    "blockwise_attention",
    "dense_attention",
    "cross_entropy_loss",
    "topk_correct",
    "ClassificationMetrics",
    "sgd_with_weight_decay",
    "build_optimizer",
    "clip_by_global_norm",
    "clip_grads_by_global_norm",
    "sharded_global_norm",
    "Policy",
    "DynamicLossScaler",
    "NoOpLossScaler",
    "step_lr",
    "warmup_cosine",
]
