"""Attention kernels: dense reference, blockwise (memory-efficient), and the
shared online-softmax combine that ring attention reuses.

The reference has no attention at all (a ResNet CNN,
``resnet_single_gpu.py:83``; SURVEY.md §5 "long-context: ABSENT") — this
module is part of the framework's first-class long-context support, built
TPU-first:

- all softmax statistics in fp32 regardless of compute dtype (bf16 QK^T
  products are fine; exp/sum are not);
- blockwise attention is a ``lax.scan`` over key/value blocks with an
  online-softmax accumulator (the Rabe-Staats / FlashAttention recurrence):
  O(L·block) activation memory instead of O(L²), static shapes, MXU-sized
  blocks; XLA autodiff differentiates the scan, and ``jax.checkpoint`` on
  the block body keeps backward memory flat;
- every kernel takes absolute position offsets for Q and KV, so the same
  code computes a causal mask inside one device's shard or across ring
  steps where the KV block came from another device
  (``parallel/sequence.py``);
- ``paged_attention`` is the serving engine's read path: decode/chunk
  queries against a block-pooled KV cache through a block table
  (``serving/kv_pool.py``) — a dense ``jnp.take``-over-blocks gather, or
  the fused Pallas kernel (``ops/paged_flash.py``) that reads the table
  from its BlockSpec index maps and never materializes the gather; both
  spellings accept int8 pools with per-row scales.

Shapes follow the JAX convention: ``[batch, length, heads, head_dim]``.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30  # additive mask value; avoids -inf - -inf = nan in softmax


class SoftmaxState(NamedTuple):
    """Online-softmax accumulator carried across KV blocks (fp32).

    o: un-normalized weighted values  [B, Lq, H, D]
    m: running row max of logits      [B, Lq, H]
    l: running sum of exp(logit - m)  [B, Lq, H]
    """

    o: jax.Array
    m: jax.Array
    l: jax.Array

    @classmethod
    def zero(cls, batch, q_len, heads, head_dim) -> "SoftmaxState":
        return cls(
            o=jnp.zeros((batch, q_len, heads, head_dim), jnp.float32),
            m=jnp.full((batch, q_len, heads), NEG_INF, jnp.float32),
            l=jnp.zeros((batch, q_len, heads), jnp.float32),
        )

    def finalize(self, dtype) -> jax.Array:
        """Normalize. Rows that saw only masked keys produce zeros."""
        denom = jnp.maximum(self.l, 1e-37)[..., None]
        return (self.o / denom).astype(dtype)


def attend_block(
    state: SoftmaxState,
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    scale: float,
    causal: bool,
    q_offset: jax.Array | int = 0,
    k_offset: jax.Array | int = 0,
) -> SoftmaxState:
    """Fold one KV block into the online-softmax state.

    This is the single source of truth for the attention recurrence — the
    blockwise kernel scans it over local KV blocks and ring attention folds
    it once per ring step with the visiting KV shard.
    """
    b, lq, h, d = q.shape
    lk = k.shape[1]
    # [B, H, Lq, Lk] logits in fp32
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    allowed = None
    if causal:
        q_pos = q_offset + jnp.arange(lq)
        k_pos = k_offset + jnp.arange(lk)
        allowed = k_pos[None, :] <= q_pos[:, None]  # [Lq, Lk]
        logits = jnp.where(allowed[None, None], logits, NEG_INF)

    m_block = jnp.max(logits, axis=-1)  # [B, H, Lq]
    m_block = jnp.transpose(m_block, (0, 2, 1))  # [B, Lq, H]
    m_new = jnp.maximum(state.m, m_block)
    # Avoid exp overflow for fully-masked rows: m_new >= NEG_INF.
    correction = jnp.exp(state.m - m_new)  # [B, Lq, H]
    p = jnp.exp(
        logits - jnp.transpose(m_new, (0, 2, 1))[..., None]
    )  # [B, H, Lq, Lk] fp32
    if allowed is not None:
        # Multiplicative zeroing so a FULLY-masked row contributes nothing
        # (additive NEG_INF alone would leave p = exp(0) = 1 uniform there):
        # l stays 0 and finalize() returns zeros, as documented.
        p = p * allowed[None, None]
    l_block = jnp.transpose(jnp.sum(p, axis=-1), (0, 2, 1))
    o_block = jnp.einsum(
        "bhqk,bkhd->bqhd", p, v.astype(jnp.float32),  # jaxlint: disable=precision-cast -- fp32 PV accumulation; o/l state is fp32 by kernel contract
        preferred_element_type=jnp.float32,
    )
    return SoftmaxState(
        o=state.o * correction[..., None] + o_block,
        m=m_new,
        l=state.l * correction + l_block,
    )


def dense_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    q_offset: jax.Array | int = 0,
    k_offset: jax.Array | int = 0,
) -> jax.Array:
    """Reference O(L²) attention (correctness baseline and short-seq path)."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    probs_mask = None
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])
        k_pos = k_offset + jnp.arange(k.shape[1])
        probs_mask = (k_pos[None, :] <= q_pos[:, None])[None, None]
        logits = jnp.where(probs_mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    if probs_mask is not None:
        # Fully-masked rows: zeros, not uniform (matches blockwise/ring).
        probs = probs * probs_mask
    return jnp.einsum(
        "bhqk,bkhd->bqhd", probs, v.astype(jnp.float32)  # jaxlint: disable=precision-cast -- fp32 PV matmul matches blockwise/ring accumulator dtype
    ).astype(q.dtype)


def paged_attention(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    block_tables: jax.Array,
    q_positions: jax.Array,
    *,
    scale: Optional[float] = None,
    gather_impl: str = "dense",
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
    split_s: Optional[int] = None,
) -> jax.Array:
    """Decode/chunk-prefill attention against a block-pooled KV cache.

    The serving engine's cache is a fixed pool of KV blocks
    (``serving.kv_pool``); each request owns a chain of blocks recorded in
    its block-table row, so admission never copies resident requests' KV.
    This op is the read side: gather each request's blocks back into a
    logical [L, H_kv, D] sequence and attend causally at absolute
    positions.

    Args:
      q: ``[B, C, H, D]`` queries — C == 1 for a decode tick, C == chunk
        length for chunked prefill (both use this one op, so the two can
        never diverge on masking).
      k_pool, v_pool: ``[n_blocks, block_len, H_kv, D]`` pooled cache.
        ``H_kv < H`` is the GQA layout; query head h reads narrow head
        ``h // (H // H_kv)`` via a grouped einsum — the widened K/V never
        materializes (same trick as the dense decode path).
      block_tables: ``[B, W]`` int32 — request b's logical positions
        ``[w*block_len, (w+1)*block_len)`` live in pool block
        ``block_tables[b, w]``. Entries past the request's allocation
        should point at the engine's trash block; they are masked out
        (their logical positions exceed every query position).
      q_positions: ``[B, C]`` int32 absolute positions of the queries;
        key position j is visible to query i iff ``j <= q_positions[i]``.
      gather_impl: ``"dense"`` — one ``jnp.take`` over the block dim,
        materializing the gathered KV in HBM (the reference spelling;
        PERF_NOTES §6's lesson is to change the math XLA sees, not excise
        ops into custom calls). ``"pallas"`` — the fused gather-attend
        kernel (``ops.paged_flash``): BlockSpec index maps read the
        block table directly (scalar prefetch), so pool blocks DMA
        HBM→VMEM in chain order and the gathered copy never exists;
        runs the Pallas interpreter on non-TPU backends, so both
        spellings execute everywhere. Either spelling compiles inside
        the same engine programs, so the program-registry bucket
        enumeration (``compilecache.serving_registry`` over
        ``PagedEngine.chunk_buckets``) covers both and the warmup
        runtime prewarms whichever the engine was built with.
      k_scale, v_scale: per-(block, slot, head) dequantization scale
        siblings ``[n_blocks, block_len, H_kv]`` — required iff the
        pools are quantized (``serving.kv_pool`` ``kv_dtype="int8"``:
        fp32 multipliers; ``"fp8"``/``"fp8_e5m2"``: int8 power-of-two
        exponents, multiplier ``2**e`` via ``kv_pool.scale_factors``).
        Both spellings dequantize before the softmax statistics; the
        pallas kernel does it block-by-block in VMEM.
      split_s: flash-decoding worker count for the pallas spelling's
        chain sweep (``ops.paged_flash``): None auto-enables when W/B
        crosses the split threshold, 1 forces the single-worker sweep,
        S > 1 forces S workers. The dense spelling has no chain sweep
        to split — it ignores this knob.

    Returns ``[B, C, H, D]`` in q's dtype. Softmax statistics in fp32.
    """
    from pytorch_distributed_tpu.serving.kv_pool import (
        is_quantized_pool,
        scale_factors,
    )

    if gather_impl not in ("dense", "pallas"):
        raise ValueError(
            f"gather_impl {gather_impl!r} must be 'dense' (jnp.take "
            "gather) or 'pallas' (fused ops.paged_flash kernel); see "
            "compilecache/registry.py for the bucket enumeration both "
            "stay in sync with"
        )
    quantized = is_quantized_pool(k_pool.dtype)
    if bool(quantized) != (k_scale is not None):
        raise ValueError(
            "quantized (int8/fp8) pools need k_scale/v_scale and float "
            f"pools must not pass them (pool dtype {k_pool.dtype}, "
            f"k_scale {'set' if k_scale is not None else 'None'})"
        )
    if gather_impl == "pallas":
        from pytorch_distributed_tpu.ops.paged_flash import (
            paged_flash_attention,
        )

        return paged_flash_attention(
            q, k_pool, v_pool, block_tables, q_positions, scale=scale,
            k_scale=k_scale, v_scale=v_scale, split_s=split_s,
        )
    b, c, h, d = q.shape
    n_blocks, block_len, h_kv, _ = k_pool.shape
    if h % h_kv:
        raise ValueError(
            f"query heads {h} not a multiple of pool KV heads {h_kv}"
        )
    group = h // h_kv
    w = block_tables.shape[1]
    scale = scale if scale is not None else d ** -0.5
    # Gather the per-request logical KV sequences: [B, W*block_len, H_kv, D].
    kg = jnp.take(k_pool, block_tables, axis=0).reshape(
        b, w * block_len, h_kv, d
    )
    vg = jnp.take(v_pool, block_tables, axis=0).reshape(
        b, w * block_len, h_kv, d
    )
    if k_scale is not None:
        # quantized pool: dequantize AFTER the gather (per-row-per-head
        # scale siblings ride the same take; scale_factors turns int8
        # exponents into 2**e multipliers for fp8 pools), keeping the
        # einsums below on fp32 values identical to what the pallas
        # kernel dequantizes in VMEM
        ks = jnp.take(scale_factors(k_scale), block_tables,
                      axis=0).reshape(b, w * block_len, h_kv)
        vs = jnp.take(scale_factors(v_scale), block_tables,
                      axis=0).reshape(b, w * block_len, h_kv)
        kg = kg.astype(jnp.float32) * ks[..., None]  # jaxlint: disable=precision-cast -- quantized-pool dequantization to the fp32 softmax-statistics dtype
        vg = vg.astype(jnp.float32) * vs[..., None]  # jaxlint: disable=precision-cast -- quantized-pool dequantization to the fp32 softmax-statistics dtype
    # Grouped logits directly against the narrow heads (query head
    # h = h_kv_idx*group + g), fp32 statistics like every other path.
    qg = (q.astype(jnp.float32) * scale).reshape(b, c, h_kv, group, d)  # jaxlint: disable=precision-cast -- fp32 softmax statistics by kernel contract
    s = jnp.einsum(
        "bqhgd,bkhd->bhgqk", qg, kg.astype(jnp.float32)  # jaxlint: disable=precision-cast -- fp32 softmax statistics by kernel contract
    )  # [B, H_kv, G, C, W*bl]
    k_pos = jnp.arange(w * block_len)
    allowed = (
        k_pos[None, None, None, None, :]
        <= q_positions[:, None, None, :, None]
    )
    s = jnp.where(allowed, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    p = p * allowed  # fully-masked rows → zeros, matching dense/blockwise
    out = jnp.einsum(
        "bhgqk,bkhd->bqhgd", p, vg.astype(jnp.float32)  # jaxlint: disable=precision-cast -- fp32 PV accumulation matches the other attention paths
    )
    return out.reshape(b, c, h, d).astype(q.dtype)


def blockwise_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    block_size: int = 512,
    q_offset: jax.Array | int = 0,
    k_offset: jax.Array | int = 0,
    remat: bool = True,
) -> jax.Array:
    """Memory-efficient attention: scan KV blocks with online softmax.

    O(Lq·block_size) live memory; with ``remat`` the scan body is
    rematerialized in backward, so training memory stays flat in sequence
    length. Block size should be MXU-friendly (multiple of 128 on TPU; it
    is clamped to the sequence length for small inputs).
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    b, lq, h, d = q.shape
    lk = k.shape[1]
    bs = min(block_size, lk)
    if lk % bs:
        raise ValueError(f"kv length {lk} not divisible by block_size {bs}")
    n_blocks = lk // bs

    k_blocks = k.reshape(b, n_blocks, bs, h, d)
    v_blocks = v.reshape(b, n_blocks, bs, h, d)

    def body(state, inputs):
        i, kb, vb = inputs
        state = attend_block(
            state, q, kb, vb,
            scale=scale, causal=causal,
            q_offset=q_offset, k_offset=k_offset + i * bs,
        )
        return state, None

    if remat:
        body = jax.checkpoint(body)

    init = SoftmaxState.zero(b, lq, h, d)
    idx = jnp.arange(n_blocks)
    state, _ = jax.lax.scan(
        body, init, (idx, jnp.moveaxis(k_blocks, 1, 0), jnp.moveaxis(v_blocks, 1, 0))
    )
    return state.finalize(q.dtype)
