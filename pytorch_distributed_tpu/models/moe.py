"""Mixture-of-Experts MLP with expert parallelism (GShard/Switch style).

Absent from the reference (SURVEY.md §2c: EP out of scope) but part of this
framework's first-class parallelism set. TPU-first shape discipline
throughout: routing is static-shape capacity-based dispatch (one-hot
einsums, no gather/scatter, no data-dependent shapes), so the whole layer
compiles into the surrounding step.

Expert parallelism rides the **data** axis: DP ranks hold different tokens
and different expert shards (the classic GShard identification of the
expert axis with the data axis), so a single ``lax.all_to_all`` per
direction moves each token to its expert's owner and back. Expert weights
are stored GLOBAL-shaped ``[E, ...]`` and sharded by placement
(``P(data)`` on the expert dim — same design as the TP rules), which keeps
checkpoints layout-independent; gradients of sharded expert weights are
local to their owner, handled by the spec-driven reduction in
``train.lm.make_lm_train_step``.

Routing: top-1 (Switch Transformer) with capacity ``ceil(cf · T / E)``;
over-capacity tokens fall through to the residual path. The Switch
load-balancing auxiliary loss is sowed (pre-weighted) into the
``aux_loss`` collection; the LM step collects and adds it.

Interaction with tensor parallelism: MoE blocks do NOT partition over the
model axis — under TP every model rank computes the full expert MLP
redundantly (replicated activations in, replicated out, identical grads).
Correct, but TP buys no FLOPs in MoE layers; partitioning the expert hidden
dim over the model axis is the planned follow-up.
"""

from __future__ import annotations

import math
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


def top1_dispatch(
    router_logits: jax.Array,  # [T, E] fp32
    capacity: int,
):
    """Static-shape top-1 routing.

    Returns (dispatch [T, E, C] f32 0/1, combine [T, E, C] f32 gate-weighted,
    aux_loss scalar). Tokens beyond an expert's capacity are dropped
    (all-zero rows in dispatch ⇒ the layer contributes nothing for them).
    """
    t, e = router_logits.shape
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)  # [T]
    onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)  # [T, E]

    # Position of each token within its chosen expert's buffer (0-based);
    # non-chosen entries contribute 0, so the row-sum is exactly the
    # chosen-expert position.
    position = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot  # [T, E]
    pos_tok = jnp.sum(position, axis=-1).astype(jnp.int32)  # [T]
    keep_tok = (pos_tok < capacity).astype(jnp.float32)  # [T]
    dispatch = (
        onehot[:, :, None]
        * jax.nn.one_hot(pos_tok, capacity, dtype=jnp.float32)[:, None, :]
        * keep_tok[:, None, None]
    )  # [T, E, C]

    gate = jnp.sum(probs * onehot, axis=-1)  # [T] chosen-expert prob
    combine = dispatch * gate[:, None, None]

    # Switch load-balancing loss: E · Σ_e (token fraction)·(mean prob).
    frac = jnp.mean(onehot, axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(frac * mean_prob)
    return dispatch, combine, aux


class MoEMLP(nn.Module):
    """Switch-style MoE replacement for the dense transformer MLP.

    Attributes mirror TransformerConfig: ``n_experts`` global experts with
    hidden width ``mlp_dim``; ``ep_size``/``expert_axis`` enable expert
    parallelism over a mesh axis (weights locally ``[E/ep, ...]`` under
    shard_map, globally ``[E, ...]``).
    """

    n_experts: int
    mlp_dim: int
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    ep_size: int = 1
    expert_axis: Optional[str] = None
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, l, d = x.shape
        t = b * l
        e = self.n_experts
        e_local = e // self.ep_size
        x_flat = x.reshape(t, d)

        router = nn.Dense(e, use_bias=False, dtype=jnp.float32, name="router")
        logits = router(x_flat.astype(jnp.float32))
        capacity = max(math.ceil(self.capacity_factor * t / e), 1)
        dispatch, combine, aux = top1_dispatch(logits, capacity)
        self.sow("aux_loss", "moe", self.aux_loss_weight * aux)

        w_up = self.param(
            "w_up",
            nn.initializers.variance_scaling(2.0, "fan_in", "truncated_normal"),
            (e_local, d, self.mlp_dim),
        )
        w_down = self.param(
            "w_down",
            nn.initializers.variance_scaling(2.0, "fan_in", "truncated_normal"),
            (e_local, self.mlp_dim, d),
        )

        # [T, E, C] × [T, D] → per-expert buffers [E, C, D]
        expert_in = jnp.einsum(
            "tec,td->ecd", dispatch.astype(self.dtype), x_flat.astype(self.dtype)
        )

        if self.expert_axis and self.ep_size > 1:
            # Ship each expert's buffer to its owner: [E, C, D] →
            # [ep, E_local, C, D], exchange over the axis, gather the ep
            # source chunks along capacity.
            xe = expert_in.reshape(self.ep_size, e_local, capacity, d)
            xe = jax.lax.all_to_all(
                xe, self.expert_axis, split_axis=0, concat_axis=0, tiled=False
            )  # [ep(src), E_local, C, D]
            xe = jnp.moveaxis(xe, 0, 1).reshape(e_local, self.ep_size * capacity, d)
        else:
            xe = expert_in  # [E(=E_local), C, D]

        h = jnp.einsum("ecd,edf->ecf", xe, w_up.astype(self.dtype))
        h = nn.gelu(h)
        ye = jnp.einsum("ecf,efd->ecd", h, w_down.astype(self.dtype))

        if self.expert_axis and self.ep_size > 1:
            ye = ye.reshape(e_local, self.ep_size, capacity, d)
            ye = jnp.moveaxis(ye, 1, 0)  # [ep(src), E_local, C, D]
            ye = jax.lax.all_to_all(
                ye, self.expert_axis, split_axis=0, concat_axis=0, tiled=False
            )  # back at the token owner: [ep(dest), E_local, C, D]
            ye = ye.reshape(e, capacity, d)

        out = jnp.einsum("tec,ecd->td", combine.astype(self.dtype), ye)
        return out.reshape(b, l, d)
