"""Mixture-of-Experts MLP with expert parallelism (GShard/Switch style).

Absent from the reference (SURVEY.md §2c: EP out of scope) but part of this
framework's first-class parallelism set. TPU-first shape discipline
throughout: routing is static-shape capacity-based dispatch (one-hot
einsums, no gather/scatter, no data-dependent shapes), so the whole layer
compiles into the surrounding step.

Expert parallelism rides the **data** axis: DP ranks hold different tokens
and different expert shards (the classic GShard identification of the
expert axis with the data axis), so a single ``lax.all_to_all`` per
direction moves each token to its expert's owner and back. Expert weights
are stored GLOBAL-shaped ``[E, ...]`` and sharded by placement
(``P(data)`` on the expert dim — same design as the TP rules), which keeps
checkpoints layout-independent; gradients of sharded expert weights are
local to their owner, handled by the spec-driven reduction in
``train.lm.make_lm_train_step``.

Routing: top-1 (Switch Transformer) with capacity ``ceil(cf · T / E)``;
over-capacity tokens fall through to the residual path. The Switch
load-balancing auxiliary loss is sowed (pre-weighted) into the
``aux_loss`` collection; the LM step collects and adds it.

Interaction with tensor parallelism: with ``model_axis``/``tp_size`` set,
the expert HIDDEN dim partitions over the model axis (Megatron column/row
split inside each expert: ``w_up`` is column-parallel, ``w_down``
row-parallel with one psum) — TP buys real FLOPs in MoE blocks. Router,
dispatch, and the capacity buffers stay replicated across the model axis
(every TP rank routes identically), so the all_to_all expert exchange is
unchanged. With ``model_axis=None`` (default) every model rank computes
the full expert MLP redundantly — correct, just wasteful, kept for
mesh-without-TP layouts.
"""

from __future__ import annotations

import math
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


def topk_dispatch(
    router_logits: jax.Array,  # [T, E] fp32
    capacity: int,
    k: int = 1,
):
    """Static-shape top-k routing (k=1: Switch; k=2: GShard).

    Returns (dispatch [T, E, C] f32 0/1, combine [T, E, C] f32
    gate-weighted, aux_loss scalar, stats dict). Capacity is filled in
    choice-rank priority (all first choices place before any second
    choice, the GShard rule); assignments beyond capacity are dropped —
    ``stats["dropped_frac"]`` is the fraction of tokens with NO surviving
    route (their block output is the residual alone).

    Gates: k=1 uses the raw chosen probability (Switch); k>1 normalizes the
    chosen probabilities to sum to 1 (GShard), keeping the layer's output
    scale constant in k.
    """
    t, e = router_logits.shape
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    topv, topi = jax.lax.top_k(probs, k)  # [T, k]
    if k == 1:
        gates = topv
    else:
        gates = topv / jnp.maximum(jnp.sum(topv, axis=-1, keepdims=True), 1e-9)

    dispatch = jnp.zeros((t, e, capacity), jnp.float32)
    combine = jnp.zeros((t, e, capacity), jnp.float32)
    counts = jnp.zeros((e,), jnp.float32)  # buffer fill from earlier ranks
    for r in range(k):  # k is a small static constant
        onehot = jax.nn.one_hot(topi[:, r], e, dtype=jnp.float32)  # [T, E]
        # Position of each token within its expert's buffer: tokens placed
        # by earlier choice-ranks (counts) go first, then arrival order.
        position = (jnp.cumsum(onehot, axis=0) - 1.0 + counts) * onehot
        pos_tok = jnp.sum(position, axis=-1).astype(jnp.int32)  # [T]
        keep = (pos_tok < capacity).astype(jnp.float32)  # [T]
        disp_r = (
            onehot[:, :, None]
            * jax.nn.one_hot(pos_tok, capacity, dtype=jnp.float32)[:, None, :]
            * keep[:, None, None]
        )  # [T, E, C]
        dispatch = dispatch + disp_r
        combine = combine + disp_r * gates[:, r][:, None, None]
        counts = counts + jnp.sum(onehot * keep[:, None], axis=0)

    # Switch/GShard load-balancing loss on FIRST-choice statistics:
    # E · Σ_e (token fraction)·(mean prob).
    first = jax.nn.one_hot(topi[:, 0], e, dtype=jnp.float32)
    aux = e * jnp.sum(jnp.mean(first, axis=0) * jnp.mean(probs, axis=0))
    routed = jnp.sum(dispatch, axis=(1, 2))  # [T] surviving routes per token
    stats = {"dropped_frac": jnp.mean((routed == 0.0).astype(jnp.float32))}
    return dispatch, combine, aux, stats


def top1_dispatch(router_logits: jax.Array, capacity: int):
    """Switch-style top-1 routing (back-compat wrapper over
    ``topk_dispatch``): returns (dispatch, combine, aux_loss)."""
    dispatch, combine, aux, _ = topk_dispatch(router_logits, capacity, k=1)
    return dispatch, combine, aux


class MoEMLP(nn.Module):
    """Switch-style MoE replacement for the dense transformer MLP.

    Attributes mirror TransformerConfig: ``n_experts`` global experts with
    hidden width ``mlp_dim``; ``ep_size``/``expert_axis`` enable expert
    parallelism over a mesh axis (weights locally ``[E/ep, ...]`` under
    shard_map, globally ``[E, ...]``).
    """

    n_experts: int
    mlp_dim: int
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    top_k: int = 1
    ep_size: int = 1
    expert_axis: Optional[str] = None
    tp_size: int = 1
    model_axis: Optional[str] = None
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, l, d = x.shape
        t = b * l
        e = self.n_experts
        e_local = e // self.ep_size
        f_local = self.mlp_dim // self.tp_size
        if self.mlp_dim % self.tp_size:
            raise ValueError(
                f"mlp_dim {self.mlp_dim} not divisible by tp_size "
                f"{self.tp_size}"
            )
        x_flat = x.reshape(t, d)

        router = nn.Dense(e, use_bias=False, dtype=jnp.float32, name="router")
        logits = router(x_flat.astype(jnp.float32))
        capacity = max(math.ceil(self.capacity_factor * self.top_k * t / e), 1)
        dispatch, combine, aux, stats = topk_dispatch(
            logits, capacity, k=self.top_k
        )
        self.sow("aux_loss", "moe", self.aux_loss_weight * aux)
        # Observability: capacity drops are otherwise silent (a dropped
        # token's block output is just the residual). The LM step reports
        # the mean over layers/shards as metrics["moe_dropped_frac"].
        self.sow("moe_stats", "dropped_frac", stats["dropped_frac"])

        # Parameters keep GLOBAL shapes (placement shards them: expert dim
        # over the data axis for EP, hidden dim over the model axis for
        # TP); under shard_map flax sees the LOCAL slices.
        w_up = self.param(
            "w_up",
            nn.initializers.variance_scaling(2.0, "fan_in", "truncated_normal"),
            (e_local, d, f_local),
        )
        w_down = self.param(
            "w_down",
            nn.initializers.variance_scaling(2.0, "fan_in", "truncated_normal"),
            (e_local, f_local, d),
        )

        # [T, E, C] × [T, D] → per-expert buffers [E, C, D]
        expert_in = jnp.einsum(
            "tec,td->ecd", dispatch.astype(self.dtype), x_flat.astype(self.dtype)
        )

        if self.expert_axis and self.ep_size > 1:
            # Ship each expert's buffer to its owner: [E, C, D] →
            # [ep, E_local, C, D], exchange over the axis, gather the ep
            # source chunks along capacity.
            xe = expert_in.reshape(self.ep_size, e_local, capacity, d)
            xe = jax.lax.all_to_all(
                xe, self.expert_axis, split_axis=0, concat_axis=0, tiled=False
            )  # [ep(src), E_local, C, D]
            xe = jnp.moveaxis(xe, 0, 1).reshape(e_local, self.ep_size * capacity, d)
        else:
            xe = expert_in  # [E(=E_local), C, D]

        # Megatron split inside each expert: w_up column-parallel (local
        # hidden slice), w_down row-parallel — the partial outputs sum over
        # the model axis with ONE psum. The f/g custom-VJP pair keeps the
        # backward exact: tp_copy (identity fwd, psum bwd) guards the
        # replicated input of the column-parallel matmul, tp_reduce (psum
        # fwd, identity bwd) combines the row-parallel partials.
        if self.model_axis and self.tp_size > 1:
            from pytorch_distributed_tpu.parallel.tensor import tp_copy

            xe = tp_copy(xe, self.model_axis)
        h = jnp.einsum("ecd,edf->ecf", xe, w_up.astype(self.dtype))
        h = nn.gelu(h)
        ye = jnp.einsum("ecf,efd->ecd", h, w_down.astype(self.dtype))
        if self.model_axis and self.tp_size > 1:
            from pytorch_distributed_tpu.parallel.tensor import tp_reduce

            ye = tp_reduce(ye, self.model_axis)

        if self.expert_axis and self.ep_size > 1:
            ye = ye.reshape(e_local, self.ep_size, capacity, d)
            ye = jnp.moveaxis(ye, 1, 0)  # [ep(src), E_local, C, D]
            ye = jax.lax.all_to_all(
                ye, self.expert_axis, split_axis=0, concat_axis=0, tiled=False
            )  # back at the token owner: [ep(dest), E_local, C, D]
            ye = ye.reshape(e, capacity, d)

        out = jnp.einsum("tec,ecd->td", combine.astype(self.dtype), ye)
        return out.reshape(b, l, d)
