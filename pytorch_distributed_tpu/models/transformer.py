"""Causal transformer LM, designed for sequence parallelism from the start.

The reference has no attention model (SURVEY.md §5: long-context ABSENT);
this is the framework's long-context workhorse. TPU-first choices:

- the module computes on a *local sequence shard*: every position-dependent
  op (positional embedding, causal mask) takes a ``position_offset``, so the
  same module runs unsharded (offset 0) or under ``shard_map`` with the
  sequence split over the ``seq`` mesh axis — where ``attention="ring"``
  makes each block attend globally via ``parallel.sequence.ring_attention``;
- pre-LN blocks, GELU MLP, learned positional embeddings; LayerNorm/softmax
  statistics in fp32, matmuls in the configured compute dtype (bf16 on MXU);
- ``attention="blockwise"`` gives O(L·block) memory single-device attention
  (``ops.attention.blockwise_attention``) for long context without a mesh;
- no data-dependent Python control flow: one XLA program per shape.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from pytorch_distributed_tpu.ops.attention import (
    blockwise_attention,
    dense_attention,
)
from pytorch_distributed_tpu.parallel.mesh import SEQ_AXIS


@dataclasses.dataclass(unsafe_hash=True)
class TransformerConfig:
    vocab_size: int = 32000
    num_layers: int = 12
    num_heads: int = 12
    embed_dim: int = 768
    mlp_ratio: int = 4
    max_seq_len: int = 2048
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16
    attention: str = "dense"  # dense | blockwise | ring
    block_size: int = 512  # kv block for blockwise attention
    seq_axis: str = SEQ_AXIS  # mesh axis for attention="ring"


class Attention(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, x, position_offset):
        cfg = self.config
        b, l, e = x.shape
        head_dim = e // cfg.num_heads
        qkv = nn.DenseGeneral(
            (3, cfg.num_heads, head_dim), dtype=cfg.dtype, name="qkv"
        )(x)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # [B, L, H, D]

        if cfg.attention == "ring":
            from pytorch_distributed_tpu.parallel.sequence import ring_attention

            # The kernel derives each shard's position as base + index*L;
            # recover the document base from the caller's absolute offset so
            # any position_offset convention stays consistent with the mask.
            base = position_offset - jax.lax.axis_index(cfg.seq_axis) * l
            out = ring_attention(
                q, k, v, axis=cfg.seq_axis, causal=True, base_offset=base
            )
        elif cfg.attention == "blockwise":
            out = blockwise_attention(
                q, k, v, causal=True, block_size=min(cfg.block_size, l),
                q_offset=position_offset, k_offset=position_offset,
            )
        elif cfg.attention == "dense":
            out = dense_attention(
                q, k, v, causal=True,
                q_offset=position_offset, k_offset=position_offset,
            )
        else:
            raise ValueError(f"unknown attention {self.config.attention!r}")
        return nn.DenseGeneral(e, axis=(-2, -1), dtype=cfg.dtype, name="proj")(out)


class Block(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, x, position_offset):
        cfg = self.config
        h = nn.LayerNorm(dtype=jnp.float32, name="ln1")(x)
        x = x + Attention(cfg, name="attn")(h, position_offset)
        h = nn.LayerNorm(dtype=jnp.float32, name="ln2")(x)
        h = nn.Dense(cfg.embed_dim * cfg.mlp_ratio, dtype=cfg.dtype, name="mlp_up")(h)
        h = nn.gelu(h)
        h = nn.Dense(cfg.embed_dim, dtype=cfg.dtype, name="mlp_down")(h)
        return x + h


class TransformerLM(nn.Module):
    """Decoder-only LM over a (possibly sharded) token sequence.

    ``__call__(tokens [B, L_local], position_offset)`` → logits
    ``[B, L_local, vocab]`` (fp32). With attention="ring" this must run
    under shard_map on a mesh whose ``seq`` axis shards the length.
    """

    config: TransformerConfig

    @nn.compact
    def __call__(self, tokens, position_offset: jax.Array | int = 0, train: bool = True):
        cfg = self.config
        del train  # dropout-free for now; signature parity with ResNet
        x = nn.Embed(cfg.vocab_size, cfg.embed_dim, dtype=cfg.dtype, name="wte")(tokens)
        pos = position_offset + jnp.arange(tokens.shape[1])
        x = x + nn.Embed(cfg.max_seq_len, cfg.embed_dim, dtype=cfg.dtype, name="wpe")(pos)
        for i in range(cfg.num_layers):
            x = Block(cfg, name=f"block{i}")(x, position_offset)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
        logits = nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype, name="lm_head")(x)
        return logits.astype(jnp.float32)


def tiny_config(**overrides) -> TransformerConfig:
    """Small config for tests/CI."""
    defaults = dict(
        vocab_size=128, num_layers=2, num_heads=2, embed_dim=32,
        max_seq_len=256, dtype=jnp.float32,
    )
    defaults.update(overrides)
    return TransformerConfig(**defaults)
