"""Causal transformer LM, designed for sequence parallelism from the start.

The reference has no attention model (SURVEY.md §5: long-context ABSENT);
this is the framework's long-context workhorse. TPU-first choices:

- the module computes on a *local sequence shard*: every position-dependent
  op (positional embedding, causal mask) takes a ``position_offset``, so the
  same module runs unsharded (offset 0) or under ``shard_map`` with the
  sequence split over the ``seq`` mesh axis — where ``attention="ring"``
  makes each block attend globally via ``parallel.sequence.ring_attention``;
- pre-LN blocks, GELU MLP, learned positional embeddings; LayerNorm/softmax
  statistics in fp32, matmuls in the configured compute dtype (bf16 on MXU);
- ``attention="blockwise"`` gives O(L·block) memory single-device attention
  (``ops.attention.blockwise_attention``) for long context without a mesh;
- no data-dependent Python control flow: one XLA program per shape.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from pytorch_distributed_tpu.ops.attention import (
    blockwise_attention,
    dense_attention,
)
from pytorch_distributed_tpu.parallel.mesh import SEQ_AXIS


@dataclasses.dataclass(unsafe_hash=True)
class TransformerConfig:
    vocab_size: int = 32000
    num_layers: int = 12
    num_heads: int = 12
    embed_dim: int = 768
    mlp_ratio: int = 4
    max_seq_len: int = 2048
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16
    attention: str = "dense"  # dense | blockwise | flash | ring | ring_flash
    block_size: int = 512  # kv block for blockwise attention
    seq_axis: str = SEQ_AXIS  # mesh axis for attention="ring"
    # Grouped-query attention: K/V get num_kv_heads heads (must divide
    # num_heads), each shared by a GROUP of num_heads/num_kv_heads query
    # heads — the KV decode cache and the kv projection shrink by the
    # group factor (the Llama-family serving-memory trade). None = MHA
    # with the fused qkv projection (checkpoint layout unchanged); GQA
    # uses separate "q"/"kv" projections. K/V repeat to full heads at
    # compute, so every attention path (dense/flash/ring/...) is
    # unchanged downstream.
    num_kv_heads: Optional[int] = None
    # Position encoding: "learned" (GPT-2-style wpe table, the default)
    # or "rope" (rotary embeddings applied to q/k INSIDE attention — no
    # wpe parameter, unbounded-length friendly). Rotation happens before
    # any attention path runs, with each token's ABSOLUTE position baked
    # in — so ring/zigzag/flash/decode all inherit it unchanged (K is
    # rotated before it travels the ring, and the KV cache stores
    # rotated keys).
    pos_embedding: str = "learned"
    rope_theta: float = 10000.0
    # Ring shard layout: "contiguous" (shard i = tokens [i*L, (i+1)*L)) or
    # "zigzag" (shard i = chunks (i, 2s-1-i) — balances the causal ring's
    # critical path, halving the max per-rank block area at sp=8;
    # ops/ring_flash.py). Zigzag batches must be host-permuted with
    # parallel.sequence.zigzag_shard (train.lm_trainer.shard_lm_batch does
    # it from this flag) and wpe positions follow the chunk map (the LM
    # steps pass a position VECTOR).
    ring_layout: str = "contiguous"
    # Megatron-style tensor parallelism: set model_axis to the mesh's model
    # axis name and tp_size to its size when running under shard_map with
    # params sharded by ``train.lm.TRANSFORMER_TP_RULES``. Parameters keep
    # GLOBAL shapes in the state (sharding is placement; checkpoints are
    # interchangeable across tp degrees); tp_size tells the module the LOCAL
    # feature widths flax should expect at apply time. None/1 = no TP.
    model_axis: Optional[str] = None
    tp_size: int = 1
    # Megatron vocab parallelism (round 5): shard the wte embedding's and
    # lm_head's VOCAB dim over the model axis. The embedding does a
    # masked local lookup psum'd across shards; the loss tail feeds the
    # LOCAL head shard to the fused CE's cross-shard logsumexp
    # (ops/fused_ce.py vocab_axis); the logits path (generate/eval
    # fallback) all_gathers the vocab dim. Cuts the lm_head+wte param,
    # grad, and optimizer memory — and the fused-CE block compute — by
    # tp. Effective only when model_axis/tp_size are set, like every
    # other TP switch; parameters keep GLOBAL shapes in the state.
    vocab_parallel: bool = False
    # Mixture-of-Experts (models/moe.py): n_experts > 0 replaces the dense
    # MLP with a Switch-style MoE in every ``moe_every``-th block. Expert
    # parallelism rides the data axis: set expert_axis/ep_size to the mesh's
    # data axis name/size (weights stay global-shaped; placement shards
    # them, like TP).
    n_experts: int = 0
    moe_every: int = 2
    capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    moe_top_k: int = 1  # 1 = Switch, 2 = GShard top-2
    expert_axis: Optional[str] = None
    ep_size: int = 1
    # Paged-serving KV gather spelling (ops.attention.paged_attention):
    # "dense" = jnp.take-over-blocks (gathered KV materializes in HBM),
    # "pallas" = the fused block-gather kernel (ops/paged_flash.py —
    # block tables read by BlockSpec index maps, online softmax in VMEM;
    # interpret mode off-TPU). Only the block_tables= serving path reads
    # it; training/dense-decode configs ignore it. Serving constructors
    # (PagedEngine/Scheduler/ContinuousBatcher gather_impl=) replace it
    # into the config, which also folds it into the registry run
    # fingerprint.
    gather_impl: str = "dense"
    # Flash-decoding split (ops.paged_flash, round 20): the pallas
    # gather's chain sweep splits across this many grid workers with a
    # cross-worker log-sum-exp merge. None = auto (split when W/B
    # crosses ops.paged_flash.SPLIT_THRESHOLD), 1 = single-worker
    # sweep, S > 1 = forced. Serving constructors replace it into the
    # config (split_s=) like gather_impl, so the registry fingerprint
    # keys the program shape; dense gathers and training ignore it.
    split_s: Optional[int] = None

    def __post_init__(self):
        if self.ring_layout not in ("contiguous", "zigzag"):
            raise ValueError(
                f"ring_layout {self.ring_layout!r} must be 'contiguous' or "
                "'zigzag'"
            )
        if self.ring_layout == "zigzag" and self.attention not in (
            "ring", "ring_flash"
        ):
            raise ValueError(
                f"ring_layout='zigzag' only applies to ring attention "
                f"(got attention={self.attention!r}); the layout is a "
                "causal-ring scheduling balance, meaningless elsewhere"
            )
        if self.n_experts and self.n_experts % self.ep_size:
            raise ValueError(
                f"n_experts {self.n_experts} not divisible by ep_size {self.ep_size}"
            )
        if self.n_experts and not 1 <= self.moe_top_k <= self.n_experts:
            raise ValueError(
                f"moe_top_k {self.moe_top_k} must be in [1, n_experts="
                f"{self.n_experts}]"
            )
        if self.embed_dim % self.num_heads:
            raise ValueError(
                f"embed_dim {self.embed_dim} not divisible by num_heads {self.num_heads}"
            )
        if self.num_heads % self.tp_size:
            raise ValueError(
                f"num_heads {self.num_heads} not divisible by tp_size {self.tp_size}"
            )
        if self.pos_embedding not in ("learned", "rope"):
            raise ValueError(
                f"pos_embedding {self.pos_embedding!r} must be 'learned' "
                "or 'rope'"
            )
        if self.pos_embedding == "rope" and (self.embed_dim
                                             // self.num_heads) % 2:
            raise ValueError(
                f"rope needs an even head_dim, got "
                f"{self.embed_dim // self.num_heads}"
            )
        if self.rope_theta <= 0.0:
            raise ValueError(
                f"rope_theta must be > 0, got {self.rope_theta}"
            )
        if self.num_kv_heads is not None:
            if self.num_kv_heads < 1:
                raise ValueError(
                    f"num_kv_heads must be >= 1, got {self.num_kv_heads}"
                )
            if self.num_heads % self.num_kv_heads:
                raise ValueError(
                    f"num_heads {self.num_heads} not divisible by "
                    f"num_kv_heads {self.num_kv_heads}"
                )
            if self.num_kv_heads % self.tp_size:
                raise ValueError(
                    f"num_kv_heads {self.num_kv_heads} not divisible by "
                    f"tp_size {self.tp_size} (each TP rank needs whole KV "
                    "heads)"
                )
        if self.vocab_parallel and self.vocab_size % self.tp_size:
            raise ValueError(
                f"vocab_size {self.vocab_size} not divisible by tp_size "
                f"{self.tp_size} (vocab_parallel shards the vocab dim)"
            )
        if self.tp_size > 1 and self.model_axis is None:
            raise ValueError(
                f"tp_size {self.tp_size} > 1 requires model_axis: without "
                "the axis name the TP collectives are skipped and the model "
                "silently trains with thin local shards"
            )
        if (self.embed_dim * self.mlp_ratio) % self.tp_size:
            raise ValueError(
                f"mlp width {self.embed_dim * self.mlp_ratio} not divisible "
                f"by tp_size {self.tp_size}"
            )
        if not 0.0 <= self.dropout < 1.0:
            raise ValueError(f"dropout must be in [0, 1), got {self.dropout}")
        if self.gather_impl not in ("dense", "pallas"):
            raise ValueError(
                f"gather_impl {self.gather_impl!r} must be 'dense' or "
                "'pallas' (ops.attention.paged_attention spellings)"
            )
        if self.split_s is not None and (
            not isinstance(self.split_s, int) or self.split_s < 1
        ):
            raise ValueError(
                f"split_s {self.split_s!r} must be None (auto) or an "
                "int >= 1 (flash-decoding worker count; ops.paged_flash)"
            )

    def uses_vocab_parallel(self) -> bool:
        """THE vocab-parallel predicate — the one place the condition
        lives. The model's head/embedding branch, the TP placement rules
        (``train/lm.py``), and the serving rule builder
        (``models/generate.py``) all call this, so they cannot diverge on
        edge cases (e.g. ``model_axis`` set with ``tp_size == 1``, where
        sharding the vocab dim would be vacuous but the collective branch
        is not free)."""
        return (
            self.vocab_parallel
            and self.model_axis is not None
            and self.tp_size > 1
        )


def _rope_rotate(x, positions, theta: float):
    """Rotary embedding on ``x`` [B, L, H, D] at absolute ``positions``
    ([1, L] shared or [B, L] per-request), interleaved-pair convention.
    fp32 trig regardless of compute dtype."""
    d = x.shape[-1]
    inv = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    ang = positions.astype(jnp.float32)[..., None] * inv  # [B?, L, D/2]
    cos = jnp.cos(ang)[:, :, None, :]  # [B?, L, 1, D/2] broadcasts over H
    sin = jnp.sin(ang)[:, :, None, :]
    x1, x2 = x[..., ::2].astype(jnp.float32), x[..., 1::2].astype(jnp.float32)
    out = jnp.stack([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.reshape(x.shape).astype(x.dtype)


class Attention(nn.Module):
    config: TransformerConfig
    deterministic: bool = True
    decode: bool = False
    prefill: bool = False

    @nn.compact
    def __call__(self, x, position_offset, positions=None,
                 block_tables=None):
        cfg = self.config
        b, l, e = x.shape
        head_dim = e // cfg.num_heads
        if cfg.model_axis:
            from pytorch_distributed_tpu.parallel.tensor import tp_copy

            x = tp_copy(x, cfg.model_axis)  # column-parallel qkv below
        heads_local = cfg.num_heads // cfg.tp_size
        if cfg.num_kv_heads is None:
            qkv = nn.DenseGeneral(
                (3, heads_local, head_dim), dtype=cfg.dtype, name="qkv"
            )(x)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # [B,L,H,D]
            kv_group = 1
        else:
            # GQA: separate projections; K/V carry num_kv_heads heads —
            # the cache below inherits the narrow head count (the serving
            # memory win), and compute repeats to full heads afterwards.
            kv_heads_local = cfg.num_kv_heads // cfg.tp_size
            kv_group = heads_local // kv_heads_local
            q = nn.DenseGeneral(
                (heads_local, head_dim), dtype=cfg.dtype, name="q"
            )(x)
            kv = nn.DenseGeneral(
                (2, kv_heads_local, head_dim), dtype=cfg.dtype, name="kv"
            )(x)
            k, v = kv[:, :, 0], kv[:, :, 1]  # [B, L, H_kv_loc, D]

        if cfg.pos_embedding == "rope":
            # Rotate BEFORE the cache write and before any attention path
            # runs: absolute positions are baked into q/k, so the ring
            # variants ship pre-rotated keys and the cache stores rotated
            # keys — downstream stays position-agnostic. The positions
            # are RESOLVED by the caller (TransformerLM / PPStage) — one
            # source of truth, never re-derived here where it could drift
            # from the wpe/cache-write/mask convention.
            if positions is None:
                raise ValueError(
                    "pos_embedding='rope' needs the resolved positions= "
                    "array ([L] shared or [B, L] per-request); "
                    "TransformerLM and train.pp.PPStage provide it"
                )
            rpos = positions[None] if positions.ndim == 1 else positions
            q = _rope_rotate(q, rpos, cfg.rope_theta)
            k = _rope_rotate(k, rpos, cfg.rope_theta)

        if block_tables is not None:
            # Paged serving (serving/): the cache is a block POOL
            # [n_blocks, block_len, H_kv, D] shared by every request, and
            # this request's logical positions map to pool blocks through
            # its block-table row. One path serves BOTH chunked prefill
            # (l == chunk) and decode (l == 1): write the chunk at its
            # absolute positions, then attend against the gathered chain —
            # which includes the chunk just written, so intra-chunk
            # causality falls out of the same mask as cross-chunk.
            # ``position_offset`` stays the single source of position
            # truth: the block/offset write indices, the attention mask,
            # and the positional embedding all derive from the same [B]
            # start vector.
            if not (self.decode or self.prefill):
                raise ValueError(
                    "block_tables= is the paged SERVING cache layout; it "
                    "requires decode or prefill mode"
                )
            from pytorch_distributed_tpu.ops.attention import paged_attention

            def _need_pool(*_a):
                raise ValueError(
                    "paged attention needs the pool cache passed in "
                    "(apply with {'cache': serving.kv_pool.init_paged_"
                    "cache(...)}); there is no in-module init for it"
                )

            kv_heads = k.shape[2]
            ck = self.variable("cache", "key", _need_pool)
            cv = self.variable("cache", "value", _need_pool)
            block_len = ck.value.shape[1]
            pos = jnp.asarray(position_offset, jnp.int32)
            if pos.ndim != 1:
                raise ValueError(
                    "paged mode takes a [B] position_offset vector (each "
                    "request's write start), got a scalar"
                )
            p = pos[:, None] + jnp.arange(l)  # [B, l] absolute positions
            blk = jnp.take_along_axis(block_tables, p // block_len, axis=1)
            off = p % block_len
            # Scatter the chunk into the pool. Index pairs are unique per
            # request (each owns its blocks); the engine routes inactive
            # slots' writes to the trash block, where duplicate hits are
            # harmless garbage.
            from pytorch_distributed_tpu.serving.kv_pool import (
                is_quantized_pool,
            )

            if is_quantized_pool(ck.value.dtype):
                # quantized pool (serving.kv_pool kv_dtype="int8"/
                # "fp8"/"fp8_e5m2"): quantize-on-scatter — each written
                # KV row stores quantized values plus its per-head scale
                # (fp32 multiplier for int8, int8 exponent for fp8) in
                # the scale siblings, at the same (block, offset)
                # indices. The read path below dequantizes (in-VMEM for
                # the pallas spelling). Intra-chunk attention therefore
                # also reads quantized KV — the same values every later
                # chunk and decode tick will see, so the stream has ONE
                # consistent quantization, not an exact-then-quantized
                # seam. With gather_impl="pallas" the scatter fuses too
                # (ops.paged_flash.paged_quantize_scatter computes the
                # scales inside the write); the jnp spelling below is
                # the dense/interpret reference — both call
                # kv_pool.quantize_rows, so the pools are bit-identical
                # across spellings.
                cks = self.variable("cache", "key_scale", _need_pool)
                cvs = self.variable("cache", "value_scale", _need_pool)
                if cfg.gather_impl == "pallas":
                    from pytorch_distributed_tpu.ops.paged_flash import (
                        paged_quantize_scatter,
                    )

                    (ck.value, cv.value, cks.value,
                     cvs.value) = paged_quantize_scatter(
                        k, v, blk, off, ck.value, cv.value,
                        cks.value, cvs.value,
                    )
                else:
                    from pytorch_distributed_tpu.serving.kv_pool import (
                        quantize_kv,
                    )

                    kq, ks_rows = quantize_kv(k, ck.value.dtype)
                    vq, vs_rows = quantize_kv(v, cv.value.dtype)
                    rows = (blk.reshape(-1), off.reshape(-1))
                    ck.value = ck.value.at[rows].set(
                        kq.reshape(b * l, kv_heads, head_dim)
                    )
                    cv.value = cv.value.at[rows].set(
                        vq.reshape(b * l, kv_heads, head_dim)
                    )
                    cks.value = cks.value.at[rows].set(
                        ks_rows.reshape(b * l, kv_heads)
                    )
                    cvs.value = cvs.value.at[rows].set(
                        vs_rows.reshape(b * l, kv_heads)
                    )
                out = paged_attention(
                    q, ck.value, cv.value, block_tables, p,
                    gather_impl=cfg.gather_impl, split_s=cfg.split_s,
                    k_scale=cks.value, v_scale=cvs.value,
                )
            else:
                ck.value = ck.value.at[blk.reshape(-1), off.reshape(-1)].set(
                    k.astype(cfg.dtype).reshape(b * l, kv_heads, head_dim)
                )
                cv.value = cv.value.at[blk.reshape(-1), off.reshape(-1)].set(
                    v.astype(cfg.dtype).reshape(b * l, kv_heads, head_dim)
                )
                out = paged_attention(
                    q, ck.value, cv.value, block_tables, p,
                    gather_impl=cfg.gather_impl, split_s=cfg.split_s,
                )
            out = nn.DenseGeneral(
                e, axis=(-2, -1), use_bias=False, dtype=cfg.dtype,
                name="proj",
            )(out)
            if cfg.model_axis:
                from pytorch_distributed_tpu.parallel.tensor import tp_reduce

                out = tp_reduce(out, cfg.model_axis)
            return out

        if self.decode or self.prefill:
            # KV cache. ``position_offset`` is the single source of
            # position truth — the write index, the attention mask, AND
            # the positional embedding all derive from it, so they cannot
            # silently disagree (no per-layer counter to drift). In decode
            # mode it may be a PER-REQUEST [B] vector (ragged serving:
            # each request writes its own cache slot).
            max_len = cfg.max_seq_len
            kv_heads = k.shape[2]  # H_kv_local under GQA, H_local for MHA
            ck = self.variable(
                "cache", "key",
                lambda: jnp.zeros((b, max_len, kv_heads, head_dim), cfg.dtype),
            )
            cv = self.variable(
                "cache", "value",
                lambda: jnp.zeros((b, max_len, kv_heads, head_dim), cfg.dtype),
            )
            pos = jnp.asarray(position_offset, jnp.int32)
            if self.decode and pos.ndim == 1:
                # per-request slot write (l == 1, asserted below)
                rows = jnp.arange(b)
                ck.value = ck.value.at[rows, pos].set(
                    k[:, 0].astype(cfg.dtype)
                )
                cv.value = cv.value.at[rows, pos].set(
                    v[:, 0].astype(cfg.dtype)
                )
            else:
                ck.value = jax.lax.dynamic_update_slice(
                    ck.value, k.astype(cfg.dtype), (0, pos, 0, 0)
                )
                cv.value = jax.lax.dynamic_update_slice(
                    cv.value, v.astype(cfg.dtype), (0, pos, 0, 0)
                )

        if self.decode:
            # Single-token step attending against the cache (O(L) per
            # token); parity vs the full causal forward is tested in
            # tests/test_generate.py.
            assert l == 1, f"decode mode processes one token/step, got {l}"
            pos = jnp.asarray(position_offset, jnp.int32)
            pos_b = pos if pos.ndim == 1 else jnp.full((b,), pos)
            scale = head_dim**-0.5
            if kv_group > 1:
                # GQA decode: grouped einsum directly against the NARROW
                # cache — no widened K/V tensor ever materializes, so the
                # decode memory traffic (the bottleneck GQA targets)
                # really is 1/group of MHA's. Query head qh maps to
                # narrow head qh // group, matching the repeat layout
                # the train path uses.
                qg = (q.astype(jnp.float32) * scale).reshape(
                    b, 1, kv_heads, kv_group, head_dim
                )
                s = jnp.einsum(
                    "bqhgd,bkhd->bhgqk", qg,
                    ck.value.astype(jnp.float32),
                )  # [B, H_kv, G, 1, max_len]
                mask = (jnp.arange(cfg.max_seq_len)[None, None, None, None]
                        <= pos_b[:, None, None, None, None])
                s = jnp.where(mask, s, -1e30)
                p = jax.nn.softmax(s, axis=-1)
                out = jnp.einsum(
                    "bhgqk,bkhd->bqhgd", p, cv.value.astype(jnp.float32)
                ).reshape(b, 1, heads_local, head_dim).astype(cfg.dtype)
            else:
                s = jnp.einsum(
                    "bqhd,bkhd->bhqk", q.astype(jnp.float32) * scale,
                    ck.value.astype(jnp.float32),
                )  # [B, H, 1, max_len]
                mask = (jnp.arange(cfg.max_seq_len)[None, None, None, :]
                        <= pos_b[:, None, None, None])
                s = jnp.where(mask, s, -1e30)
                p = jax.nn.softmax(s, axis=-1)
                out = jnp.einsum(
                    "bhqk,bkhd->bqhd", p, cv.value.astype(jnp.float32)
                ).astype(cfg.dtype)
            out = nn.DenseGeneral(
                e, axis=(-2, -1), use_bias=False, dtype=cfg.dtype, name="proj"
            )(out)
            if cfg.model_axis:
                from pytorch_distributed_tpu.parallel.tensor import tp_reduce

                out = tp_reduce(out, cfg.model_axis)
            return out
        # prefill falls through: one BATCHED causal forward over the prompt
        # (the cache write above is its only side effect)

        if kv_group > 1:
            # GQA: widen K/V to the full head count for the attention
            # paths below — they all see plain MHA shapes (the cache
            # above already stored the NARROW heads; this is compute-side
            # only)
            k = jnp.repeat(k, kv_group, axis=2)
            v = jnp.repeat(v, kv_group, axis=2)

        if cfg.attention == "ring":
            from pytorch_distributed_tpu.parallel.sequence import ring_attention

            if cfg.ring_layout == "zigzag":
                # zigzag derives chunk positions from the ring index with
                # a document-rooted convention; the trainer feeds wpe a
                # matching position VECTOR (train/lm.py) and batches are
                # host-permuted, so base is 0 here.
                out = ring_attention(
                    q, k, v, axis=cfg.seq_axis, causal=True, layout="zigzag"
                )
            else:
                # The kernel derives each shard's position as
                # base + index*L; recover the document base from the
                # caller's absolute offset so any position_offset
                # convention stays consistent with the mask.
                base = position_offset - jax.lax.axis_index(cfg.seq_axis) * l
                out = ring_attention(
                    q, k, v, axis=cfg.seq_axis, causal=True, base_offset=base
                )
        elif cfg.attention == "ring_flash":
            from pytorch_distributed_tpu.ops.ring_flash import (
                ring_flash_attention,
            )

            # Same ring schedule, Pallas flash kernels per visiting shard
            # (ops/ring_flash.py). Causal structure comes from ring
            # positions, which is exact for any uniform position offset.
            # Blocks must DIVIDE the kernel's working length — the shard
            # under the contiguous layout, a HALF-shard chunk under zigzag
            # — and should stay lane-aligned: prefer the largest
            # 128-multiple divisor within block_size; small shards run as
            # one block; anything else (e.g. L_local=250) is rejected
            # rather than silently degenerating to tiny unaligned blocks.
            zig = cfg.ring_layout == "zigzag"
            lw = l // 2 if zig else l
            limit = min(cfg.block_size, lw)
            blk = max(
                (c for c in range(128, limit + 1, 128) if lw % c == 0),
                default=None,
            )
            if blk is None and lw <= limit and (lw < 128 or lw % 8 == 0):
                blk = lw  # single-block shard (small/test shapes)
            if blk is None:
                raise ValueError(
                    f"ring_flash: no usable block size for working length "
                    f"{lw} (block_size {cfg.block_size}); pad the sequence "
                    "so it has a 128-multiple divisor, or use "
                    "attention='ring'"
                )
            out = ring_flash_attention(
                q, k, v, axis=cfg.seq_axis, causal=True,
                block_q=blk, block_k=blk, layout=cfg.ring_layout,
            )
        elif cfg.attention == "blockwise":
            out = blockwise_attention(
                q, k, v, causal=True, block_size=min(cfg.block_size, l),
                q_offset=position_offset, k_offset=position_offset,
            )
        elif cfg.attention == "flash":
            from pytorch_distributed_tpu.ops.flash_attention import flash_attention

            # Pallas kernel path. The kernel masks from position 0, which is
            # exact for any equal-offset self-attention: the causal
            # predicate (k_off + j <= q_off + i) is offset-invariant when
            # q_off == k_off, as it is here.
            out = flash_attention(q, k, v, causal=True)
        elif cfg.attention == "dense":
            out = dense_attention(
                q, k, v, causal=True,
                q_offset=position_offset, k_offset=position_offset,
            )
        else:
            raise ValueError(f"unknown attention {self.config.attention!r}")
        # Row-parallel output projection: bias-free so the TP psum does not
        # add the bias tp times.
        out = nn.DenseGeneral(
            e, axis=(-2, -1), use_bias=False, dtype=cfg.dtype, name="proj"
        )(out)
        if cfg.model_axis:
            from pytorch_distributed_tpu.parallel.tensor import tp_reduce

            out = tp_reduce(out, cfg.model_axis)
        # Residual dropout AFTER tp_reduce: activations here are replicated
        # across the model axis, and the step derives the dropout rng from
        # (seed, step, data/seq coords) only — model-axis replicas see the
        # same mask and stay bitwise identical (train/lm.py rng plumbing).
        if cfg.dropout:
            out = nn.Dropout(cfg.dropout, deterministic=self.deterministic)(out)
        return out


class Block(nn.Module):
    config: TransformerConfig
    use_moe: bool = False
    deterministic: bool = True
    decode: bool = False
    prefill: bool = False

    @nn.compact
    def __call__(self, x, position_offset, positions=None,
                 block_tables=None):
        cfg = self.config
        h = nn.LayerNorm(dtype=jnp.float32, name="ln1")(x)
        x = x + Attention(
            cfg, deterministic=self.deterministic, decode=self.decode,
            prefill=self.prefill, name="attn",
        )(h, position_offset, positions, block_tables)
        h = nn.LayerNorm(dtype=jnp.float32, name="ln2")(x)
        if self.use_moe:
            from pytorch_distributed_tpu.models.moe import MoEMLP

            out = MoEMLP(
                n_experts=cfg.n_experts,
                mlp_dim=cfg.embed_dim * cfg.mlp_ratio,
                capacity_factor=cfg.capacity_factor,
                aux_loss_weight=cfg.moe_aux_weight,
                top_k=cfg.moe_top_k,
                ep_size=cfg.ep_size,
                expert_axis=cfg.expert_axis,
                tp_size=cfg.tp_size,
                model_axis=cfg.model_axis,
                dtype=cfg.dtype,
                name="moe",
            )(h)
            if cfg.dropout:  # residual dropout, same placement as dense MLP
                out = nn.Dropout(cfg.dropout, deterministic=self.deterministic)(out)
            return x + out
        if cfg.model_axis:
            from pytorch_distributed_tpu.parallel.tensor import tp_copy, tp_reduce

            h = tp_copy(h, cfg.model_axis)  # column-parallel mlp_up
        h = nn.Dense(
            cfg.embed_dim * cfg.mlp_ratio // cfg.tp_size, dtype=cfg.dtype,
            name="mlp_up",
        )(h)
        h = nn.gelu(h)
        # Row-parallel mlp_down: bias-free (see Attention.proj).
        h = nn.Dense(cfg.embed_dim, use_bias=False, dtype=cfg.dtype, name="mlp_down")(h)
        if cfg.model_axis:
            h = tp_reduce(h, cfg.model_axis)
        if cfg.dropout:  # after tp_reduce — see Attention
            h = nn.Dropout(cfg.dropout, deterministic=self.deterministic)(h)
        return x + h


class TransformerLM(nn.Module):
    """Decoder-only LM over a (possibly sharded) token sequence.

    ``__call__(tokens [B, L_local], position_offset)`` → logits
    ``[B, L_local, vocab]`` (fp32). With attention="ring" this must run
    under shard_map on a mesh whose ``seq`` axis shards the length.
    """

    config: TransformerConfig

    @nn.compact
    def __call__(self, tokens, position_offset: jax.Array | int = 0,
                 train: bool = True, decode: bool = False,
                 prefill: bool = False, positions: jax.Array | None = None,
                 return_hidden: bool = False,
                 block_tables: jax.Array | None = None):
        cfg = self.config
        # Dropout is active only when train=True AND an rng is provided
        # (apply(..., rngs={"dropout": key}) — train/lm.py derives the key
        # from (seed, step, shard coords) so resumed runs are bit-identical).
        inference = decode or prefill
        deterministic = not (train and cfg.dropout > 0.0) or inference
        vp = cfg.uses_vocab_parallel()  # THE shared predicate — train/lm.py
        # and models/generate.py consult the same method, so the head/
        # embedding branch and the placement rules cannot diverge
        if vp:
            # Vocab-parallel embedding: each shard owns vocab rows
            # [r*V/tp, (r+1)*V/tp); out-of-range tokens look up a clipped
            # row, are zero-masked, and tp_reduce (psum forward, IDENTITY
            # backward — the Megatron g; a plain psum would transpose to
            # another psum and scale wte grads by tp) assembles the one
            # real row per token. The mask kills foreign rows'
            # cotangents, so each shard's wte grad lands only on the
            # rows it owns.
            from pytorch_distributed_tpu.parallel.tensor import tp_reduce

            v_loc = cfg.vocab_size // cfg.tp_size
            off = jax.lax.axis_index(cfg.model_axis) * v_loc
            loc = tokens - off
            ok = (loc >= 0) & (loc < v_loc)
            emb = nn.Embed(v_loc, cfg.embed_dim, dtype=cfg.dtype,
                           name="wte")(jnp.clip(loc, 0, v_loc - 1))
            x = tp_reduce(
                jnp.where(ok[..., None], emb, jnp.zeros((), emb.dtype)),
                cfg.model_axis,
            )
        else:
            x = nn.Embed(
                cfg.vocab_size, cfg.embed_dim, dtype=cfg.dtype, name="wte"
            )(tokens)
        # ``positions`` ([L_local] i32) overrides the contiguous
        # offset+arange convention — required for the zigzag ring layout,
        # whose shards hold non-contiguous chunk pairs (train/lm.py
        # computes the chunk-map vector). Refuse silently-wrong math: a
        # zigzag config with no position vector would embed contiguous
        # wpe positions for non-contiguous tokens.
        if cfg.ring_layout == "zigzag" and positions is None:
            raise ValueError(
                "ring_layout='zigzag' requires the per-shard position "
                "vector (positions=): shards hold chunk pairs "
                "(r, 2s-1-r), so offset+arange wpe positions are wrong. "
                "Use the LM train/eval steps (train/lm.py), which compute "
                "it, and shard batches with shard_lm_batch(..., "
                "layout='zigzag')."
            )
        off = jnp.asarray(position_offset, jnp.int32)
        if off.ndim == 1 and not (
            (decode and tokens.shape[1] == 1) or block_tables is not None
        ):
            raise ValueError(
                "a [B] position_offset vector is the ragged DECODE "
                "convention (one token per request) or the paged serving "
                "convention (block_tables= set); prefill/training use a "
                "scalar offset or positions="
            )
        # ONE resolution of per-token absolute positions, feeding BOTH
        # the learned wpe lookup and (passed down to every block) the
        # rope rotation — the two can never disagree. Shapes: [L] shared,
        # [B, L] per-request, [B, 1] ragged decode, or [B, chunk] paged
        # chunk prefill (each request's chunk at its own start).
        if positions is not None:
            pos = positions
        elif off.ndim == 1:
            # per-request start positions [B] (ragged/paged serving)
            pos = off[:, None] + jnp.arange(tokens.shape[1])
        else:
            pos = off + jnp.arange(tokens.shape[1])
        if cfg.pos_embedding == "learned":
            x = x + nn.Embed(
                cfg.max_seq_len, cfg.embed_dim, dtype=cfg.dtype, name="wpe"
            )(pos)
        # rope: no wpe table — Attention rotates q/k from the same pos
        if cfg.dropout and not inference:
            x = nn.Dropout(cfg.dropout, deterministic=deterministic)(x)
        for i in range(cfg.num_layers):
            use_moe = bool(cfg.n_experts) and (i % cfg.moe_every == cfg.moe_every - 1)
            x = Block(
                cfg, use_moe=use_moe, deterministic=deterministic,
                decode=decode, prefill=prefill, name=f"block{i}",
            )(x, position_offset, pos, block_tables)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
        head = nn.Dense(
            cfg.vocab_size // cfg.tp_size if vp else cfg.vocab_size,
            use_bias=False, dtype=cfg.dtype, name="lm_head",
        )
        if return_hidden:
            # Fused-CE path (ops/fused_ce.py): the caller streams the
            # lm_head matmul into a blockwise logsumexp using
            # params["lm_head"]["kernel"] directly — the full [B, L, V]
            # fp32 logits never materialize. CAUTION: flax creates params
            # only for CALLED submodules, so init must always take the
            # logits path below (it does: create_lm_state applies with the
            # default return_hidden=False); apply-time skipping merely
            # leaves the existing lm_head params unused, which flax
            # tolerates — checkpoint layout identical either way.
            return x
        if vp:
            # column-parallel head: replicated input, vocab-sharded
            # output — the f-operator (identity fwd, psum bwd) collects
            # each shard's dx contribution, exactly like qkv/mlp_up
            from pytorch_distributed_tpu.parallel.tensor import tp_copy

            x = tp_copy(x, cfg.model_axis)
        logits = head(x).astype(jnp.float32)
        if vp:
            # full logits for sampling/eval callers: concatenate the
            # vocab shards in axis order (matches the shard offsets).
            # tp_all_gather, not lax.all_gather: downstream losses are
            # replicated over the model axis, and the raw gather's
            # psum_scatter transpose would scale grads by tp.
            from pytorch_distributed_tpu.parallel.tensor import tp_all_gather

            logits = tp_all_gather(logits, cfg.model_axis, dim=-1)
        return logits


def tiny_config(**overrides) -> TransformerConfig:
    """Small config for tests/CI."""
    defaults = dict(
        vocab_size=128, num_layers=2, num_heads=2, embed_dim=32,
        max_seq_len=256, dtype=jnp.float32,
    )
    defaults.update(overrides)
    return TransformerConfig(**defaults)
