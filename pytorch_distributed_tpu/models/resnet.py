"""ResNet family (v1.5) in flax, TPU-first.

Capability parity with ``torchvision.models.resnet50`` as used by the
reference (``resnet_single_gpu.py:83``, ``restnet_ddp.py:98``): same
architecture (7x7 stem, [3,4,6,3] bottleneck stages, stride on the 3x3 conv
— the "v1.5" variant torchvision ships), same parameter count (25,557,032
for ResNet-50), same BatchNorm semantics (momentum 0.1 in torch convention =
0.9 decay here, eps 1e-5, per-replica statistics by default — matching DDP's
non-synced BN; pass ``bn_cross_replica_axis`` for sync-BN, which the
reference cannot do at all).

TPU-first choices:
- NHWC layout throughout (XLA:TPU's native conv layout; torchvision is NCHW).
- ``dtype`` is the *compute* dtype: pass ``jnp.bfloat16`` for mixed precision
  — parameters stay fp32, matmuls/convs run bf16 on the MXU, and the final
  logits are returned fp32 (replaces CUDA AMP autocast,
  ``resnet_ddp_apex.py:27-29``).
- Everything is a pure function of (params, batch_stats, inputs): jit/pjit
  compile the whole forward into one XLA program; no Python control flow
  depends on data.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

ModuleDef = Any

# torchvision's kaiming_normal_(mode='fan_out', nonlinearity='relu')
conv_kernel_init = nn.initializers.variance_scaling(2.0, "fan_out", "normal")


class Conv1x1(nn.Module):
    """Pointwise convolution expressed as a ``dot_general`` contraction.

    A 1x1 conv IS a matmul over the channel dim; lowering it as
    ``dot_general`` instead of ``conv_general_dilated`` steers XLA:TPU onto
    the MXU matmul emitters in both directions. Measured on v5e (see
    PERF_NOTES.md): exact output parity, but no step-time win — the full
    train step is HBM-bandwidth-bound, not conv-emitter-bound — so this
    stays an option (``ResNet.use_dot_1x1``), default off.

    Parameter shape and name match ``nn.Conv`` ((1, 1, Cin, Cout) under
    "kernel") so checkpoints are interchangeable with the conv formulation.
    """

    features: int
    strides: int = 1
    dtype: Any = jnp.float32
    kernel_init: Any = conv_kernel_init

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel", self.kernel_init, (1, 1, x.shape[-1], self.features), jnp.float32
        )
        if self.strides != 1:
            x = x[:, :: self.strides, :: self.strides, :]
        x = x.astype(self.dtype)
        return jax.lax.dot_general(
            x,
            kernel[0, 0].astype(self.dtype),
            (((x.ndim - 1,), (0,)), ((), ())),
        )


class BasicBlock(nn.Module):
    """3x3 + 3x3 residual block (ResNet-18/34)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    strides: int = 1

    expansion: int = 1

    pointwise: Optional[ModuleDef] = None

    @nn.compact
    def __call__(self, x):
        residual = x
        # Explicit (1,1) padding = torchvision's padding=1: identical to
        # SAME at stride 1, but at stride 2 SAME pads (0,1) and shifts the
        # conv windows one pixel off torch's — exact-parity blocker.
        y = self.conv(
            self.filters, (3, 3), (self.strides, self.strides),
            padding=[(1, 1), (1, 1)],
        )(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), padding=[(1, 1), (1, 1)])(y)
        y = self.norm()(y)
        if residual.shape != y.shape:
            if self.pointwise is not None:
                residual = self.pointwise(
                    self.filters * self.expansion,
                    strides=self.strides,
                    name="downsample_conv",
                )(residual)
            else:
                residual = self.conv(
                    self.filters * self.expansion,
                    (1, 1),
                    (self.strides, self.strides),
                    name="downsample_conv",
                )(residual)
            residual = self.norm(name="downsample_bn")(residual)
        return nn.relu(residual + y)


class BottleneckBlock(nn.Module):
    """1x1 → 3x3(stride) → 1x1(4x) residual block (ResNet-50/101/152).

    Stride lives on the 3x3 conv, matching torchvision's v1.5 behavior.
    """

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    strides: int = 1

    expansion: int = 4

    pointwise: Optional[ModuleDef] = None

    @nn.compact
    def __call__(self, x):
        pw = self.pointwise
        conv1x1 = (
            (lambda f, s=1, name=None: pw(f, strides=s, name=name))
            if pw is not None
            else (lambda f, s=1, name=None: self.conv(f, (1, 1), (s, s), name=name))
        )
        residual = x
        y = conv1x1(self.filters, name="Conv_0")(x)
        y = self.norm()(y)
        y = nn.relu(y)
        # padding=1 like torchvision: SAME would pad (0,1) at stride 2 and
        # shift windows one pixel off torch's (see BasicBlock note).
        y = self.conv(
            self.filters, (3, 3), (self.strides, self.strides),
            padding=[(1, 1), (1, 1)], name="Conv_1",
        )(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = conv1x1(self.filters * self.expansion, name="Conv_2")(y)
        y = self.norm()(y)
        if residual.shape != y.shape:
            residual = conv1x1(
                self.filters * self.expansion, self.strides, name="downsample_conv"
            )(residual)
            residual = self.norm(name="downsample_bn")(residual)
        return nn.relu(residual + y)


class _Kernel1x1(nn.Module):
    """Scope holder for a 1x1 conv kernel: creates ``<name>/kernel`` with
    the exact shape/name ``nn.Conv`` would, but returns the raw parameter so
    the caller can both apply the conv and use the weights in stats math
    (see ``FusedBottleneckBlock``)."""

    features: int
    kernel_init: Any = conv_kernel_init

    @nn.compact
    def __call__(self, in_features: int) -> jax.Array:
        return self.param(
            "kernel", self.kernel_init, (1, 1, in_features, self.features),
            jnp.float32,
        )


class _TailBatchNorm(nn.Module):
    """Owns BN3's params/running stats around ``_fused_expand_tail``.

    The tail consumes (gamma, beta) and *produces* the batch stats, so this
    module hands its parameters to a caller-supplied closure and applies the
    running-average update to whatever stats come back. Same variable layout
    as ``nn.BatchNorm`` — checkpoints interchange with the plain block."""

    momentum: float = 0.9
    epsilon: float = 1e-5

    @nn.compact
    def __call__(self, features: int, run_tail, train: bool):
        gamma = self.param("scale", nn.initializers.ones, (features,), jnp.float32)
        beta = self.param("bias", nn.initializers.zeros, (features,), jnp.float32)
        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((features,), jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((features,), jnp.float32)
        )
        if train:
            out, mean, var = run_tail(gamma, beta, None, None)
            if not self.is_initializing():
                ra_mean.value = (
                    self.momentum * ra_mean.value + (1 - self.momentum) * mean
                )
                ra_var.value = (
                    self.momentum * ra_var.value + (1 - self.momentum) * var
                )
        else:
            out, _, _ = run_tail(gamma, beta, ra_mean.value, ra_var.value)
        return out


class _MomentBatchNorm(nn.Module):
    """BatchNorm whose batch statistics are supplied by the caller.

    Parameter/variable layout is identical to ``nn.BatchNorm`` (params
    scale/bias, batch_stats mean/var), so checkpoints interchange with the
    plain block. The caller computes the batch stats from input moments
    (exactly — see FusedBottleneckBlock) instead of from a materialized
    pre-normalization tensor; this module just owns the state and applies
    the affine + running-average bookkeeping."""

    momentum: float = 0.9
    epsilon: float = 1e-5

    @nn.compact
    def __call__(self, features: int, batch_mean, batch_var, train: bool):
        """Returns fp32 ``(scale, bias)`` such that
        ``bn(y) = y * scale + bias`` for raw conv output ``y``."""
        gamma = self.param("scale", nn.initializers.ones, (features,), jnp.float32)
        beta = self.param("bias", nn.initializers.zeros, (features,), jnp.float32)
        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((features,), jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((features,), jnp.float32)
        )
        if train:
            mean, var = batch_mean, batch_var
            if not self.is_initializing():
                ra_mean.value = (
                    self.momentum * ra_mean.value + (1 - self.momentum) * mean
                )
                ra_var.value = (
                    self.momentum * ra_var.value + (1 - self.momentum) * var
                )
        else:
            mean, var = ra_mean.value, ra_var.value
        scale = gamma * jax.lax.rsqrt(var + self.epsilon)
        return scale, beta - mean * scale


@partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def _fused_expand_tail(z2, residual, w, gamma, beta, epsilon, axis=None):
    """``relu(bn(conv1x1(z2, w)) + residual)`` with batch stats from input
    moments, and a hand-written backward.

    Forward: see ``_expand_bn_stats`` — the [*, 4F] pre-BN tensor is never
    read for statistics, so XLA fuses normalize+add+relu into the conv's
    epilogue. Backward: the skinny matmul ``P = z2ᵀ(g·mask)`` is
    simultaneously the conv weight gradient (``P·a``) and the source of
    BN's reduction ``Σ g·y = colsum(P ∘ w)``, and the moment path's input
    gradient collapses to F×F-sized corrections — autodiff instead
    materializes the wide intermediates twice (measured +16 ms/step on
    the v5e ResNet-50 train step vs this formulation).

    ``axis``: mesh axis name for sync-BN under shard_map. The input
    moments are additive, so the forward psums (Σz, zᵀz) once; the
    backward mirrors what autodiff-through-psum would produce — LOCAL
    cotangents for the param grads (the trainer's cross-replica pmean
    completes them) and PSUM'd (dmean, dvar) for the activation grad,
    because the psum'd statistics make every replica's loss depend on
    this shard's input.

    Returns ``(out, batch_mean, batch_var)``.
    """
    return _fused_expand_tail_fwd(z2, residual, w, gamma, beta, epsilon,
                                  axis)[0]


_NHWC_1x1 = ("NHWC", "HWIO", "NHWC")


def _conv1x1(x, w2d, strides=(1, 1)):
    """1x1 NHWC conv with a [Cin, Cout] kernel, in x's dtype."""
    return jax.lax.conv_general_dilated(
        x, w2d[None, None].astype(x.dtype), strides, "VALID",
        dimension_numbers=_NHWC_1x1,
    )


def _moments_nhwc(x, axis=None):
    """(Σx, xᵀx, n) over (B,H,W) of an NHWC tensor, fp32 accumulation.

    ``axis``: sync-BN mesh axis — the moments are additive, so one psum
    makes them (and the element count n) global; this is the ONE site
    that owns the cross-replica reduction for every moment-path consumer.

    Rank-4 contractions on purpose: collapsing B,H,W with a reshape
    changes the tensor's second-to-last dim and forces a physical
    retiling copy on TPU (measured: flattening these [*,F] operands cost
    +8 ms/step on the v5e ResNet-50 step)."""
    n = x.shape[0] * x.shape[1] * x.shape[2]
    s = jnp.sum(x, axis=(0, 1, 2), dtype=jnp.float32)
    m2 = jax.lax.dot_general(
        x, x, (((0, 1, 2), (0, 1, 2)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    if axis is not None:
        s, m2 = jax.lax.psum((s, m2), axis)
        n = n * jax.lax.psum(1, axis)
    return s, m2, n


def _fused_expand_tail_fwd(z2, residual, w, gamma, beta, epsilon, axis=None):
    # Two measured dead ends are worth recording here: (1) a Pallas
    # one-pass version of these reductions (ops/bottleneck_tail.py) was
    # SLOWER in the full step — the custom-call boundary costs XLA its
    # conv layouts and epilogue fusions, +8.5 ms of layout copies; (2) a
    # ones-channel augmentation folding Σz2/Σgp into the contractions
    # broke lane alignment (65 channels pads to 128 lanes, doubling the
    # bytes of every pass at stage 1/2) for +7 ms. See PERF_NOTES.md.
    dt = z2.dtype
    # sync-BN (axis set): _moments_nhwc psums the additive moments once;
    # everything downstream sees global statistics.
    s, m2, n = _moments_nhwc(z2, axis)
    m = s / n
    m2n = m2 / n  # E[z zᵀ], global when syncing
    mean = m @ w
    ey2 = jnp.sum(m2n @ w * w, axis=0)
    var = ey2 - mean * mean
    sigma_inv = jax.lax.rsqrt(var + epsilon)
    a = gamma * sigma_inv
    b = beta - mean * a

    y3 = _conv1x1(z2, w)
    out = jax.nn.relu(y3 * a.astype(dt) + b.astype(dt) + residual.astype(dt))
    saved = (z2, w, gamma, m, m2n, n, mean, var, sigma_inv, a, out)
    return (out, mean, var), saved


def _fused_expand_tail_bwd(epsilon, axis, saved, cotangents):
    g, g_mean, g_var = cotangents
    z2, w, gamma, m, m2n, n, mean, var, sigma_inv, a, out = saved

    gp = jnp.where(out > 0, g, 0)  # [B,h,w,E]; also IS the residual grad
    # One skinny contraction carries the conv weight grad AND the BN
    # reductions: p = Σ_(b,h,w) z2 ⊗ gp.
    p = jax.lax.dot_general(
        z2, gp, (((0, 1, 2), (0, 1, 2)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [F, E]
    sb = jnp.sum(gp, axis=(0, 1, 2), dtype=jnp.float32)  # [E] = dL/db
    sa = jnp.sum(p * w, axis=0)  # [E] = Σ g·y
    a_grad = sa - mean * sb  # dL/da
    dgamma = a_grad * sigma_inv
    dbeta = sb
    # Param grads (dgamma/dbeta/dw) use LOCAL cotangents — the trainer's
    # cross-replica grad combine completes them, exactly as it would for
    # autodiff of a psum'd-stats forward. m/m2n/mean/sigma are global
    # forward VALUES, so the formulas are unchanged.
    dvar = -0.5 * a_grad * gamma * sigma_inv**3 + g_var
    dmean = -a * sb - 2.0 * mean * dvar + g_mean
    dw = p * a + jnp.outer(m, dmean) + 2.0 * m2n @ w * dvar

    # The ACTIVATION grad needs the psum: the transposed moment-psum
    # delivers every replica's (dmean, dvar) back to this shard's z2.
    if axis is not None:
        dmean, dvar = jax.lax.psum((dmean, dvar), axis)
    dm = w @ dmean  # [F]
    # dM is symmetric: w·diag(dvar)·wᵀ/n
    dm2 = (w * dvar) @ w.T / n  # [F, F]

    dt = z2.dtype
    # Both wide matmuls stay 1x1 NHWC convs (layout, see _moments_nhwc);
    # the elementwise scale/add fuse into their operands.
    dz = (
        _conv1x1(gp * a.astype(dt), w.T)
        + _conv1x1(z2, 2.0 * dm2)
        + (dm / n).astype(dt)
    )
    return dz.astype(dt), gp, dw, dgamma, dbeta


_fused_expand_tail.defvjp(_fused_expand_tail_fwd, _fused_expand_tail_bwd)


def _expand_bn_stats(z2f, w, axis=None):
    """Exact batch stats of ``conv1x1(z, w)`` from the moments of ``z``.

    The 1x1 expand conv is linear, so with ``m = E[z]`` and
    ``M2 = E[z zᵀ]`` (an F×F matrix, F the *narrow* width):

        E[y_c]  = m · w_c
        E[y_c²] = w_cᵀ M2 w_c

    This replaces the usual stats pass over the [N, 4F] conv output — the
    widest tensor in the block — with one skinny [N,F]×[N,F] matmul, which
    is what lets normalize+add+relu ride as an epilogue of the conv instead
    of forcing the raw output through HBM twice (PERF_NOTES.md §5 fix #1).
    Accumulation in fp32 on the MXU, same as a conv's own accumulator.
    Variance via E[y²]−E[y]², flax's fast-variance formula. ``z`` is NHWC
    (rank-4 contraction — see ``_moments_nhwc`` for why not flattened).
    """
    # sync-BN (axis set): psum'd inside _moments_nhwc; autodiff transposes
    # the psum itself, so this path needs no hand-written backward
    s, m2, n = _moments_nhwc(z2f, axis)
    mean = (s / n) @ w
    ey2 = jnp.sum((m2 / n) @ w * w, axis=0)
    return mean, ey2 - mean * mean


class FusedBottleneckBlock(nn.Module):
    """BottleneckBlock restructured so the expand tail fuses.

    Identical math and parameter tree to ``BottleneckBlock`` (same conv /
    BN names, interchangeable checkpoints, same batch-stat semantics); the
    difference is purely how BN3/downsample-BN batch statistics are
    obtained: from input moments via ``_expand_bn_stats`` rather than from
    the materialized raw conv outputs. The [B,H,W,4F] pre-BN tensors — the
    widest in the network — then never need a separate stats read, and XLA
    fuses ``relu(y3*scale + bias + residual)`` into the conv epilogue.
    Profiled on v5e: this was the HBM traffic PERF_NOTES.md §4 showed
    bounding the step.
    """

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    strides: int = 1
    expansion: int = 4
    dtype: Any = jnp.float32
    momentum: float = 0.9
    epsilon: float = 1e-5
    # sync-BN mesh axis for the moment-path stats (BN3 + downsample);
    # BN0/BN1 sync via the ``norm`` partial's own axis_name. None = local
    # per-replica statistics, the reference's DDP semantics.
    bn_cross_replica_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        f, e = self.filters, self.expansion
        y = self.conv(f, (1, 1), (1, 1), name="Conv_0")(x)
        y = self.norm(name="BatchNorm_0")(y)
        y = nn.relu(y)
        y = self.conv(
            f, (3, 3), (self.strides, self.strides),
            padding=[(1, 1), (1, 1)], name="Conv_1",
        )(y)
        y = self.norm(name="BatchNorm_1")(y)
        z2 = nn.relu(y)  # [B, h, w, F] compute dtype

        w3 = _Kernel1x1(f * e, name="Conv_2")(f)[0, 0]  # [F, 4F] fp32

        if x.shape[-1] != f * e or self.strides != 1:
            wd = _Kernel1x1(f * e, name="downsample_conv")(x.shape[-1])[0, 0]
            if train:
                xs = x[:, :: self.strides, :: self.strides, :]
                ds_mean, ds_var = _expand_bn_stats(
                    xs, wd, self.bn_cross_replica_axis
                )
            else:
                ds_mean = ds_var = None
            scaled, biasd = _MomentBatchNorm(
                self.momentum, self.epsilon, name="downsample_bn"
            )(f * e, ds_mean, ds_var, train)
            ds = _conv1x1(
                x.astype(self.dtype), wd, (self.strides, self.strides)
            )
            residual = ds * scaled.astype(self.dtype) + biasd.astype(self.dtype)
        else:
            residual = x.astype(self.dtype)

        def run_tail(gamma, beta, ra_mean, ra_var):
            if ra_mean is None:  # train: stats from moments inside the vjp
                return _fused_expand_tail(
                    z2, residual, w3, gamma, beta, self.epsilon,
                    self.bn_cross_replica_axis,
                )
            scale = gamma * jax.lax.rsqrt(ra_var + self.epsilon)
            bias = beta - ra_mean * scale
            y3 = _conv1x1(z2.astype(self.dtype), w3)
            out = nn.relu(
                y3 * scale.astype(self.dtype) + bias.astype(self.dtype)
                + residual
            )
            return out, ra_mean, ra_var

        return _TailBatchNorm(self.momentum, self.epsilon, name="BatchNorm_2")(
            f * e, run_tail, train
        )


@jax.custom_vjp
def _ste_quant_dequant(x, scale):
    """int8 round-trip with a straight-through gradient. The value
    semantics are the quantized ones (NON-parity with the plain model —
    opt-in only); the grad passes through unchanged (STE), so training
    proceeds at full-precision gradient fidelity."""
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    # materialize the int8 tensor: without the barrier XLA is free to keep
    # the wide dtype live between block fusions and the experiment
    # measures nothing
    q = jax.lax.optimization_barrier(q)
    return (q.astype(x.dtype) * scale).astype(x.dtype)


def _ste_fwd(x, scale):
    return _ste_quant_dequant(x, scale), None


def _ste_bwd(_, g):
    return g, None


_ste_quant_dequant.defvjp(_ste_fwd, _ste_bwd)


def _int8_trunk(x):
    """Store the residual trunk int8 between blocks (HBM-traffic
    experiment, PERF_NOTES §7): per-channel abs-max symmetric scale, STE
    backward. The quantize rides the producing block's epilogue, the
    dequant fuses into both consumers (next conv + residual add) — the
    tensor materialized between fusions is the int8 one."""
    scale = (
        jnp.max(jnp.abs(x.astype(jnp.float32)), axis=(0, 1, 2),
                keepdims=True) / 127.0 + 1e-12
    ).astype(x.dtype)
    return _ste_quant_dequant(x, scale)


class SpaceToDepthStem(nn.Module):
    """The 7×7/2 ImageNet stem computed on a space-to-depth input.

    The stem convolution has C_in=3 — 3 of the MXU's 128 lanes do work.
    The classic MLPerf transform: reshape the image [H, W, 3] →
    [H/2, W/2, 12] (2×2 sub-pixels into channels) and apply an EXACTLY
    equivalent 4×4 stride-1 conv whose kernel is a zero-padded rearrangement
    of the canonical 7×7 weights:

        W8[u+1, v+1] = W[u, v]            (pad one row/col at the top-left,
                                           aligning the window to even pixels)
        K[a, b, (di·2+dj)·3+c, f] = W8[2a+di, 2b+dj, c, f]   → [4, 4, 12, F]

    The parameter stays the canonical ``[7, 7, 3, F]`` "kernel" (the
    rearrangement is a differentiable reshape inside apply), so checkpoints
    are bit-interchangeable with the plain stem.
    """

    features: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        w = self.param(
            "kernel", conv_kernel_init, (7, 7, 3, self.features), jnp.float32
        )
        b_, h, wd, c = x.shape
        if h % 2 or wd % 2 or c != 3:
            raise ValueError(
                f"space-to-depth stem needs even HxW RGB input, got {x.shape}"
            )
        x = x.astype(self.dtype)
        # [B, H, W, 3] → [B, H/2, W/2, 12], channel order (di, dj, c)
        x2 = x.reshape(b_, h // 2, 2, wd // 2, 2, 3)
        x2 = x2.transpose(0, 1, 3, 2, 4, 5).reshape(b_, h // 2, wd // 2, 12)
        # canonical 7x7 weights → the equivalent 4x4x12 kernel
        w8 = jnp.pad(w, ((1, 0), (1, 0), (0, 0), (0, 0)))
        k = (
            w8.reshape(4, 2, 4, 2, 3, self.features)
            .transpose(0, 2, 1, 3, 4, 5)
            .reshape(4, 4, 12, self.features)
        ).astype(self.dtype)
        return jax.lax.conv_general_dilated(
            x2, k, (1, 1), [(2, 1), (2, 1)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )


class ResNet(nn.Module):
    """ResNet v1.5 with an ImageNet stem.

    Attributes:
      stage_sizes: blocks per stage, e.g. (3, 4, 6, 3) for ResNet-50.
      block_cls: BasicBlock or BottleneckBlock.
      num_classes: classifier width (1000 for ImageNet).
      num_filters: stem width (64).
      dtype: compute dtype (bf16 for TPU mixed precision; params stay fp32).
      bn_cross_replica_axis: mesh axis name for sync-BN under shard_map; None
        (default) keeps per-replica statistics like the reference's DDP.
      use_dot_1x1: lower pointwise convs as dot_general (see ``Conv1x1``);
        identical math and checkpoint layout, measured perf-neutral on v5e.
      remat_blocks: wrap each residual block in ``jax.checkpoint``; trades
        ~20% step time (measured v5e, bs128) for activation memory —
        useful when batch size is HBM-limited.
      space_to_depth_stem: compute the stem on a [H/2, W/2, 12] input (see
        ``SpaceToDepthStem``) — mathematically identical, checkpoint-
        compatible, avoids the C_in=3 lane waste of the 7x7 conv.
      fused_bottleneck: use ``FusedBottleneckBlock`` (bottleneck stages
        only): same math, same checkpoint tree, but the expand-tail BN
        stats come from input moments so the widest activations skip a
        stats pass and normalize+add+relu fuse into the conv epilogue.
    """

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.float32
    bn_cross_replica_axis: Optional[str] = None
    use_dot_1x1: bool = False
    remat_blocks: bool = False
    space_to_depth_stem: bool = False
    fused_bottleneck: bool = False
    # EXPERIMENT (PERF_NOTES §7), opt-in and NON-parity: store the
    # residual trunk int8 between blocks (per-channel abs-max scale,
    # straight-through grads). Halves the bytes of the widest stored
    # activations vs bf16 — the storage-level lever the r3 roofline
    # analysis named.
    int8_trunk: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(
            nn.Conv,
            use_bias=False,
            padding="SAME",
            dtype=self.dtype,
            kernel_init=conv_kernel_init,
        )
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            axis_name=self.bn_cross_replica_axis,
        )

        pointwise = (
            partial(Conv1x1, dtype=self.dtype, kernel_init=conv_kernel_init)
            if self.use_dot_1x1
            else None
        )

        x = x.astype(self.dtype)
        if self.space_to_depth_stem:
            x = SpaceToDepthStem(
                self.num_filters, dtype=self.dtype, name="conv_init"
            )(x)
        else:
            x = conv(
                self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                name="conv_init",
            )(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])

        fused = self.fused_bottleneck and self.block_cls is BottleneckBlock
        block_cls = FusedBottleneckBlock if fused else self.block_cls
        if self.remat_blocks:
            block_cls = nn.remat(block_cls, static_argnums=(2,) if fused else ())
        for i, stage_size in enumerate(self.stage_sizes):
            for j in range(stage_size):
                strides = 2 if i > 0 and j == 0 else 1
                if fused:
                    x = block_cls(
                        filters=self.num_filters * 2**i,
                        conv=conv,
                        norm=norm,
                        strides=strides,
                        dtype=self.dtype,
                        bn_cross_replica_axis=self.bn_cross_replica_axis,
                        name=f"stage{i + 1}_block{j + 1}",
                    )(x, train)
                else:
                    x = block_cls(
                        filters=self.num_filters * 2**i,
                        conv=conv,
                        norm=norm,
                        strides=strides,
                        pointwise=pointwise,
                        name=f"stage{i + 1}_block{j + 1}",
                    )(x)
                if self.int8_trunk:
                    x = _int8_trunk(x)

        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="fc")(x)
        # Logits in fp32 regardless of compute dtype: softmax/CE stay accurate
        # under bf16 mixed precision.
        return x.astype(jnp.float32)


def _resnet(stage_sizes, block_cls) -> Callable[..., ResNet]:
    def build(num_classes: int = 1000, **kwargs) -> ResNet:
        return ResNet(
            stage_sizes=stage_sizes,
            block_cls=block_cls,
            num_classes=num_classes,
            **kwargs,
        )

    return build


resnet18 = _resnet((2, 2, 2, 2), BasicBlock)
resnet34 = _resnet((3, 4, 6, 3), BasicBlock)
resnet50 = _resnet((3, 4, 6, 3), BottleneckBlock)
resnet101 = _resnet((3, 4, 23, 3), BottleneckBlock)
resnet152 = _resnet((3, 8, 36, 3), BottleneckBlock)
