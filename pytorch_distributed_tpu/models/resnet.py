"""ResNet family (v1.5) in flax, TPU-first.

Capability parity with ``torchvision.models.resnet50`` as used by the
reference (``resnet_single_gpu.py:83``, ``restnet_ddp.py:98``): same
architecture (7x7 stem, [3,4,6,3] bottleneck stages, stride on the 3x3 conv
— the "v1.5" variant torchvision ships), same parameter count (25,557,032
for ResNet-50), same BatchNorm semantics (momentum 0.1 in torch convention =
0.9 decay here, eps 1e-5, per-replica statistics by default — matching DDP's
non-synced BN; pass ``bn_cross_replica_axis`` for sync-BN, which the
reference cannot do at all).

TPU-first choices:
- NHWC layout throughout (XLA:TPU's native conv layout; torchvision is NCHW).
- ``dtype`` is the *compute* dtype: pass ``jnp.bfloat16`` for mixed precision
  — parameters stay fp32, matmuls/convs run bf16 on the MXU, and the final
  logits are returned fp32 (replaces CUDA AMP autocast,
  ``resnet_ddp_apex.py:27-29``).
- Everything is a pure function of (params, batch_stats, inputs): jit/pjit
  compile the whole forward into one XLA program; no Python control flow
  depends on data.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

ModuleDef = Any

# torchvision's kaiming_normal_(mode='fan_out', nonlinearity='relu')
conv_kernel_init = nn.initializers.variance_scaling(2.0, "fan_out", "normal")


class Conv1x1(nn.Module):
    """Pointwise convolution expressed as a ``dot_general`` contraction.

    A 1x1 conv IS a matmul over the channel dim; lowering it as
    ``dot_general`` instead of ``conv_general_dilated`` steers XLA:TPU onto
    the MXU matmul emitters in both directions. Measured on v5e (see
    PERF_NOTES.md): exact output parity, but no step-time win — the full
    train step is HBM-bandwidth-bound, not conv-emitter-bound — so this
    stays an option (``ResNet.use_dot_1x1``), default off.

    Parameter shape and name match ``nn.Conv`` ((1, 1, Cin, Cout) under
    "kernel") so checkpoints are interchangeable with the conv formulation.
    """

    features: int
    strides: int = 1
    dtype: Any = jnp.float32
    kernel_init: Any = conv_kernel_init

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel", self.kernel_init, (1, 1, x.shape[-1], self.features), jnp.float32
        )
        if self.strides != 1:
            x = x[:, :: self.strides, :: self.strides, :]
        x = x.astype(self.dtype)
        return jax.lax.dot_general(
            x,
            kernel[0, 0].astype(self.dtype),
            (((x.ndim - 1,), (0,)), ((), ())),
        )


class BasicBlock(nn.Module):
    """3x3 + 3x3 residual block (ResNet-18/34)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    strides: int = 1

    expansion: int = 1

    pointwise: Optional[ModuleDef] = None

    @nn.compact
    def __call__(self, x):
        residual = x
        # Explicit (1,1) padding = torchvision's padding=1: identical to
        # SAME at stride 1, but at stride 2 SAME pads (0,1) and shifts the
        # conv windows one pixel off torch's — exact-parity blocker.
        y = self.conv(
            self.filters, (3, 3), (self.strides, self.strides),
            padding=[(1, 1), (1, 1)],
        )(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), padding=[(1, 1), (1, 1)])(y)
        y = self.norm()(y)
        if residual.shape != y.shape:
            if self.pointwise is not None:
                residual = self.pointwise(
                    self.filters * self.expansion,
                    strides=self.strides,
                    name="downsample_conv",
                )(residual)
            else:
                residual = self.conv(
                    self.filters * self.expansion,
                    (1, 1),
                    (self.strides, self.strides),
                    name="downsample_conv",
                )(residual)
            residual = self.norm(name="downsample_bn")(residual)
        return nn.relu(residual + y)


class BottleneckBlock(nn.Module):
    """1x1 → 3x3(stride) → 1x1(4x) residual block (ResNet-50/101/152).

    Stride lives on the 3x3 conv, matching torchvision's v1.5 behavior.
    """

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    strides: int = 1

    expansion: int = 4

    pointwise: Optional[ModuleDef] = None

    @nn.compact
    def __call__(self, x):
        pw = self.pointwise
        conv1x1 = (
            (lambda f, s=1, name=None: pw(f, strides=s, name=name))
            if pw is not None
            else (lambda f, s=1, name=None: self.conv(f, (1, 1), (s, s), name=name))
        )
        residual = x
        y = conv1x1(self.filters, name="Conv_0")(x)
        y = self.norm()(y)
        y = nn.relu(y)
        # padding=1 like torchvision: SAME would pad (0,1) at stride 2 and
        # shift windows one pixel off torch's (see BasicBlock note).
        y = self.conv(
            self.filters, (3, 3), (self.strides, self.strides),
            padding=[(1, 1), (1, 1)], name="Conv_1",
        )(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = conv1x1(self.filters * self.expansion, name="Conv_2")(y)
        y = self.norm()(y)
        if residual.shape != y.shape:
            residual = conv1x1(
                self.filters * self.expansion, self.strides, name="downsample_conv"
            )(residual)
            residual = self.norm(name="downsample_bn")(residual)
        return nn.relu(residual + y)


class SpaceToDepthStem(nn.Module):
    """The 7×7/2 ImageNet stem computed on a space-to-depth input.

    The stem convolution has C_in=3 — 3 of the MXU's 128 lanes do work.
    The classic MLPerf transform: reshape the image [H, W, 3] →
    [H/2, W/2, 12] (2×2 sub-pixels into channels) and apply an EXACTLY
    equivalent 4×4 stride-1 conv whose kernel is a zero-padded rearrangement
    of the canonical 7×7 weights:

        W8[u+1, v+1] = W[u, v]            (pad one row/col at the top-left,
                                           aligning the window to even pixels)
        K[a, b, (di·2+dj)·3+c, f] = W8[2a+di, 2b+dj, c, f]   → [4, 4, 12, F]

    The parameter stays the canonical ``[7, 7, 3, F]`` "kernel" (the
    rearrangement is a differentiable reshape inside apply), so checkpoints
    are bit-interchangeable with the plain stem.
    """

    features: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        w = self.param(
            "kernel", conv_kernel_init, (7, 7, 3, self.features), jnp.float32
        )
        b_, h, wd, c = x.shape
        if h % 2 or wd % 2 or c != 3:
            raise ValueError(
                f"space-to-depth stem needs even HxW RGB input, got {x.shape}"
            )
        x = x.astype(self.dtype)
        # [B, H, W, 3] → [B, H/2, W/2, 12], channel order (di, dj, c)
        x2 = x.reshape(b_, h // 2, 2, wd // 2, 2, 3)
        x2 = x2.transpose(0, 1, 3, 2, 4, 5).reshape(b_, h // 2, wd // 2, 12)
        # canonical 7x7 weights → the equivalent 4x4x12 kernel
        w8 = jnp.pad(w, ((1, 0), (1, 0), (0, 0), (0, 0)))
        k = (
            w8.reshape(4, 2, 4, 2, 3, self.features)
            .transpose(0, 2, 1, 3, 4, 5)
            .reshape(4, 4, 12, self.features)
        ).astype(self.dtype)
        return jax.lax.conv_general_dilated(
            x2, k, (1, 1), [(2, 1), (2, 1)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )


class ResNet(nn.Module):
    """ResNet v1.5 with an ImageNet stem.

    Attributes:
      stage_sizes: blocks per stage, e.g. (3, 4, 6, 3) for ResNet-50.
      block_cls: BasicBlock or BottleneckBlock.
      num_classes: classifier width (1000 for ImageNet).
      num_filters: stem width (64).
      dtype: compute dtype (bf16 for TPU mixed precision; params stay fp32).
      bn_cross_replica_axis: mesh axis name for sync-BN under shard_map; None
        (default) keeps per-replica statistics like the reference's DDP.
      use_dot_1x1: lower pointwise convs as dot_general (see ``Conv1x1``);
        identical math and checkpoint layout, measured perf-neutral on v5e.
      remat_blocks: wrap each residual block in ``jax.checkpoint``; trades
        ~20% step time (measured v5e, bs128) for activation memory —
        useful when batch size is HBM-limited.
      space_to_depth_stem: compute the stem on a [H/2, W/2, 12] input (see
        ``SpaceToDepthStem``) — mathematically identical, checkpoint-
        compatible, avoids the C_in=3 lane waste of the 7x7 conv.
    """

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.float32
    bn_cross_replica_axis: Optional[str] = None
    use_dot_1x1: bool = False
    remat_blocks: bool = False
    space_to_depth_stem: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(
            nn.Conv,
            use_bias=False,
            padding="SAME",
            dtype=self.dtype,
            kernel_init=conv_kernel_init,
        )
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            axis_name=self.bn_cross_replica_axis,
        )

        pointwise = (
            partial(Conv1x1, dtype=self.dtype, kernel_init=conv_kernel_init)
            if self.use_dot_1x1
            else None
        )

        x = x.astype(self.dtype)
        if self.space_to_depth_stem:
            x = SpaceToDepthStem(
                self.num_filters, dtype=self.dtype, name="conv_init"
            )(x)
        else:
            x = conv(
                self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                name="conv_init",
            )(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])

        block_cls = self.block_cls
        if self.remat_blocks:
            block_cls = nn.remat(block_cls)
        for i, stage_size in enumerate(self.stage_sizes):
            for j in range(stage_size):
                strides = 2 if i > 0 and j == 0 else 1
                x = block_cls(
                    filters=self.num_filters * 2**i,
                    conv=conv,
                    norm=norm,
                    strides=strides,
                    pointwise=pointwise,
                    name=f"stage{i + 1}_block{j + 1}",
                )(x)

        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="fc")(x)
        # Logits in fp32 regardless of compute dtype: softmax/CE stay accurate
        # under bf16 mixed precision.
        return x.astype(jnp.float32)


def _resnet(stage_sizes, block_cls) -> Callable[..., ResNet]:
    def build(num_classes: int = 1000, **kwargs) -> ResNet:
        return ResNet(
            stage_sizes=stage_sizes,
            block_cls=block_cls,
            num_classes=num_classes,
            **kwargs,
        )

    return build


resnet18 = _resnet((2, 2, 2, 2), BasicBlock)
resnet34 = _resnet((3, 4, 6, 3), BasicBlock)
resnet50 = _resnet((3, 4, 6, 3), BottleneckBlock)
resnet101 = _resnet((3, 4, 23, 3), BottleneckBlock)
resnet152 = _resnet((3, 8, 36, 3), BottleneckBlock)
