"""ResNet family (v1.5) in flax, TPU-first.

Capability parity with ``torchvision.models.resnet50`` as used by the
reference (``resnet_single_gpu.py:83``, ``restnet_ddp.py:98``): same
architecture (7x7 stem, [3,4,6,3] bottleneck stages, stride on the 3x3 conv
— the "v1.5" variant torchvision ships), same parameter count (25,557,032
for ResNet-50), same BatchNorm semantics (momentum 0.1 in torch convention =
0.9 decay here, eps 1e-5, per-replica statistics by default — matching DDP's
non-synced BN; pass ``bn_cross_replica_axis`` for sync-BN, which the
reference cannot do at all).

TPU-first choices:
- NHWC layout throughout (XLA:TPU's native conv layout; torchvision is NCHW).
- ``dtype`` is the *compute* dtype: pass ``jnp.bfloat16`` for mixed precision
  — parameters stay fp32, matmuls/convs run bf16 on the MXU, and the final
  logits are returned fp32 (replaces CUDA AMP autocast,
  ``resnet_ddp_apex.py:27-29``).
- Everything is a pure function of (params, batch_stats, inputs): jit/pjit
  compile the whole forward into one XLA program; no Python control flow
  depends on data.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any

# torchvision's kaiming_normal_(mode='fan_out', nonlinearity='relu')
conv_kernel_init = nn.initializers.variance_scaling(2.0, "fan_out", "normal")


class BasicBlock(nn.Module):
    """3x3 + 3x3 residual block (ResNet-18/34)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    strides: int = 1

    expansion: int = 1

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), (self.strides, self.strides))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm()(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * self.expansion,
                (1, 1),
                (self.strides, self.strides),
                name="downsample_conv",
            )(residual)
            residual = self.norm(name="downsample_bn")(residual)
        return nn.relu(residual + y)


class BottleneckBlock(nn.Module):
    """1x1 → 3x3(stride) → 1x1(4x) residual block (ResNet-50/101/152).

    Stride lives on the 3x3 conv, matching torchvision's v1.5 behavior.
    """

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    strides: int = 1

    expansion: int = 4

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), (self.strides, self.strides))(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * self.expansion, (1, 1))(y)
        y = self.norm()(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * self.expansion,
                (1, 1),
                (self.strides, self.strides),
                name="downsample_conv",
            )(residual)
            residual = self.norm(name="downsample_bn")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    """ResNet v1.5 with an ImageNet stem.

    Attributes:
      stage_sizes: blocks per stage, e.g. (3, 4, 6, 3) for ResNet-50.
      block_cls: BasicBlock or BottleneckBlock.
      num_classes: classifier width (1000 for ImageNet).
      num_filters: stem width (64).
      dtype: compute dtype (bf16 for TPU mixed precision; params stay fp32).
      bn_cross_replica_axis: mesh axis name for sync-BN under shard_map; None
        (default) keeps per-replica statistics like the reference's DDP.
    """

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.float32
    bn_cross_replica_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(
            nn.Conv,
            use_bias=False,
            padding="SAME",
            dtype=self.dtype,
            kernel_init=conv_kernel_init,
        )
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            axis_name=self.bn_cross_replica_axis,
        )

        x = x.astype(self.dtype)
        x = conv(
            self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)], name="conv_init"
        )(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])

        for i, stage_size in enumerate(self.stage_sizes):
            for j in range(stage_size):
                strides = 2 if i > 0 and j == 0 else 1
                x = self.block_cls(
                    filters=self.num_filters * 2**i,
                    conv=conv,
                    norm=norm,
                    strides=strides,
                    name=f"stage{i + 1}_block{j + 1}",
                )(x)

        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="fc")(x)
        # Logits in fp32 regardless of compute dtype: softmax/CE stay accurate
        # under bf16 mixed precision.
        return x.astype(jnp.float32)


def _resnet(stage_sizes, block_cls) -> Callable[..., ResNet]:
    def build(num_classes: int = 1000, **kwargs) -> ResNet:
        return ResNet(
            stage_sizes=stage_sizes,
            block_cls=block_cls,
            num_classes=num_classes,
            **kwargs,
        )

    return build


resnet18 = _resnet((2, 2, 2, 2), BasicBlock)
resnet34 = _resnet((3, 4, 6, 3), BasicBlock)
resnet50 = _resnet((3, 4, 6, 3), BottleneckBlock)
resnet101 = _resnet((3, 4, 23, 3), BottleneckBlock)
resnet152 = _resnet((3, 8, 36, 3), BottleneckBlock)
