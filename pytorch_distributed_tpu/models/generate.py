"""Autoregressive generation with a KV cache.

Beyond the reference's surface (a training benchmark repo) but expected of
an LM framework: ONE batched causal forward prefills the cache over the
whole prompt (O(L²) parallel, not L sequential steps), then a ``lax.scan``
decodes with greedy / temperature / top-k sampling, each step attending
against the cached K/V only (O(L) per token). One compiled program total.

``position_offset`` is the single source of position truth throughout
(``models.transformer.Attention``): the cache write index, the attention
mask, and the positional embedding all derive from it, so a stale cache
and a wrong offset cannot silently disagree.

Dense-attention math (the cache IS the global sequence, so no ring is
needed at decode time); ``generate`` runs with replicated params,
``generate_tp`` shards the decode matmuls and the KV cache over the model
axis (Megatron layout). Deterministic under a fixed rng key.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from pytorch_distributed_tpu.models.transformer import (
    TransformerConfig,
    TransformerLM,
)


def init_cache(config: TransformerConfig, params, batch_size: int):
    """Zero decode cache; shapes via ``eval_shape`` (nothing is traced into
    any compiled program, let alone executed)."""
    model = TransformerLM(config)
    _, shapes = jax.eval_shape(
        lambda p: model.apply(
            {"params": p},
            jnp.zeros((batch_size, 1), jnp.int32),
            position_offset=0,
            decode=True,
            mutable=["cache"],
        ),
        params,
    )
    return jax.tree.map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes["cache"]
    )


def _sample(logits, rng, temperature: float, top_k: Optional[int]):
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / jnp.maximum(temperature, 1e-6)
    if top_k is not None:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -1e30, logits)
    return jax.random.categorical(rng, logits).astype(jnp.int32)


def _validate_generate_args(config, prompt, max_new_tokens, temperature,
                            top_k):
    l_prompt = prompt.shape[1]
    if l_prompt < 1:
        raise ValueError("prompt must contain at least one token")
    if l_prompt + max_new_tokens > config.max_seq_len:
        raise ValueError(
            f"prompt ({l_prompt}) + max_new_tokens ({max_new_tokens}) "
            f"exceeds max_seq_len {config.max_seq_len}"
        )
    if temperature < 0.0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if top_k is not None and not 1 <= top_k <= config.vocab_size:
        raise ValueError(
            f"top_k must be in [1, vocab_size={config.vocab_size}], "
            f"got {top_k}"
        )
    if getattr(config, "attention", "dense") != "dense":
        raise ValueError(
            "generation is dense-attention only (the KV cache IS the "
            "global sequence); build the decode config with "
            "attention='dense' — ring/ring_flash are training-time "
            "sequence-parallel layouts"
        )


def _generate_core(config, params, prompt, rng, max_new_tokens, temperature,
                   top_k):
    """The prefill + scan decode body; runs replicated or (under shard_map
    with a TP config) with Megatron collectives inside each apply."""
    model = TransformerLM(config)
    b, l_prompt = prompt.shape
    logits, variables = model.apply(
        {"params": params},
        prompt,
        position_offset=0,
        prefill=True,
        mutable=["cache"],
    )
    cache = variables["cache"]
    last_logits = logits[:, -1]

    def step(cache, token, pos):
        logits, variables = model.apply(
            {"params": params, "cache": cache},
            token[:, None],
            position_offset=pos,
            decode=True,
            mutable=["cache"],
        )
        return variables["cache"], logits[:, 0]

    def decode_body(carry, rng_step):
        cache, pos, logits = carry
        token = _sample(logits, rng_step, temperature, top_k)
        cache, next_logits = step(cache, token, pos)
        return (cache, pos + 1, next_logits), token

    rngs = jax.random.split(rng, max_new_tokens)
    _, tokens = jax.lax.scan(
        decode_body,
        (cache, jnp.asarray(l_prompt, jnp.int32), last_logits),
        rngs,
    )
    return jnp.concatenate([prompt, tokens.T], axis=1)


def generate_tp(
    mesh,
    config: TransformerConfig,
    params,
    prompt: jax.Array,
    rng: jax.Array,
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
) -> jax.Array:
    """Tensor-parallel generation: the decode step's matmuls and the KV
    cache shard over ``config.model_axis`` (qkv/proj by head, MLP by
    hidden dim — the cache inherits the local head count because the
    Attention module builds it from the sharded K/V it computes).

    ``params`` may be replicated or already placed by
    ``TRANSFORMER_TP_RULES``; either way the in_specs pin the Megatron
    layout and the output tokens come back replicated. Exact parity with
    replicated ``generate`` (tests/test_generate.py) — sampling happens on
    replicated logits with the same keys.
    """
    from jax.sharding import PartitionSpec as P

    from pytorch_distributed_tpu.parallel.mesh import shard_map
    from pytorch_distributed_tpu.parallel.tensor import match_partition_rules
    from pytorch_distributed_tpu.train.lm import TRANSFORMER_TP_RULES

    if config.model_axis is None or config.tp_size <= 1:
        raise ValueError(
            "generate_tp needs a TP config (model_axis + tp_size > 1); "
            "use generate() for replicated decoding"
        )
    if mesh.shape[config.model_axis] != config.tp_size:
        raise ValueError(
            f"mesh {config.model_axis!r} size "
            f"{mesh.shape[config.model_axis]} != tp_size {config.tp_size}"
        )
    _validate_generate_args(config, prompt, max_new_tokens, temperature,
                            top_k)
    fn = _generate_tp_compiled(mesh, config, max_new_tokens, temperature,
                               top_k)
    return fn(params, prompt, rng)


import functools as _functools


@_functools.lru_cache(maxsize=32)
def _generate_tp_compiled(mesh, config, max_new_tokens, temperature, top_k):
    """Cached shard_map+jit program per (mesh, config, decode params) —
    rebuilding the closure per call would recompile every time."""
    from jax.sharding import PartitionSpec as P

    from pytorch_distributed_tpu.parallel.mesh import MODEL_AXIS, shard_map
    from pytorch_distributed_tpu.parallel.tensor import match_partition_rules
    from pytorch_distributed_tpu.train.lm import TRANSFORMER_TP_RULES

    rules = [
        (pat, P(*(config.model_axis if part == MODEL_AXIS else part
                  for part in spec)))
        for pat, spec in TRANSFORMER_TP_RULES
    ]

    def local(params, prompt, rng):
        return _generate_core(config, params, prompt, rng, max_new_tokens,
                              temperature, top_k)

    def build(params, prompt, rng):
        param_specs = match_partition_rules(rules, params)
        fn = shard_map(
            local,
            mesh=mesh,
            in_specs=(param_specs, P(), P()),
            out_specs=P(),
            check_vma=False,
        )
        return fn(params, prompt, rng)

    return jax.jit(build)


@partial(
    jax.jit,
    static_argnames=("config", "max_new_tokens", "temperature", "top_k"),
)
def generate(
    config: TransformerConfig,
    params,
    prompt: jax.Array,  # [B, L_prompt] int32
    rng: jax.Array,
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
) -> jax.Array:
    """Generate ``max_new_tokens`` continuations of ``prompt``.

    Returns ``[B, L_prompt + max_new_tokens]``. ``temperature=0`` is
    greedy; ``top_k`` restricts sampling to the k highest logits.
    """
    _validate_generate_args(config, prompt, max_new_tokens, temperature,
                            top_k)
    if config.model_axis is not None:
        raise ValueError(
            "generate() runs replicated; for tensor-parallel decoding use "
            "generate_tp(mesh, config, params, ...) — or clear "
            "model_axis/tp_size (checkpoints are interchangeable across tp "
            "degrees, so TP-trained params load into the replicated config)"
        )

    # Prefill (one batched causal forward filling the cache) + scan decode
    return _generate_core(config, params, prompt, rng, max_new_tokens,
                          temperature, top_k)
