"""Autoregressive generation with a KV cache.

Beyond the reference's surface (a training benchmark repo) but expected of
an LM framework: ONE batched causal forward prefills the cache over the
whole prompt (O(L²) parallel, not L sequential steps), then a ``lax.scan``
decodes with greedy / temperature / top-k sampling, each step attending
against the cached K/V only (O(L) per token). One compiled program total.

``position_offset`` is the single source of position truth throughout
(``models.transformer.Attention``): the cache write index, the attention
mask, and the positional embedding all derive from it, so a stale cache
and a wrong offset cannot silently disagree.

Dense-attention math (the cache IS the global sequence, so no ring is
needed at decode time); ``generate`` runs with replicated params,
``generate_tp`` shards the decode matmuls and the KV cache over the model
axis (Megatron layout). Deterministic under a fixed rng key.

Round 4 adds the ragged-serving layer: ``generate_ragged`` (per-request
prompt lengths, one compiled prefill + per-slot decode) and
``ContinuousBatcher`` (requests admitted/retired at token boundaries
across shared decode slots). Measured at 32 slots, GPT-2-small shape,
prompts 16-249: prefill 16.9 ms (269k prompt-tok/s), decode 7,108 tok/s
(4.5 ms/token across slots) — scripts/bench_serving.py. The scope
boundary is stated at the ragged section below.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_tpu.models.transformer import (
    TransformerConfig,
    TransformerLM,
)


def init_cache(config: TransformerConfig, params, batch_size: int):
    """Zero decode cache; shapes via ``eval_shape`` (nothing is traced into
    any compiled program, let alone executed)."""
    model = TransformerLM(config)
    _, shapes = jax.eval_shape(
        lambda p: model.apply(
            {"params": p},
            jnp.zeros((batch_size, 1), jnp.int32),
            position_offset=0,
            decode=True,
            mutable=["cache"],
        ),
        params,
    )
    return jax.tree.map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes["cache"]
    )


def _sample(logits, rng, temperature: float, top_k: Optional[int]):
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / jnp.maximum(temperature, 1e-6)
    if top_k is not None:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -1e30, logits)
    return jax.random.categorical(rng, logits).astype(jnp.int32)


def _validate_sampling(config, temperature, top_k):
    if temperature < 0.0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if top_k is not None and not 1 <= top_k <= config.vocab_size:
        raise ValueError(
            f"top_k must be in [1, vocab_size={config.vocab_size}], "
            f"got {top_k}"
        )


def _validate_dense_decode(config):
    if getattr(config, "attention", "dense") != "dense":
        raise ValueError(
            "generation is dense-attention only (the KV cache IS the "
            "global sequence); build the decode config with "
            "attention='dense' — ring/ring_flash are training-time "
            "sequence-parallel layouts"
        )


def _validate_generate_args(config, prompt, max_new_tokens, temperature,
                            top_k):
    l_prompt = prompt.shape[1]
    if l_prompt < 1:
        raise ValueError("prompt must contain at least one token")
    if l_prompt + max_new_tokens > config.max_seq_len:
        raise ValueError(
            f"prompt ({l_prompt}) + max_new_tokens ({max_new_tokens}) "
            f"exceeds max_seq_len {config.max_seq_len}"
        )
    _validate_sampling(config, temperature, top_k)
    _validate_dense_decode(config)


def _generate_core(config, params, prompt, rng, max_new_tokens, temperature,
                   top_k):
    """The prefill + scan decode body; runs replicated or (under shard_map
    with a TP config) with Megatron collectives inside each apply."""
    model = TransformerLM(config)
    b, l_prompt = prompt.shape
    logits, variables = model.apply(
        {"params": params},
        prompt,
        position_offset=0,
        prefill=True,
        mutable=["cache"],
    )
    cache = variables["cache"]
    last_logits = logits[:, -1]

    def step(cache, token, pos):
        logits, variables = model.apply(
            {"params": params, "cache": cache},
            token[:, None],
            position_offset=pos,
            decode=True,
            mutable=["cache"],
        )
        return variables["cache"], logits[:, 0]

    def decode_body(carry, rng_step):
        cache, pos, logits = carry
        token = _sample(logits, rng_step, temperature, top_k)
        cache, next_logits = step(cache, token, pos)
        return (cache, pos + 1, next_logits), token

    rngs = jax.random.split(rng, max_new_tokens)
    _, tokens = jax.lax.scan(
        decode_body,
        (cache, jnp.asarray(l_prompt, jnp.int32), last_logits),
        rngs,
    )
    return jnp.concatenate([prompt, tokens.T], axis=1)


def generate_tp(
    mesh,
    config: TransformerConfig,
    params,
    prompt: jax.Array,
    rng: jax.Array,
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
) -> jax.Array:
    """Tensor-parallel generation: the decode step's matmuls and the KV
    cache shard over ``config.model_axis`` (qkv/proj by head, MLP by
    hidden dim — the cache inherits the local head count because the
    Attention module builds it from the sharded K/V it computes).

    ``params`` may be replicated or already placed by
    ``TRANSFORMER_TP_RULES``; either way the in_specs pin the Megatron
    layout and the output tokens come back replicated. Exact parity with
    replicated ``generate`` (tests/test_generate.py) — sampling happens on
    replicated logits with the same keys.
    """
    from jax.sharding import PartitionSpec as P

    from pytorch_distributed_tpu.parallel.mesh import shard_map
    from pytorch_distributed_tpu.parallel.tensor import match_partition_rules
    from pytorch_distributed_tpu.train.lm import TRANSFORMER_TP_RULES

    if config.model_axis is None or config.tp_size <= 1:
        raise ValueError(
            "generate_tp needs a TP config (model_axis + tp_size > 1); "
            "use generate() for replicated decoding"
        )
    if mesh.shape[config.model_axis] != config.tp_size:
        raise ValueError(
            f"mesh {config.model_axis!r} size "
            f"{mesh.shape[config.model_axis]} != tp_size {config.tp_size}"
        )
    _validate_generate_args(config, prompt, max_new_tokens, temperature,
                            top_k)
    fn = _generate_tp_compiled(mesh, config, max_new_tokens, temperature,
                               top_k)
    return fn(params, prompt, rng)


import functools as _functools


@_functools.lru_cache(maxsize=32)
def _generate_tp_compiled(mesh, config, max_new_tokens, temperature, top_k):
    """Cached shard_map+jit program per (mesh, config, decode params) —
    rebuilding the closure per call would recompile every time."""
    from jax.sharding import PartitionSpec as P

    from pytorch_distributed_tpu.parallel.mesh import shard_map
    from pytorch_distributed_tpu.parallel.tensor import match_partition_rules

    rules = _tp_rules(config)  # ONE rule builder for all TP entry points

    def local(params, prompt, rng):
        return _generate_core(config, params, prompt, rng, max_new_tokens,
                              temperature, top_k)

    def build(params, prompt, rng):
        param_specs = match_partition_rules(rules, params)
        fn = shard_map(
            local,
            mesh=mesh,
            in_specs=(param_specs, P(), P()),
            out_specs=P(),
            check_vma=False,
        )
        return fn(params, prompt, rng)

    return jax.jit(build)


@partial(
    jax.jit,
    static_argnames=("config", "max_new_tokens", "temperature", "top_k"),
)
def generate(
    config: TransformerConfig,
    params,
    prompt: jax.Array,  # [B, L_prompt] int32
    rng: jax.Array,
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
) -> jax.Array:
    """Generate ``max_new_tokens`` continuations of ``prompt``.

    Returns ``[B, L_prompt + max_new_tokens]``. ``temperature=0`` is
    greedy; ``top_k`` restricts sampling to the k highest logits.
    """
    _validate_generate_args(config, prompt, max_new_tokens, temperature,
                            top_k)
    if config.model_axis is not None:
        raise ValueError(
            "generate() runs replicated; for tensor-parallel decoding use "
            "generate_tp(mesh, config, params, ...) — or clear "
            "model_axis/tp_size (checkpoints are interchangeable across tp "
            "degrees, so TP-trained params load into the replicated config)"
        )

    # Prefill (one batched causal forward filling the cache) + scan decode
    return _generate_core(config, params, prompt, rng, max_new_tokens,
                          temperature, top_k)


# ---------------------------------------------------------------------------
# Ragged serving: per-request prompt lengths + continuous decode slots.
#
# Scope decision (VERDICT r3 weak #8, made explicit; r4 #7 quantified):
# this is the FRAMEWORK layer of serving — one compiled ragged prefill,
# one compiled per-slot decode step, and a host-side continuous batcher
# that admits and retires requests at token boundaries. It deliberately
# stops short of a serving SYSTEM (paged/attention-block KV memory,
# chunked prefill scheduling, streaming transports); dense attention, one
# shared max_seq_len cache per slot. The admission stall this leaves on
# the table is MEASURED (scripts/bench_serving.py --stall, BENCH_LM.md
# round 5): 4-6 ms per admission at 32 slots after fusing the row insert
# into the prefill program — an equilibrium throughput tax of ~31% at
# 64-token outputs (admissions are frequent) falling to ~10% at 256 —
# which is the number chunked prefill would be buying back. Accepted at
# this layer; round 5 adds tensor parallelism (mesh=) instead, which the
# r4 verdict ranked higher.
#
# Why right-padding needs no prefill mask: causal attention already hides
# a request's padded TAIL positions from its real tokens (they are in the
# future), and the decode mask (arange <= pos_b, per request) never reads
# beyond the slot's own write frontier — garbage K/V written for padding
# is overwritten by decoded tokens before it ever becomes visible.
# ---------------------------------------------------------------------------


def _validate_serving_config(config, mesh=None):
    _validate_dense_decode(config)
    if mesh is not None and config.model_axis is None:
        raise ValueError(
            "a mesh was passed but config.model_axis is unset — serving "
            "would silently run replicated on one device; set "
            "model_axis/tp_size (or drop mesh=)"
        )
    if config.model_axis is not None:
        if mesh is None:
            raise ValueError(
                "a TP config (model_axis set) needs the mesh: pass "
                "mesh= to ContinuousBatcher/generate_ragged_tp — or "
                "clear model_axis/tp_size for replicated serving"
            )
        if mesh.shape.get(config.model_axis) != config.tp_size:
            raise ValueError(
                f"mesh {config.model_axis!r} size "
                f"{mesh.shape.get(config.model_axis)} != tp_size "
                f"{config.tp_size}"
            )


def _tp_rules(config):
    """TP placement rules for serving: the Megatron layout remapped to
    the config's axis name, plus the vocab-parallel head/embedding when
    configured (same rule set ``_generate_tp_compiled`` uses)."""
    from jax.sharding import PartitionSpec as P

    from pytorch_distributed_tpu.parallel.mesh import MODEL_AXIS
    from pytorch_distributed_tpu.train.lm import TRANSFORMER_TP_RULES

    rules = [
        (pat, P(*(config.model_axis if part == MODEL_AXIS else part
                  for part in spec)))
        for pat, spec in TRANSFORMER_TP_RULES
    ]
    if getattr(config, "uses_vocab_parallel", lambda: False)():
        # THE shared predicate (TransformerConfig.uses_vocab_parallel) —
        # same condition the model's head branch and train/lm.py use
        from pytorch_distributed_tpu.train.lm import _vocab_rules

        rules += [(pat, P(*spec)) for pat, spec in _vocab_rules(config)]
    return rules


def _cache_specs(config, cache):
    """KV-cache placement: [B, L, H_kv, D] leaves shard their HEAD dim
    over the model axis — the same split the TP Attention computes, so
    each shard's cache slice is exactly the K/V its heads produce."""
    from jax.sharding import PartitionSpec as P

    return jax.tree.map(
        lambda _: P(None, None, config.model_axis, None), cache
    )


def _validate_ragged(config, prompts, max_new_tokens, temperature=0.0,
                     top_k=None):
    _validate_serving_config(config)
    _validate_sampling(config, temperature, top_k)
    # Static worst case: per-request lengths are runtime values, so the
    # trace-time bound assumes a full-length prompt (lengths[b] == L_max).
    # The batcher's host-side submit applies the EXACT per-request check.
    if prompts.shape[1] + max_new_tokens > config.max_seq_len:
        raise ValueError(
            f"padded prompt length ({prompts.shape[1]}) + max_new_tokens "
            f"({max_new_tokens}) exceeds max_seq_len {config.max_seq_len} "
            "(static worst case: a request may be full-length)"
        )


def ragged_prefill(config: TransformerConfig, params, prompts: jax.Array,
                   lengths: jax.Array):
    """ONE batched causal forward prefills every request's cache slice.

    ``prompts``: [B, L_max] right-padded int32; ``lengths``: [B] true
    prompt lengths (1 <= len <= L_max). Returns ``(cache, last_logits)``
    where ``last_logits[b]`` is the logits at request b's LAST REAL token
    (gathered at lengths-1) — the distribution for its first new token.
    """
    model = TransformerLM(config)
    logits, variables = model.apply(
        {"params": params}, prompts, position_offset=0, prefill=True,
        mutable=["cache"],
    )
    last = logits[jnp.arange(prompts.shape[0]), lengths - 1]
    return variables["cache"], last


def ragged_decode_step(config: TransformerConfig, params, cache,
                       tokens: jax.Array, positions: jax.Array):
    """Advance every slot one token: ``tokens`` [B] written at per-request
    cache ``positions`` [B]; returns ``(cache, logits [B, vocab])``."""
    model = TransformerLM(config)
    logits, variables = model.apply(
        {"params": params, "cache": cache},
        tokens[:, None],
        position_offset=positions,
        decode=True,
        mutable=["cache"],
    )
    return variables["cache"], logits[:, 0]


@partial(
    jax.jit,
    static_argnames=("config", "max_new_tokens", "temperature", "top_k"),
)
def generate_ragged(
    config: TransformerConfig,
    params,
    prompts: jax.Array,   # [B, L_max] right-padded int32
    lengths: jax.Array,   # [B] true prompt lengths
    rng: jax.Array,
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
) -> jax.Array:
    """Batched generation with PER-REQUEST prompt lengths, one compiled
    program. Returns ``[B, max_new_tokens]`` — request b's continuation
    starts at its own position ``lengths[b]`` (exact parity with
    per-request ``generate`` calls: tests/test_serving.py)."""
    _validate_ragged(config, prompts, max_new_tokens, temperature, top_k)
    cache, last_logits = ragged_prefill(config, params, prompts, lengths)

    def body(carry, rng_step):
        cache, pos, logits = carry
        token = _sample(logits, rng_step, temperature, top_k)
        cache, nxt = ragged_decode_step(config, params, cache, token, pos)
        return (cache, pos + 1, nxt), token

    rngs = jax.random.split(rng, max_new_tokens)
    _, tokens = jax.lax.scan(
        body, (cache, lengths.astype(jnp.int32), last_logits), rngs
    )
    return tokens.T  # [B, max_new_tokens]


def generate_ragged_tp(
    mesh,
    config: TransformerConfig,
    params,
    prompts: jax.Array,
    lengths: jax.Array,
    rng: jax.Array,
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
) -> jax.Array:
    """Tensor-parallel ``generate_ragged``: the whole prefill+scan body
    runs under shard_map over ``config.model_axis`` (params in Megatron
    layout, cache head-sharded, sampling on replicated logits — exact
    parity with the replicated path, tests/test_serving_tp.py)."""
    if config.model_axis is None or config.tp_size <= 1:
        raise ValueError(
            "generate_ragged_tp needs a TP config (model_axis + "
            "tp_size > 1); use generate_ragged() for replicated serving"
        )
    _validate_serving_config(config, mesh)
    _validate_sampling(config, temperature, top_k)
    if prompts.shape[1] + max_new_tokens > config.max_seq_len:
        raise ValueError(
            f"padded prompt length ({prompts.shape[1]}) + max_new_tokens "
            f"({max_new_tokens}) exceeds max_seq_len {config.max_seq_len}"
        )
    fn = _generate_ragged_tp_compiled(mesh, config, max_new_tokens,
                                      temperature, top_k)
    return fn(params, prompts, lengths, rng)


@_functools.lru_cache(maxsize=32)
def _generate_ragged_tp_compiled(mesh, config, max_new_tokens, temperature,
                                 top_k):
    from jax.sharding import PartitionSpec as P

    from pytorch_distributed_tpu.parallel.mesh import shard_map
    from pytorch_distributed_tpu.parallel.tensor import match_partition_rules

    def local(params, prompts, lengths, rng):
        cache, last_logits = ragged_prefill(config, params, prompts,
                                            lengths)

        def body(carry, rng_step):
            cache, pos, logits = carry
            token = _sample(logits, rng_step, temperature, top_k)
            cache, nxt = ragged_decode_step(config, params, cache, token,
                                            pos)
            return (cache, pos + 1, nxt), token

        rngs = jax.random.split(rng, max_new_tokens)
        _, tokens = jax.lax.scan(
            body, (cache, lengths.astype(jnp.int32), last_logits), rngs
        )
        return tokens.T

    def build(params, prompts, lengths, rng):
        param_specs = match_partition_rules(_tp_rules(config), params)
        fn = shard_map(
            local, mesh=mesh,
            in_specs=(param_specs, P(), P(), P()),
            out_specs=P(),
            check_vma=False,
        )
        return fn(params, prompts, lengths, rng)

    return jax.jit(build)


class ContinuousBatcher:
    """Continuous batching over ``n_slots`` decode lanes (host-side
    scheduler around compiled programs).

    ``submit`` prefills ONE request into a free slot; ``step`` advances
    ALL active slots one token and retires slots that hit their budget.
    Requests therefore enter and leave at token boundaries while others
    keep decoding.

    Round 6: the default cache is the block-pooled PAGED layout
    (``cache_layout="paged"``, ``pytorch_distributed_tpu.serving``) —
    admission allocates fresh KV blocks and writes O(prompt), never
    copying resident requests' KV; the round-4 dense layout (one
    ``max_seq_len`` KV row per slot, admission writing the full row)
    survives as ``cache_layout="dense"`` for parity tests and A/B
    benches. Both layouts produce token-identical greedy streams
    (tests/test_paged_serving.py). ``prefill_bucket`` is the prompt
    padding granularity in both: the dense prefill pads prompts to it;
    the paged engine uses it as the chunk length. For queueing instead
    of submit-time failure (and chunked prefill interleaved with
    decode), use ``serving.Scheduler``.
    """

    def __init__(self, config: TransformerConfig, params, n_slots: int,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 prefill_bucket: int = 128, seed: int = 0,
                 eos_id: Optional[int] = None, mesh=None,
                 cache_layout: str = "paged", block_len: int = 16,
                 n_blocks: Optional[int] = None,
                 gather_impl: Optional[str] = None,
                 kv_dtype: Optional[str] = None,
                 split_s: Optional[int] = None,
                 autotune_dir: Optional[str] = None):
        _validate_serving_config(config, mesh)
        _validate_sampling(config, temperature, top_k)
        if eos_id is not None and not 0 <= eos_id < config.vocab_size:
            raise ValueError(
                f"eos_id {eos_id} outside [0, vocab_size={config.vocab_size})"
            )
        if cache_layout not in ("paged", "dense"):
            raise ValueError(
                f"cache_layout {cache_layout!r} must be 'paged' (block-"
                "pooled KV, O(prompt) admission) or 'dense' (one "
                "max_seq_len row per slot, the r4 layout)"
            )
        self.eos_id = eos_id
        self.config = config
        self.n_slots = n_slots
        self.temperature = temperature
        self.top_k = top_k
        self.prefill_bucket = prefill_bucket
        self.cache_layout = cache_layout
        if cache_layout != "paged" and (gather_impl not in (None, "dense")
                                        or kv_dtype is not None
                                        or split_s is not None
                                        or autotune_dir is not None):
            raise ValueError(
                "gather_impl=/kv_dtype=/split_s=/autotune_dir= are "
                "block-pool knobs (the dense layout has no block tables "
                "to gather through, no quantized pool, and no chain "
                "sweep to split); use cache_layout='paged'"
            )
        if cache_layout == "paged":
            from pytorch_distributed_tpu.serving.engine import PagedEngine

            self.engine = PagedEngine(
                config, params, n_slots, n_blocks=n_blocks,
                block_len=block_len, prefill_chunk=prefill_bucket,
                temperature=temperature, top_k=top_k, mesh=mesh,
                gather_impl=gather_impl, kv_dtype=kv_dtype,
                split_s=split_s, autotune_dir=autotune_dir,
            )
            self.config = self.engine.config  # gather_impl=/split_s= in
            self.mesh = mesh
            self.params = self.engine.params
            self.positions = np.zeros(n_slots, np.int32)
            self.remaining = np.zeros(n_slots, np.int32)
            self._rng = jax.random.key(seed)
            return
        self.engine = None
        tp = config.model_axis is not None
        # Cache shapes are GLOBAL (full head count — from a collective-free
        # twin config); under TP, placement shards the head dim over the
        # model axis, matching the slice each shard's Attention computes.
        import dataclasses as _dc

        init_cfg = (
            _dc.replace(config, model_axis=None, tp_size=1) if tp else config
        )
        self.cache = init_cache(init_cfg, params, n_slots)
        self.positions = np.zeros(n_slots, np.int32)
        self.remaining = np.zeros(n_slots, np.int32)
        self.logits = jnp.zeros((n_slots, config.vocab_size), jnp.float32)
        self._rng = jax.random.key(seed)

        cfg = config
        temp, topk = temperature, top_k

        def _submit_body(params, prompt, length, cache, logits, slot):
            # prefill + row insert in ONE program, big cache donated:
            # measured separately (scripts/bench_serving.py --stall) the
            # standalone insert cost ~8 ms/admission — a full-cache copy
            # XLA elides when the write lives in the same program as the
            # producer
            row_cache, row_logits = ragged_prefill(cfg, params, prompt,
                                                   length)
            cache = jax.tree.map(
                lambda big, row: big.at[slot].set(row[0]), cache,
                row_cache,
            )
            return cache, logits.at[slot].set(row_logits[0])

        def _step_body(params, cache, logits, positions, active, rng):
            tokens = _sample(logits, rng, temp, topk)
            new_cache, new_logits = ragged_decode_step(
                cfg, params, cache, tokens, positions
            )
            # Inactive rows' cache/logits are DEAD state: a retired slot's
            # whole row is replaced by the next submit before it is read, so
            # their garbage decode writes need no freeze (and freezing
            # would read+select the multi-GB cache every token). Only the
            # positions stay frozen — submit() reads them.
            positions = jnp.where(active, positions + 1, positions)
            return new_cache, new_logits, positions, tokens

        if tp:
            # TP serving (round 5, lifting the r4 replicated-only scope):
            # the prefill/decode programs run under shard_map over the
            # model axis — Megatron collectives inside each apply, KV
            # cache head-sharded at rest, logits/sampling replicated so
            # every shard retires the same tokens.
            from jax.sharding import NamedSharding, PartitionSpec as P

            from pytorch_distributed_tpu.parallel.mesh import shard_map
            from pytorch_distributed_tpu.parallel.tensor import (
                match_partition_rules,
            )

            self.mesh = mesh
            param_specs = match_partition_rules(_tp_rules(cfg), params)
            cache_specs = _cache_specs(cfg, self.cache)
            self.params = jax.device_put(
                params,
                jax.tree.map(
                    lambda s: NamedSharding(mesh, s), param_specs
                ),
            )
            self.cache = jax.device_put(
                self.cache,
                jax.tree.map(
                    lambda s: NamedSharding(mesh, s), cache_specs
                ),
            )
            self._submit_one = jax.jit(shard_map(
                _submit_body, mesh=mesh,
                in_specs=(param_specs, P(), P(), cache_specs, P(), P()),
                out_specs=(cache_specs, P()),
                check_vma=False,
            ), donate_argnums=(3, 4))
            self._step_fn = jax.jit(shard_map(
                _step_body, mesh=mesh,
                in_specs=(param_specs, cache_specs, P(), P(), P(), P()),
                out_specs=(cache_specs, P(), P(), P()),
                check_vma=False,
            ), donate_argnums=(1, 2))
        else:
            self.mesh = None
            self.params = params
            self._submit_one = jax.jit(_submit_body, donate_argnums=(3, 4))
            self._step_fn = jax.jit(_step_body, donate_argnums=(1, 2))

    @property
    def cache(self):
        """The KV cache pytree: the block POOL under the paged layout
        (leaves ``[n_blocks, block_len, H_kv, D]``), per-slot dense rows
        (``[n_slots, max_seq_len, H_kv, D]``) under the dense one."""
        return self.engine.cache if self.engine is not None else self._cache

    @cache.setter
    def cache(self, value):
        if self.engine is not None:
            self.engine.cache = value
        else:
            self._cache = value

    @property
    def logits(self):
        return (
            self.engine.logits if self.engine is not None else self._logits
        )

    @logits.setter
    def logits(self, value):
        if self.engine is not None:
            self.engine.logits = value
        else:
            self._logits = value

    def free_slots(self):
        return [i for i in range(self.n_slots) if self.remaining[i] == 0]

    def _validate_submit(self, l: int, max_new_tokens: int) -> None:
        if l < 1:
            raise ValueError("prompt must contain at least one token")
        pad = -l % self.prefill_bucket
        # exact per-request bounds: the prefill writes l+pad cache rows
        # (pad garbage is dead — overwritten before the decode mask can
        # reach it) and decode reaches position l+max_new_tokens-1
        if l + pad > self.config.max_seq_len:
            raise ValueError(
                f"prompt ({l}) padded to {l + pad} exceeds max_seq_len "
                f"{self.config.max_seq_len}"
            )
        if l + max_new_tokens > self.config.max_seq_len:
            raise ValueError(
                f"prompt ({l}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds max_seq_len {self.config.max_seq_len}"
            )

    def submit(self, prompt: np.ndarray, max_new_tokens: int) -> int:
        """Admit one request ([L] int32); returns its slot. Raises if no
        slot is free or the budget exceeds the cache.

        Paged layout: admission allocates the request's block chain and
        prefills O(prompt) — chunk-program writes into FRESH blocks; no
        resident request's KV is copied (the r5 admission tax is gone:
        the dense layout wrote a full max_seq_len row here). With the
        default pool size a free slot always implies free blocks; an
        explicitly undersized ``n_blocks`` can raise on pool exhaustion
        — use ``serving.Scheduler`` when you want queueing instead."""
        free = self.free_slots()
        if not free:
            raise RuntimeError("no free decode slot; call step() to drain")
        slot = free[0]
        l = len(prompt)
        self._validate_submit(l, max_new_tokens)
        if self.engine is not None:
            from pytorch_distributed_tpu.serving.engine import ChunkJob

            if not self.engine.admit(slot, l, max_new_tokens):
                raise RuntimeError(
                    "KV block pool exhausted (custom n_blocks below slot "
                    "capacity); retire requests, raise n_blocks, or use "
                    "serving.Scheduler to queue admissions"
                )
            c = self.engine.chunk
            prompt = np.asarray(prompt, np.int32).reshape(-1)
            for start in range(0, l, c):
                seg = prompt[start:start + c]
                tokens = np.zeros((c,), np.int32)
                tokens[:len(seg)] = seg
                is_last = start + c >= l
                # chunks run in order: chunk n+1 attends to chunk n's
                # pool writes
                self.engine.run_chunks([ChunkJob(
                    slot=slot, tokens=tokens, start=start, is_last=is_last,
                    last_idx=(l - 1 - start) if is_last else 0,
                )])
            self.positions[slot] = l
            self.remaining[slot] = max_new_tokens
            return slot
        pad = -l % self.prefill_bucket
        padded = np.zeros((1, l + pad), np.int32)
        padded[0, :l] = prompt
        self.cache, self.logits = self._submit_one(
            self.params, jnp.asarray(padded), jnp.asarray([l], jnp.int32),
            self.cache, self.logits, jnp.asarray(slot),
        )
        self.positions[slot] = l
        self.remaining[slot] = max_new_tokens
        return slot

    def step(self):
        """One decode tick for every active slot. Returns
        ``[(slot, token)]`` for the tokens produced this tick (an EOS
        token is returned AND retires its slot immediately when
        ``eos_id`` is set — the slot frees for the next submit)."""
        active_np = self.remaining > 0
        if not active_np.any():
            return []
        self._rng, sub = jax.random.split(self._rng)
        if self.engine is not None:
            toks, self.positions = self.engine.decode(
                self.positions, active_np, sub
            )
        else:
            cache, logits, positions, tokens = self._step_fn(
                self.params, self.cache, self.logits,
                jnp.asarray(self.positions), jnp.asarray(active_np), sub,
            )
            self.cache, self.logits = cache, logits
            self.positions = np.array(positions)  # owned, writable copy
            toks = np.asarray(tokens)
        out = []
        for slot in np.nonzero(active_np)[0]:
            token = int(toks[slot])
            out.append((int(slot), token))
            if self.eos_id is not None and token == self.eos_id:
                self.remaining[slot] = 0  # early retirement
            else:
                self.remaining[slot] -= 1
            if self.engine is not None and self.remaining[slot] == 0:
                # retirement returns the block chain to the pool (LIFO
                # reuse) and routes the dead lane's writes to trash
                self.engine.release(int(slot))
        return out
