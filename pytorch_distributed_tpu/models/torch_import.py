"""torchvision → flax ResNet weight import.

The reference's entire correctness bar is torchvision's ResNet-50 reaching
top-1/top-5 on ImageNet (``restnet_ddp.py:58-70``, ``README.md:20-24``).
A full ImageNet run is impossible in this environment, so the honest proxy
is *numerical parity*: torchvision weights imported through this module
must produce the same logits as the torch model on the same batch (tested
in tests/test_torch_parity.py, both eval and train/batch-stats mode, plus
an identical-data SGD loss-trajectory comparison).

Layout translations:
- conv weights OIHW → HWIO (``transpose(2, 3, 1, 0)``);
- linear weights [out, in] → kernel [in, out];
- BatchNorm weight/bias → scale/bias params; running_mean/var → batch_stats
  (torch momentum 0.1 ≡ flax momentum 0.9 — already the model default);
- torch module names → the flax module tree (layer1.0.conv2 →
  stage1_block1.Conv_1, downsample.0/1 → downsample_conv/downsample_bn).

Works on any state_dict of the right architecture — pretrained
(``torchvision.models.resnet50(weights=...)``) or fresh — because the
mapping is purely structural. Inputs must be NHWC and preprocessed the
same way (this repo's transforms already match torchvision's normalize).
"""

from __future__ import annotations

from typing import Mapping, Sequence

import numpy as np


def _np(t) -> np.ndarray:
    # torch tensors (detached) or arrays both land as fp32 numpy. COPY —
    # torch's .numpy() aliases the live parameter/buffer storage, and a
    # later torch forward (BN running-stat update) would silently mutate
    # the imported tree through the shared memory.
    a = t.detach().cpu().numpy() if hasattr(t, "detach") else t
    return np.array(a, np.float32, copy=True)


def _conv(sd: Mapping, name: str) -> np.ndarray:
    return _np(sd[name]).transpose(2, 3, 1, 0)  # OIHW → HWIO


def _bn(sd: Mapping, name: str):
    params = {"scale": _np(sd[f"{name}.weight"]), "bias": _np(sd[f"{name}.bias"])}
    stats = {"mean": _np(sd[f"{name}.running_mean"]),
             "var": _np(sd[f"{name}.running_var"])}
    return params, stats


def import_resnet_state(
    state_dict: Mapping,
    stage_sizes: Sequence[int],
    bottleneck: bool = True,
) -> dict:
    """Translate a torchvision ResNet ``state_dict`` into flax variables.

    Returns ``{"params": ..., "batch_stats": ...}`` ready for
    ``model.apply(variables, x, train=False, mutable=False)`` on the
    matching ``models.resnet`` builder (same ``stage_sizes``/block type).

    ``bottleneck`` selects the block naming: ResNet-50/101/152 use three
    convs per block (torch conv1/2/3 → flax Conv_0/1/2), ResNet-18/34 two
    (flax auto-names them Conv_0/Conv_1).
    """
    params: dict = {}
    stats: dict = {}

    params["conv_init"] = {"kernel": _conv(state_dict, "conv1.weight")}
    bn_p, bn_s = _bn(state_dict, "bn1")
    params["bn_init"], stats["bn_init"] = bn_p, bn_s

    n_convs = 3 if bottleneck else 2
    for i, stage_size in enumerate(stage_sizes):
        for j in range(stage_size):
            tname = f"layer{i + 1}.{j}"
            fname = f"stage{i + 1}_block{j + 1}"
            bp: dict = {}
            bs: dict = {}
            for c in range(n_convs):
                bp[f"Conv_{c}"] = {
                    "kernel": _conv(state_dict, f"{tname}.conv{c + 1}.weight")
                }
                p, s = _bn(state_dict, f"{tname}.bn{c + 1}")
                bp[f"BatchNorm_{c}"], bs[f"BatchNorm_{c}"] = p, s
            if f"{tname}.downsample.0.weight" in state_dict:
                bp["downsample_conv"] = {
                    "kernel": _conv(state_dict, f"{tname}.downsample.0.weight")
                }
                p, s = _bn(state_dict, f"{tname}.downsample.1")
                bp["downsample_bn"], bs["downsample_bn"] = p, s
            params[fname] = bp
            stats[fname] = bs

    params["fc"] = {
        "kernel": _np(state_dict["fc.weight"]).T,
        "bias": _np(state_dict["fc.bias"]),
    }
    return {"params": params, "batch_stats": stats}


def export_resnet_state(variables: Mapping, bottleneck: bool = True) -> dict:
    """Inverse of :func:`import_resnet_state`: flax variables → a torch-style
    ``state_dict`` of numpy arrays (load with
    ``model.load_state_dict({k: torch.from_numpy(v) ...})``). Round-trips
    bit-exactly; lets torch tooling consume checkpoints trained here."""
    params, stats = variables["params"], variables["batch_stats"]
    sd: dict = {}

    def put_conv(name, kernel):
        sd[name] = np.asarray(kernel, np.float32).transpose(3, 2, 0, 1)

    def put_bn(name, p, s):
        sd[f"{name}.weight"] = np.asarray(p["scale"], np.float32)
        sd[f"{name}.bias"] = np.asarray(p["bias"], np.float32)
        sd[f"{name}.running_mean"] = np.asarray(s["mean"], np.float32)
        sd[f"{name}.running_var"] = np.asarray(s["var"], np.float32)

    put_conv("conv1.weight", params["conv_init"]["kernel"])
    put_bn("bn1", params["bn_init"], stats["bn_init"])

    n_convs = 3 if bottleneck else 2
    for fname in params:
        if not fname.startswith("stage"):
            continue
        stage, block = fname.removeprefix("stage").split("_block")
        tname = f"layer{stage}.{int(block) - 1}"
        bp, bs = params[fname], stats[fname]
        for c in range(n_convs):
            put_conv(f"{tname}.conv{c + 1}.weight", bp[f"Conv_{c}"]["kernel"])
            put_bn(f"{tname}.bn{c + 1}", bp[f"BatchNorm_{c}"], bs[f"BatchNorm_{c}"])
        if "downsample_conv" in bp:
            put_conv(f"{tname}.downsample.0.weight", bp["downsample_conv"]["kernel"])
            put_bn(f"{tname}.downsample.1", bp["downsample_bn"], bs["downsample_bn"])

    sd["fc.weight"] = np.asarray(params["fc"]["kernel"], np.float32).T
    sd["fc.bias"] = np.asarray(params["fc"]["bias"], np.float32)
    return sd
