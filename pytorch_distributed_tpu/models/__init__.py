from pytorch_distributed_tpu.models.generate import generate
from pytorch_distributed_tpu.models.transformer import (
    TransformerConfig,
    TransformerLM,
    tiny_config,
)
from pytorch_distributed_tpu.models.resnet import (
    ResNet,
    resnet18,
    resnet34,
    resnet50,
    resnet101,
    resnet152,
)

__all__ = [
    "generate",
    "TransformerConfig",
    "TransformerLM",
    "tiny_config",
    "ResNet",
    "resnet18",
    "resnet34",
    "resnet50",
    "resnet101",
    "resnet152",
]
