"""HTTP/SSE serving front door over the fleet (round 22).

``server.Gateway`` mounts ``POST /v1/generate`` (SSE token streaming),
``GET /v1/health`` (the PR 17 health plane), and the Prometheus
``/metrics`` over a ``FleetRouter``, with ``X-Deadline-Ms`` → admission
deadlines, ``SLOGate`` shed → 429 + ``Retry-After``, and client
disconnect → ``FleetRouter.cancel`` (blocks freed, span tree closed
``outcome=cancelled``). ``client`` is the stdlib SSE client the tests
and ``bench_serving.py --http`` drive it with. ANALYSIS.md "Front
door" documents the protocol.
"""

from pytorch_distributed_tpu.gateway.client import (
    GatewayError,
    SSEStream,
    generate,
    health,
    metrics_text,
    open_stream,
)
from pytorch_distributed_tpu.gateway.server import Gateway

__all__ = [
    "Gateway",
    "GatewayError",
    "SSEStream",
    "generate",
    "health",
    "metrics_text",
    "open_stream",
]
