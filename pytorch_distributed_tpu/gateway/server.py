"""HTTP/SSE front door: streaming ingress over the serving fleet.

Round 22 (ROADMAP item 5, the last open half). Every request used to
enter through in-process ``FleetRouter.submit`` calls, so nothing ever
exercised the real front-door semantics a vLLM-style server lives
behind: sockets, token streaming, client disconnects. This module is
that front end — stdlib-only (the PR 8 ``/metrics`` exporter's
``ThreadingHTTPServer`` approach, no new deps):

- ``POST /v1/generate`` — SSE token streaming (``text/event-stream``):
  one ``event: token`` per materialized token, then one ``event: done``
  carrying the request's true outcome + usage. Body is JSON
  ``{"prompt": [token ids], "max_new_tokens": N, "session": S?}``.
- ``GET /v1/health`` — the per-replica health-plane states (PR 17's
  healthy/suspect/dead/draining/rejoining records) + routable count.
- ``GET /metrics`` — Prometheus text: the router's fleet rollup
  (snapshotted on the driver thread — scrapes never race the host
  loop) merged with the gateway's own ``gateway_*`` gauges.

The ingress maps onto the EXISTING control planes instead of inventing
new ones:

- ``X-Deadline-Ms`` header → the PR 17 admission deadline
  (``deadline_s``); a lapsed-at-admission budget sheds through the
  ``SLOGate`` with reason ``deadline-expired`` exactly like an
  in-process submit.
- ``SLOGate`` SHED → HTTP 429 with ``Retry-After`` and the gate's
  reason in a JSON body; SPILL/QUEUE/PREEMPT admit as usual (they are
  backpressure, not failure — the client just sees a slower TTFT).
- client disconnect → ``FleetRouter.cancel(rid)``: a broken pipe on an
  SSE write, or a socket the peer closed while the request was still
  queued (probed with ``select`` + ``MSG_PEEK`` between token waits),
  detaches the stream and queues a cancel for the driver thread. The
  PR 16 cancel path frees the KV blocks and closes the span tree with
  ``outcome=cancelled``; the blocksan disconnect-storm acceptance in
  ``tests/test_gateway.py`` proves zero leaked blocks over real
  sockets.
- malformed input (bad JSON, non-numeric ``X-Deadline-Ms``, a prompt
  the scheduler's admission validator rejects) → 400 with a JSON error
  body — never a stack trace down the socket.

Threading model (``rules_threads``-clean): ONE driver thread owns the
``FleetRouter`` — it drains handler-side ingress/cancel queues, calls
``submit``/``cancel``/``step``, and fans tokens out to bounded,
census-declared per-rid queues (``_Stream.buf``). HTTP handler threads
(spawned by ``ThreadingHTTPServer``) never touch the router; they talk
to the driver exclusively through ``_lock``-guarded queues and wait on
``_wake``. A per-rid queue that overflows (a consumer slower than the
decode tick for ``stream_queue_cap`` tokens) cancels the request —
that is the bounded-backpressure promise the census audits, not a
silent drop. The router's ``on_retire`` hook (fired on the driver
thread, before the final token fans out) closes each stream with its
true outcome, so the terminal SSE event and the span tree always
agree.

    router = FleetRouter(cfg, params, async_host=True,
                         retain_results=False, ...)
    with Gateway(router, port=8000) as gw:
        ...  # curl -N -X POST :8000/v1/generate -d '{"prompt": [1,2]}'

``port=0`` binds an ephemeral port (tests); ``.port`` reports it.
``recipes/serve_lm.py --http-port`` mounts this over the existing
fleet flags; ``scripts/bench_serving.py --http`` drives the heavy-tail
trace through it over real sockets (``serving_http_*``); ANALYSIS.md
"Front door" documents the status-code ↔ gate-ladder mapping.
"""

from __future__ import annotations

import json
import select
import socket
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

import numpy as np

from pytorch_distributed_tpu.telemetry import LatencySeries, prometheus_text
from pytorch_distributed_tpu.telemetry.census import Decl

#: replica states the router will still route to (fleet.router._ROUTABLE
#: re-stated here so /v1/health has no import-order coupling)
_ROUTABLE = ("healthy", "suspect")

_SSE_HEADERS = (
    ("Content-Type", "text/event-stream"),
    ("Cache-Control", "no-cache"),
    ("Connection", "close"),
)


class _Submit:
    """One handler→driver admission request; the handler blocks on
    ``event`` until the driver has routed it through the gate."""

    __slots__ = ("prompt", "max_new", "session", "deadline_s",
                 "event", "rid", "shed_reason", "error", "stream")

    def __init__(self, prompt, max_new, session, deadline_s):
        self.prompt = prompt
        self.max_new = max_new
        self.session = session
        self.deadline_s = deadline_s
        self.event = threading.Event()
        self.rid = -1
        self.shed_reason: Optional[str] = None
        self.error: Optional[str] = None
        self.stream: Optional["_Stream"] = None


class _Stream:
    """Driver→handler token channel for one admitted rid. All fields
    are guarded by the owning Gateway's ``_lock``."""

    __slots__ = ("rid", "prompt_len", "buf", "done", "outcome",
                 "detached", "detach_t", "done_t", "finished",
                 "nbytes", "ttft", "ntok", "deadline_ms")

    def __init__(self, rid: int, prompt_len: int):
        self.rid = rid
        self.prompt_len = prompt_len
        self.buf: deque = deque()
        self.done = False
        self.outcome: Optional[str] = None
        self.detached = False
        self.detach_t: Optional[float] = None
        self.done_t: Optional[float] = None
        self.finished = False  # popped + logged exactly once
        # wire facts stashed by a detaching handler so the driver-side
        # close still writes an honest per-connection record
        self.nbytes = 0
        self.ttft: Optional[float] = None
        self.ntok = 0
        self.deadline_ms = None


def _client_gone(conn) -> bool:
    """True when the peer closed the connection: readable with zero
    bytes on a MSG_PEEK. A streaming client never sends after its
    request body, so readable ⇒ FIN (stray pipelined bytes read as
    alive, which only delays detection to the next write)."""
    try:
        r, _, _ = select.select([conn], [], [], 0)
        if not r:
            return False
        return conn.recv(1, socket.MSG_PEEK) == b""
    except (OSError, ValueError):
        return True


class Gateway:
    """Serve a ``FleetRouter`` over HTTP with SSE token streaming."""

    def __init__(self, router, port: int = 0, host: str = "127.0.0.1", *,
                 metrics_log=None, stream_queue_cap: int = 512,
                 max_pending: int = 4096, max_body_bytes: int = 1 << 20,
                 stream_timeout_s: float = 600.0, poll_s: float = 0.05,
                 idle_sleep_s: float = 0.002, prefix: str = "pdt"):
        self.router = router
        self.metrics_log = metrics_log
        self.stream_queue_cap = int(stream_queue_cap)
        self.max_pending = int(max_pending)
        self.max_body_bytes = int(max_body_bytes)
        self.stream_timeout_s = float(stream_timeout_s)
        self.poll_s = float(poll_s)
        self.idle_sleep_s = float(idle_sleep_s)
        self.prefix = prefix
        self._host = host
        self._requested_port = int(port)
        self.port: Optional[int] = None
        self._server: Optional[ThreadingHTTPServer] = None
        self._http_thread: Optional[threading.Thread] = None
        self._driver: Optional[threading.Thread] = None
        # ---- driver/handler shared state (all under _lock) ----
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._ingress: deque = deque()  # _Submit, handler → driver
        self._cancels: deque = deque()  # (rid, reason), handler → driver
        self._streams: Dict[int, _Stream] = {}
        self._retire_events: deque = deque()  # (rid, outcome, t)
        self._metrics_cache: Dict[str, float] = {}
        self._stop = False
        self._driver_error: Optional[str] = None
        # counters + wire-latency series (all mutated under _lock)
        self._conns = 0
        self._http_400 = 0
        self._http_429 = 0
        self._cancelled_total = 0
        self._completed = 0
        self._bytes_out = 0
        self._worst_gap_s = 0.0
        self.ttft_wire = LatencySeries("ttft_wire")
        self.gap = LatencySeries("gap")
        self.cancel_free = LatencySeries("cancel_free")

    # ---- lifecycle ----

    def start(self) -> "Gateway":
        if self._server is not None:
            return self
        self._refresh_metrics()
        self.router.on_retire = self._on_retire
        gw = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802 (stdlib API name)
                if self.path == "/v1/generate":
                    gw._handle_generate(self)
                else:
                    gw._send_json(self, 404, {"error": "not-found"})

            def do_GET(self):  # noqa: N802 (stdlib API name)
                if self.path == "/v1/health":
                    gw._handle_health(self)
                elif self.path in ("/metrics", "/"):
                    gw._handle_metrics(self)
                elif self.path == "/healthz":
                    gw._send_json(self, 200, {"ok": True})
                else:
                    gw._send_json(self, 404, {"error": "not-found"})

            def log_message(self, *args):  # silence per-request stderr
                pass

        self._server = ThreadingHTTPServer(
            (self._host, self._requested_port), Handler
        )
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._http_thread = threading.Thread(
            target=self._server.serve_forever, name="pdt-gateway-http",
            daemon=True,
        )
        self._http_thread.start()
        self._driver = threading.Thread(
            target=self._drive, name="pdt-gateway-driver", daemon=True,
        )
        self._driver.start()
        return self

    def stop(self) -> None:
        """Close the listener, fail queued admissions, end every open
        stream with ``outcome=shutdown``, and join the driver. The
        router is handed back non-drained — callers run the usual
        ``router.drain()`` epilogue (host-work flush + blocksan
        quiesce) themselves."""
        if self._server is None:
            return
        self._server.shutdown()
        self._server.server_close()
        if self._http_thread is not None:
            self._http_thread.join(timeout=5.0)
        with self._lock:
            self._stop = True
            for st in self._streams.values():
                if not st.done:
                    st.done = True
                    st.outcome = st.outcome or "shutdown"
                    st.done_t = time.perf_counter()
            self._wake.notify_all()
        if self._driver is not None:
            self._driver.join(timeout=30.0)
        self.router.on_retire = None
        self._server = None
        self._http_thread = None
        self._driver = None

    def __enter__(self) -> "Gateway":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ---- the driver thread: sole owner of the router ----

    def _drive(self) -> None:
        try:
            self._drive_loop()
        except Exception as e:  # noqa: BLE001 — the front door must
            # not wedge its handler threads on a router bug: fail every
            # open stream/queued admission loudly instead
            with self._lock:
                self._driver_error = repr(e)
                for st in self._streams.values():
                    if not st.done:
                        st.done = True
                        st.outcome = "error"
                        st.done_t = time.perf_counter()
                for sub in self._ingress:
                    sub.error = f"gateway driver failed: {e!r}"
                    sub.event.set()
                self._ingress.clear()
                self._wake.notify_all()

    def _drive_loop(self) -> None:
        n = 0
        while True:
            with self._lock:
                subs = list(self._ingress)
                self._ingress.clear()
                cancels = list(self._cancels)
                self._cancels.clear()
                stopping = self._stop
            for rid, reason in cancels:
                # synchronous: the PR 16 path frees blocks and fires the
                # retire hook (→ _retire_events) before this returns;
                # False = already terminal, idempotently nothing to do
                self.router.cancel(rid, reason=reason)
            for sub in subs:
                if stopping:
                    sub.error = "gateway shutting down"
                    sub.event.set()
                else:
                    self._admit(sub)
            busy = not self.router.idle
            out = self.router.step() if busy and not stopping else []
            self._deliver(out)
            if stopping and not subs and not cancels:
                break
            n += 1
            if n % 64 == 0:
                self._refresh_metrics()
            if not busy and not subs:
                time.sleep(self.idle_sleep_s)

    def _admit(self, sub: _Submit) -> None:
        """Route one handler admission through the gate. Runs on the
        driver thread; the shed contract is synchronous (a shed rid is
        in ``router.rejected`` when ``submit`` returns), so the waiting
        handler learns its 429 here, not from a poll."""
        try:
            rid = self.router.submit(
                np.asarray(sub.prompt, dtype=np.int32), sub.max_new,
                session=sub.session, deadline_s=sub.deadline_s,
            )
        except ValueError as e:
            # the scheduler's admission validator (empty prompt, prompt
            # past max_seq_len, budget overflow) — a client error
            sub.error = str(e)
            sub.event.set()
            return
        reason = self.router.rejected.get(rid)
        if reason is not None:
            sub.rid = rid
            sub.shed_reason = reason
            sub.event.set()
            return
        st = _Stream(rid, prompt_len=len(sub.prompt))
        with self._lock:
            self._streams[rid] = st
        sub.rid = rid
        sub.stream = st
        sub.event.set()

    def _on_retire(self, rid: int, outcome: str) -> None:
        """FleetRouter.on_retire hook — driver thread, mid-step."""
        with self._lock:
            self._retire_events.append((rid, outcome, time.perf_counter()))

    def _deliver(self, out: List[Tuple[int, int]]) -> None:
        """Fan this step's tokens out to their streams, then apply the
        step's retire events (tokens first: the retire hook fires
        mid-collect, before the final token reaches ``out``)."""
        overflowed: List[int] = []
        with self._lock:
            for rid, tok in out:
                st = self._streams.get(rid)
                if st is None or st.done:
                    continue
                if len(st.buf) >= self.stream_queue_cap:
                    if rid not in overflowed:
                        overflowed.append(rid)
                    continue
                st.buf.append(int(tok))
            retired = False
            while self._retire_events:
                rid, outcome, t = self._retire_events.popleft()
                st = self._streams.get(rid)
                if st is None:
                    continue
                retired = True
                if not st.done or (st.detached
                                   and st.outcome == "shutdown"):
                    st.done = True
                    st.outcome = outcome
                    st.done_t = t
                if st.detached:
                    # no handler will ever write the terminal event —
                    # close the books here (cancel-to-block-free lands
                    # in the latency series the bench quotes)
                    self._finish_detached_locked(st)
            if out or overflowed or retired:
                self._wake.notify_all()
        for rid in overflowed:
            # bounded-backpressure promise: a consumer slower than the
            # decode tick for stream_queue_cap tokens is cancelled, so
            # neither host memory nor KV blocks wait on a stuck socket
            self.router.cancel(rid, reason="slow-consumer")

    def _refresh_metrics(self) -> None:
        """Snapshot the fleet rollup on the driver thread so ``/metrics``
        scrapes never race the host loop."""
        try:
            snap = self.router.metrics()
        except Exception:  # noqa: BLE001 — a scrape cache refresh must
            return  # never kill the driver; the stale snapshot stands
        flat = {k: v for k, v in snap.items()
                if isinstance(v, (int, float, bool))}
        with self._lock:
            self._metrics_cache = flat

    # ---- stream bookkeeping (lock held where noted) ----

    def _finish_detached_locked(self, st: _Stream) -> None:
        if st.finished:
            return
        st.finished = True
        self._streams.pop(st.rid, None)
        if st.outcome == "cancelled":
            self._cancelled_total += 1  # jaxlint: disable=thread-unsynced-mutation -- _locked suffix: every caller (_deliver, stop) holds self._lock
            if st.detach_t is not None and st.done_t is not None:
                self.cancel_free.observe(max(st.done_t - st.detach_t, 0.0))
        self._log_http_locked(
            rid=st.rid, route="/v1/generate", status=200,
            deadline=st.deadline_ms, disconnect=True, nbytes=st.nbytes,
            ttft_wire=st.ttft, outcome=st.outcome, tokens=st.ntok,
            gap_max_ms=None,
        )

    def _finish_conn(self, st: _Stream, *, deadline_ms, nbytes: int,
                     ttft: Optional[float], ntok: int,
                     gaps: List[float]) -> None:
        """Handler-side normal completion: terminal event written."""
        with self._lock:
            if st.finished:
                return
            st.finished = True
            self._streams.pop(st.rid, None)
            self._completed += 1
            self._bytes_out += nbytes
            if ttft is not None:
                self.ttft_wire.observe(ttft)
            gap_max = 0.0
            for g in gaps:
                self.gap.observe(g)
                gap_max = max(gap_max, g)
            if gap_max > self._worst_gap_s:
                self._worst_gap_s = gap_max
            self._log_http_locked(
                rid=st.rid, route="/v1/generate", status=200,
                deadline=deadline_ms, disconnect=False, nbytes=nbytes,
                ttft_wire=ttft, outcome=st.outcome, tokens=ntok,
                gap_max_ms=round(gap_max * 1e3, 3) if gaps else None,
            )

    def _detach(self, st: _Stream, *, deadline_ms, nbytes: int,
                ttft: Optional[float], ntok: int, reason: str) -> None:
        """Handler-side disconnect: hand the rid to the driver for
        cancellation and stop touching the socket."""
        with self._lock:
            if st.finished:
                return
            self._bytes_out += nbytes
            if ttft is not None:
                self.ttft_wire.observe(ttft)
            if st.done:
                # raced its own retirement — nothing left to cancel
                st.finished = True
                self._streams.pop(st.rid, None)
                self._log_http_locked(
                    rid=st.rid, route="/v1/generate", status=200,
                    deadline=deadline_ms, disconnect=True, nbytes=nbytes,
                    ttft_wire=ttft, outcome=st.outcome, tokens=ntok,
                    gap_max_ms=None,
                )
                return
            st.detached = True
            st.detach_t = time.perf_counter()
            st.nbytes = nbytes
            st.ttft = ttft
            st.ntok = ntok
            st.deadline_ms = deadline_ms
            self._cancels.append((st.rid, reason))

    def _log_http_locked(self, *, rid: int, route: str, status: int,
                         deadline, disconnect: bool, nbytes: int,
                         ttft_wire: Optional[float], outcome=None,
                         tokens: Optional[int] = None, reason=None,
                         gap_max_ms=None) -> None:
        self._conns += 1  # jaxlint: disable=thread-unsynced-mutation -- _locked suffix: every caller holds self._lock (handlers via _finish_conn/_detach/_reject, driver via _deliver)
        if self.metrics_log is None:
            return
        self.metrics_log.log(
            kind="http", rid=rid, route=route, status=status,
            deadline=deadline, disconnect=bool(disconnect), bytes=nbytes,
            ttft_wire=(round(ttft_wire, 6)
                       if ttft_wire is not None else None),
            outcome=outcome, tokens=tokens, reason=reason,
            gap_max_ms=gap_max_ms,
            open=len(self._streams), queued=len(self._ingress),
        )

    # ---- HTTP handlers (ThreadingHTTPServer threads) ----

    def _send_json(self, h, status: int, body: dict,
                   headers: Tuple[Tuple[str, str], ...] = ()) -> None:
        payload = json.dumps(body).encode()
        try:
            h.send_response(status)
            h.send_header("Content-Type", "application/json")
            h.send_header("Content-Length", str(len(payload)))
            for k, v in headers:
                h.send_header(k, v)
            h.end_headers()
            h.wfile.write(payload)
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass  # peer gone before the error body landed — nothing owed

    def _reject(self, h, status: int, body: dict, *, route: str,
                rid: int = -1, deadline=None,
                headers: Tuple[Tuple[str, str], ...] = ()) -> None:
        with self._lock:
            if status == 400:
                self._http_400 += 1
            elif status == 429:
                self._http_429 += 1
            self._log_http_locked(
                rid=rid, route=route, status=status, deadline=deadline,
                disconnect=False, nbytes=0, ttft_wire=None,
                reason=body.get("reason") or body.get("error"),
            )
        self._send_json(h, status, body, headers)

    def _read_request(self, h):
        """(payload, deadline_ms, error_response) — error_response is a
        (status, body) pair when the request is malformed."""
        try:
            length = int(h.headers.get("Content-Length", ""))
        except ValueError:
            return None, None, (400, {"error": "missing-length"})
        if length > self.max_body_bytes:
            return None, None, (413, {"error": "body-too-large",
                                      "limit": self.max_body_bytes})
        try:
            raw = h.rfile.read(length)
            payload = json.loads(raw)
        except (ValueError, OSError):
            return None, None, (400, {"error": "bad-json"})
        if not isinstance(payload, dict):
            return None, None, (400, {"error": "bad-json"})
        deadline_ms = None
        header = h.headers.get("X-Deadline-Ms")
        if header is not None:
            try:
                deadline_ms = float(header)
            except ValueError:
                return None, None, (
                    400, {"error": "bad-deadline",
                          "detail": "X-Deadline-Ms must be numeric"})
        prompt = payload.get("prompt")
        if (not isinstance(prompt, list) or not prompt
                or not all(isinstance(t, int) and not isinstance(t, bool)
                           for t in prompt)):
            return None, None, (
                400, {"error": "bad-prompt",
                      "detail": "prompt must be a non-empty list of "
                                "token ids"})
        max_new = payload.get("max_new_tokens", 16)
        if not isinstance(max_new, int) or isinstance(max_new, bool) \
                or max_new < 1:
            return None, None, (
                400, {"error": "bad-max-new-tokens",
                      "detail": "max_new_tokens must be a positive int"})
        session = payload.get("session")
        if session is not None and not isinstance(session, int):
            return None, None, (
                400, {"error": "bad-session",
                      "detail": "session must be an int"})
        return (prompt, max_new, session), deadline_ms, None

    def _handle_generate(self, h) -> None:
        t0 = time.perf_counter()
        route = "/v1/generate"
        parsed, deadline_ms, err = self._read_request(h)
        if err is not None:
            self._reject(h, err[0], err[1], route=route,
                         deadline=deadline_ms)
            return
        prompt, max_new, session = parsed
        deadline_s = deadline_ms / 1e3 if deadline_ms is not None else None
        sub = _Submit(prompt, max_new, session, deadline_s)
        with self._lock:
            if self._stop or self._driver_error is not None:
                err = (503, {"error": "unavailable",
                             "detail": self._driver_error or "shutting down"})
            elif len(self._ingress) >= self.max_pending:
                err = (503, {"error": "overloaded"})
            else:
                err = None
                self._ingress.append(sub)
        if err is not None:
            self._reject(h, err[0], err[1], route=route,
                         deadline=deadline_ms)
            return
        if not sub.event.wait(timeout=30.0):
            self._reject(h, 503, {"error": "admission-timeout"},
                         route=route, deadline=deadline_ms)
            return
        if sub.error is not None:
            self._reject(h, 400, {"error": "invalid-request",
                                  "detail": sub.error},
                         route=route, deadline=deadline_ms)
            return
        if sub.shed_reason is not None:
            # the SLOGate ladder's SHED rung in HTTP: explicit, with a
            # hint to come back — reason strings are the gate's own
            # (queue_depth / slo_* / deadline-expired / draining / ...)
            self._reject(
                h, 429, {"error": "shed", "reason": sub.shed_reason,
                         "rid": sub.rid},
                route=route, rid=sub.rid, deadline=deadline_ms,
                headers=(("Retry-After", "1"),),
            )
            return
        self._stream_sse(h, sub, t0, deadline_ms)

    def _stream_sse(self, h, sub: _Submit, t0: float,
                    deadline_ms) -> None:
        st = sub.stream
        ttft: Optional[float] = None
        nbytes = 0
        ntok = 0
        last_t: Optional[float] = None
        gaps: List[float] = []
        give_up = t0 + self.stream_timeout_s
        try:
            h.send_response(200)
            for k, v in _SSE_HEADERS:
                h.send_header(k, v)
            h.end_headers()
            while True:
                with self._lock:
                    if not st.buf and not st.done:
                        self._wake.wait(timeout=self.poll_s)
                    toks = list(st.buf)
                    st.buf.clear()
                    done, outcome = st.done, st.outcome
                if toks:
                    now = time.perf_counter()
                    if ttft is None:
                        ttft = now - t0
                    elif last_t is not None:
                        gaps.append(now - last_t)
                    last_t = now
                    for tok in toks:
                        data = json.dumps({"i": ntok, "token": tok})
                        chunk = f"event: token\ndata: {data}\n\n".encode()
                        h.wfile.write(chunk)
                        nbytes += len(chunk)
                        ntok += 1
                    h.wfile.flush()
                if done and not st.buf:
                    data = json.dumps({
                        "rid": st.rid, "outcome": outcome,
                        "usage": {"prompt_tokens": st.prompt_len,
                                  "completion_tokens": ntok},
                    })
                    chunk = f"event: done\ndata: {data}\n\n".encode()
                    h.wfile.write(chunk)
                    h.wfile.flush()
                    nbytes += len(chunk)
                    self._finish_conn(st, deadline_ms=deadline_ms,
                                      nbytes=nbytes, ttft=ttft, ntok=ntok,
                                      gaps=gaps)
                    return
                if not toks and _client_gone(h.connection):
                    self._detach(st, deadline_ms=deadline_ms,
                                 nbytes=nbytes, ttft=ttft, ntok=ntok,
                                 reason="client-disconnect")
                    return
                if time.perf_counter() > give_up:
                    self._detach(st, deadline_ms=deadline_ms,
                                 nbytes=nbytes, ttft=ttft, ntok=ntok,
                                 reason="stream-timeout")
                    return
        except (BrokenPipeError, ConnectionResetError, OSError):
            # mid-stream disconnect: the write raised, the blocks must
            # not wait for a reader that is gone
            self._detach(st, deadline_ms=deadline_ms, nbytes=nbytes,
                         ttft=ttft, ntok=ntok, reason="client-disconnect")
        except Exception:  # noqa: BLE001 — a handler bug must neither
            # leak the stream entry nor write a stack trace down the
            # socket; the cancel path reclaims the blocks
            self._detach(st, deadline_ms=deadline_ms, nbytes=nbytes,
                         ttft=ttft, ntok=ntok, reason="handler-error")

    def _handle_health(self, h) -> None:
        replicas = [dict(rec, replica=i)
                    for i, rec in enumerate(self.router.health)]
        routable = sum(1 for r in replicas if r["state"] in _ROUTABLE)
        self._send_json(h, 200, {
            "replicas": replicas, "routable": routable,
            "total": len(replicas),
        })

    def _handle_metrics(self, h) -> None:
        body = prometheus_text(self.metrics(), prefix=self.prefix).encode()
        try:
            h.send_response(200)
            h.send_header("Content-Type", "text/plain; version=0.0.4")
            h.send_header("Content-Length", str(len(body)))
            h.end_headers()
            h.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass

    # ---- metrics + census ----

    def metrics(self) -> dict:
        """Fleet rollup (driver-thread snapshot) + ``gateway_*`` gauges."""
        with self._lock:
            out = dict(self._metrics_cache)
            out.update({
                "gateway_open_streams": len(self._streams),
                "gateway_queued": len(self._ingress),
                "gateway_connections": self._conns,
                "gateway_completed": self._completed,
                "gateway_http_400": self._http_400,
                "gateway_http_429": self._http_429,
                "gateway_cancels": self._cancelled_total,
                "gateway_bytes_out": self._bytes_out,
                "gateway_worst_gap_ms": round(self._worst_gap_s * 1e3, 3),
            })
            out.update(self.ttft_wire.summary("gateway_ttft_wire"))
            out.update(self.gap.summary("gateway_gap"))
            out.update(self.cancel_free.summary("gateway_cancel_free"))
        return out

    def census_decls(self):
        """Round 21 contract: every long-lived container on the gateway
        declares its bound (telemetry/census.py)."""
        return [
            Decl("_ingress", "fixed", cap=lambda g: g.max_pending,
                 why="handler→driver admissions; each entry is a blocked "
                     "HTTP thread, refused past max_pending (503)"),
            Decl("_cancels", "fixed", cap=lambda g: g.max_pending,
                 why="handler→driver cancel requests; at most one per "
                     "open connection, drained every driver loop"),
            Decl("_streams", "live", per_live=1, why=(
                "one bounded token queue per in-flight HTTP request; "
                "popped at terminal write, or by the driver when a "
                "detached rid retires")),
            Decl("_retire_events", "fixed", cap=16384,
                 why="terminal transitions queued for end-of-step "
                     "delivery; drained every _deliver call"),
            Decl("_metrics_cache", "fixed", cap=512,
                 why="one flat scalar snapshot of router.metrics(), "
                     "replaced (never grown) each refresh"),
            Decl("ttft_wire.values", "fixed",
                 cap=lambda g: 2 * g.ttft_wire.window,
                 why="LatencySeries percentile window"),
            Decl("gap.values", "fixed", cap=lambda g: 2 * g.gap.window,
                 why="LatencySeries percentile window"),
            Decl("cancel_free.values", "fixed",
                 cap=lambda g: 2 * g.cancel_free.window,
                 why="LatencySeries percentile window"),
        ]

    def census_owners(self):
        """Swept (name, object) pairs — the gateway itself; the router
        and its replicas publish their own owner set."""
        return [("gateway", self)]
