"""urllib-based SSE client for the gateway — tests and bench use this.

Stdlib only, mirroring the server's no-new-deps rule. Two layers:

- ``open_stream`` returns an ``SSEStream`` over a live ``/v1/generate``
  response: iterate ``events()`` for ``("token", {...})`` /
  ``("done", {...})`` pairs, or ``close()`` mid-stream to exercise the
  server's disconnect→cancel path (closing the response closes the
  socket; the server's next write breaks, or its queued-probe sees the
  FIN). 4xx/5xx raise ``GatewayError`` with the parsed JSON error body
  and any ``Retry-After`` hint.
- ``generate`` is the blocking convenience: drains the stream and
  returns one flat dict (``status/tokens/outcome/usage/rid``); HTTP
  errors return ``{"status", ...body, "retry_after"}`` instead of
  raising, so a shed reads as data, not control flow.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Iterator, Optional, Tuple


class GatewayError(Exception):
    """Non-200 response: ``status``, parsed JSON ``body`` (or raw text
    under ``{"error": "non-json", ...}``), and ``retry_after``."""

    def __init__(self, status: int, body, retry_after: Optional[str] = None):
        super().__init__(f"HTTP {status}: {body}")
        self.status = status
        self.body = body
        self.retry_after = retry_after


class SSEStream:
    """One live SSE response; context manager closes the socket."""

    def __init__(self, resp):
        self._resp = resp

    def events(self) -> Iterator[Tuple[str, dict]]:
        """Yield ``(event_name, data)`` per SSE event until EOF."""
        name, data_lines = None, []
        for raw in self._resp:
            line = raw.decode("utf-8", "replace").rstrip("\r\n")
            if not line:
                if name is not None or data_lines:
                    data = json.loads("".join(data_lines)) \
                        if data_lines else None
                    yield (name or "message", data)
                name, data_lines = None, []
                continue
            if line.startswith("event:"):
                name = line[len("event:"):].strip()
            elif line.startswith("data:"):
                data_lines.append(line[len("data:"):].strip())

    def close(self) -> None:
        try:
            self._resp.close()
        except OSError:
            pass

    def __enter__(self) -> "SSEStream":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _post(base: str, path: str, payload: dict, headers: dict,
          timeout: float):
    req = urllib.request.Request(
        base.rstrip("/") + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **headers},
        method="POST",
    )
    return urllib.request.urlopen(req, timeout=timeout)


def open_stream(base: str, prompt, max_new_tokens: int = 16, *,
                session: Optional[int] = None, deadline_ms=None,
                timeout: float = 30.0) -> SSEStream:
    """POST ``/v1/generate`` and return the live token stream.

    ``deadline_ms`` rides the ``X-Deadline-Ms`` header verbatim
    (``str()``-ed — pass garbage to exercise the server's 400 path).
    Raises ``GatewayError`` on any non-200 status.
    """
    payload = {"prompt": [int(t) for t in prompt],
               "max_new_tokens": int(max_new_tokens)}
    if session is not None:
        payload["session"] = int(session)
    headers = {}
    if deadline_ms is not None:
        headers["X-Deadline-Ms"] = str(deadline_ms)
    try:
        resp = _post(base, "/v1/generate", payload, headers, timeout)
    except urllib.error.HTTPError as e:
        raw = e.read()
        try:
            body = json.loads(raw)
        except ValueError:
            body = {"error": "non-json", "raw": raw.decode("utf-8",
                                                           "replace")}
        raise GatewayError(e.code, body, e.headers.get("Retry-After")) \
            from None
    return SSEStream(resp)


def generate(base: str, prompt, max_new_tokens: int = 16, *,
             session: Optional[int] = None, deadline_ms=None,
             timeout: float = 30.0) -> dict:
    """Blocking generate: drain the stream, return one flat dict.

    200 → ``{"status": 200, "rid", "tokens", "outcome", "usage"}``;
    4xx/5xx → ``{"status", **error_body, "retry_after"}``.
    """
    try:
        stream = open_stream(base, prompt, max_new_tokens,
                             session=session, deadline_ms=deadline_ms,
                             timeout=timeout)
    except GatewayError as e:
        out = {"status": e.status, "retry_after": e.retry_after}
        if isinstance(e.body, dict):
            out.update(e.body)
        return out
    tokens, outcome, usage, rid = [], None, None, None
    with stream:
        for name, data in stream.events():
            if name == "token":
                tokens.append(int(data["token"]))
            elif name == "done":
                outcome = data.get("outcome")
                usage = data.get("usage")
                rid = data.get("rid")
                break
    return {"status": 200, "rid": rid, "tokens": tokens,
            "outcome": outcome, "usage": usage}


def health(base: str, timeout: float = 10.0) -> dict:
    """GET ``/v1/health`` → the per-replica health-plane snapshot."""
    with urllib.request.urlopen(base.rstrip("/") + "/v1/health",
                                timeout=timeout) as resp:
        return json.loads(resp.read())


def metrics_text(base: str, timeout: float = 10.0) -> str:
    """GET ``/metrics`` → Prometheus text exposition."""
    with urllib.request.urlopen(base.rstrip("/") + "/metrics",
                                timeout=timeout) as resp:
        return resp.read().decode()
