"""Pre-decoded raw image records: the decode-free input path.

The reference's input story is ffrecord feeding ~5,500 img/s of JPEGs to 8
GPUs (``/root/reference/README.md:13-18``), with JPEG decode farmed out to
many DataLoader worker processes per host (D2/D11). Measurement on this
framework (scripts/bench_data.py) shows PIL JPEG decode costs ~5-8 ms/image
per core — one v5e chip at ~2,700 img/s needs ~15-20 cores of decode, and a
pod host may not have them to spare. This module removes decode from the hot
path entirely:

- ``write_imagenet_raw_split``: decode once at pack time, store uint8 HWC
  pixels (shorter side resized to ``image_size``, center-cropped square, the
  standard raw-ImageNet prep) in the same TPRC container with a tiny
  per-record header;
- ``RawImageNet``: dataset over the raw split. Train augmentation keeps
  torchvision ``RandomResizedCrop``+flip SEMANTICS (scale/aspect jitter via
  the same transform classes) but applies them to the stored 256px image
  instead of the original-resolution JPEG — the one documented deviation of
  this fast path. ``aug="crop"`` swaps in the cheaper classic
  random-crop+flip (pure numpy, no PIL at all).
- samples come back **uint8**: 4x fewer host→device bytes than float32, and
  the compiled train/eval step normalizes on device
  (``train/step.py::prepare_image``) with the exact same constants the host
  ``Normalize`` uses — bitwise-equivalent math, parity-tested.
"""

from __future__ import annotations

import io
import os
import struct
from typing import Iterable, Optional, Tuple

import numpy as np

from pytorch_distributed_tpu.data import transforms as T
from pytorch_distributed_tpu.data.packed_record import (
    PackedRecordReader,
    PackedRecordWriter,
)

# label u32 | height u16 | width u16, then h*w*3 uint8 payload
_HDR = struct.Struct("<IHH")


def encode_raw_record(image: np.ndarray, label: int) -> bytes:
    """uint8 HWC image + label → one raw record."""
    image = np.ascontiguousarray(image)
    if image.dtype != np.uint8 or image.ndim != 3 or image.shape[2] != 3:
        raise ValueError(f"expected uint8 HWC RGB, got {image.dtype} {image.shape}")
    h, w = image.shape[:2]
    return _HDR.pack(int(label), h, w) + image.tobytes()


def decode_raw_record(record: bytes) -> Tuple[np.ndarray, int]:
    label, h, w = _HDR.unpack(record[: _HDR.size])
    arr = np.frombuffer(record, np.uint8, count=h * w * 3, offset=_HDR.size)
    return arr.reshape(h, w, 3), int(label)


def write_imagenet_raw_split(
    path: str | os.PathLike,
    samples: Iterable[tuple],
    image_size: int = 256,
) -> int:
    """Pack (jpeg_bytes | PIL.Image | uint8 array, label) pairs as raw
    records: decode, resize shorter side to ``image_size``, center-crop
    square. Decode cost is paid ONCE here instead of every epoch.

    Returns the record count. Atomic like every TPRC write: a crash
    publishes nothing.
    """
    from PIL import Image

    resize = T.Resize(image_size)
    crop = T.CenterCrop(image_size)
    n = 0
    with PackedRecordWriter(os.fspath(path)) as w:
        for item, label in samples:
            if isinstance(item, np.ndarray):
                img = item
                if img.shape[:2] != (image_size, image_size):
                    pil = crop(resize(Image.fromarray(img)))
                    img = np.asarray(pil.convert("RGB"), np.uint8)
            else:
                pil = item
                if isinstance(pil, (bytes, bytearray, memoryview)):
                    pil = Image.open(io.BytesIO(pil))
                pil = crop(resize(pil.convert("RGB")))
                img = np.asarray(pil, np.uint8)
            w.write(encode_raw_record(img, int(label)))
            n += 1
    return n


class _RandomCropFlip:
    """Classic fast-path augmentation: random ``size``-crop + horizontal
    flip, pure numpy on the uint8 array (no PIL in the hot loop)."""

    def __init__(self, size: int):
        self.size = size

    def __call__(self, arr: np.ndarray, rng: np.random.Generator) -> np.ndarray:
        h, w = arr.shape[:2]
        s = self.size
        top = int(rng.integers(0, h - s + 1)) if h > s else 0
        left = int(rng.integers(0, w - s + 1)) if w > s else 0
        out = arr[top : top + s, left : left + s]
        if rng.random() < 0.5:
            out = out[:, ::-1]
        return np.ascontiguousarray(out)


class _RRCFlip:
    """torchvision-semantics RandomResizedCrop + flip on the stored raw
    image, emitting uint8 (device normalizes)."""

    def __init__(self, size: int):
        self.rrc = T.RandomResizedCrop(size)
        self.flip = T.RandomHorizontalFlip()

    def __call__(self, arr: np.ndarray, rng: np.random.Generator) -> np.ndarray:
        from PIL import Image

        img = self.flip(self.rrc(Image.fromarray(arr), rng), rng)
        return np.asarray(img.convert("RGB"), np.uint8)


class _EvalCrop:
    def __init__(self, size: int):
        self.size = size

    def __call__(self, arr: np.ndarray, rng=None) -> np.ndarray:
        h, w = arr.shape[:2]
        s = self.size
        top, left = (h - s) // 2, (w - s) // 2
        return np.ascontiguousarray(arr[top : top + s, left : left + s])


class RawImageNet:
    """Dataset over a raw split. Same (image, label) sample protocol as
    ``ImageNet`` — images are uint8 HWC; pair with the train/eval steps'
    on-device normalization.

    ``aug``: "rrc" (default — torchvision RandomResizedCrop semantics) |
    "crop" (classic random-crop+flip, fastest) | "none"/eval center-crop.
    """

    def __init__(
        self,
        split: str = "train",
        data_dir: str = ".",
        crop_size: int = 224,
        aug: Optional[str] = None,
        use_native: bool | None = None,
        verify_crc: bool = False,
    ):
        self.split = split
        self.path = os.path.join(data_dir, f"{split}.rawtprc")
        if not os.path.exists(self.path):
            raise FileNotFoundError(
                f"raw packed split not found: {self.path} — build it with "
                "pytorch_distributed_tpu.data.raw.write_imagenet_raw_split()"
            )
        self.reader = PackedRecordReader(self.path, use_native=use_native)
        # see ImageNet.verify_crc: per-read CRC costs ~3x read bandwidth
        self.verify_crc = verify_crc
        self._hw = None  # stored image size, lazily read from record 0
        self._native_declined = False  # latched on a variable-size split
        if aug is None:
            aug = "rrc" if split == "train" else "none"
        if aug == "rrc":
            self.transform = _RRCFlip(crop_size)
        elif aug == "crop":
            self.transform = _RandomCropFlip(crop_size)
        elif aug == "none":
            self.transform = _EvalCrop(crop_size)
        else:
            raise ValueError(f"unknown aug {aug!r}; known: rrc, crop, none")

    def __len__(self) -> int:
        return len(self.reader)

    def getitem_rng(self, i: int, rng: np.random.Generator):
        arr, label = decode_raw_record(self.reader.read(int(i), self.verify_crc))
        return self.transform(arr, rng), label

    def __getitem__(self, i: int):
        return self.getitem_rng(i, np.random.default_rng())

    def collate_batch(self, indices, make_rng):
        """Native whole-batch fast path (csrc ``tpr_crop_batch``): read +
        crop + flip + collate in one C call — one copy, no GIL, threaded.

        ``make_rng(i)`` builds the per-sample augmentation rng (only called
        once this path has decided to run); crop coordinates/flips are
        drawn in the SAME order as the Python transforms, so the two paths
        are bit-identical (parity-tested). Returns None when unavailable —
        no native reader, an augmentation that needs PIL, per-read CRC
        verification requested (the C kernel doesn't verify), or a record
        whose stored size differs from record 0's (the kernel checks every
        header and we fall back to the per-record-size Python path) — and
        the loader then does per-sample fetch.
        """
        nat = self.reader._native
        if (
            nat is None
            or self._native_declined
            or self.verify_crc
            or not isinstance(self.transform, (_RandomCropFlip, _EvalCrop))
        ):
            return None
        s = self.transform.size
        if self._hw is None:
            arr, _ = decode_raw_record(self.reader.read(int(indices[0]), False))
            self._hw = arr.shape[:2]
        h, w = self._hw
        if h < s or w < s:
            # stored image smaller than the crop: the Python transforms
            # degrade gracefully (no-crop slice); the C kernel would error
            return None
        n = len(indices)
        if isinstance(self.transform, _RandomCropFlip):
            tops, lefts, flips = [], [], []
            for i in indices:
                rng = make_rng(i)
                # exact rng consumption order of _RandomCropFlip.__call__
                tops.append(int(rng.integers(0, h - s + 1)) if h > s else 0)
                lefts.append(int(rng.integers(0, w - s + 1)) if w > s else 0)
                flips.append(bool(rng.random() < 0.5))
        else:
            tops = [(h - s) // 2] * n
            lefts = [(w - s) // 2] * n
            flips = [False] * n
        from pytorch_distributed_tpu.data.native import SizeMismatch
        from pytorch_distributed_tpu.resilience.retry import retry_call

        try:
            # bounded retry on transient pread failures, mirroring
            # PackedRecordReader.read; SizeMismatch is structural (not an
            # OSError) and falls through to the Python path unretried
            images, labels = retry_call(
                nat.crop_batch, indices, tops, lefts, flips, s, h, w,
                no_retry_on=(SizeMismatch,), what="raw batch crop",
            )
        except SizeMismatch:
            # variable-size split: the per-sample path reads true sizes.
            # Latch the decision — retrying the kernel every batch would
            # read (and discard) each batch twice, forever.
            self._native_declined = True
            return None
        return {"image": images, "label": labels}

    def loader(self, batch_size: int, sampler=None, num_workers: int = 4,
               drop_last: bool = True, prefetch: int = 2, **_compat):
        from pytorch_distributed_tpu.data.loader import DataLoader

        return DataLoader(
            self,
            batch_size=batch_size,
            sampler=sampler,
            num_workers=num_workers,
            drop_last=drop_last,
            prefetch=prefetch,
        )
