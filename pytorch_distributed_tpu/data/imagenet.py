"""ImageNet over TPRC packed records.

Replaces ``hfai.datasets.ImageNet(split, transform)`` + ``.loader(...)``
(reference D2; ``restnet_ddp.py:107-109,117-119``). Storage layout: one
TPRC file per split (``train.tprc`` / ``val.tprc``) whose records are
``u32 label || JPEG bytes`` — the packed-file design that let the reference
sustain >5 000 img/s from a cluster filesystem, rebuilt on our own
container format (data/packed_record.py, C++ read core).

``ImageNet.loader(...)`` mirrors the reference's call shape so recipes read
the same. A conversion helper builds TPRC splits from any (bytes, label)
iterator — e.g. a torchvision ImageFolder walk on the host that owns the
raw dataset.
"""

from __future__ import annotations

import io
import os
import struct
from typing import Callable, Iterable, Optional, Tuple

import numpy as np

from pytorch_distributed_tpu.data import transforms as T
from pytorch_distributed_tpu.data.loader import DataLoader
from pytorch_distributed_tpu.data.packed_record import (
    PackedRecordReader,
    PackedRecordWriter,
)

_LABEL = struct.Struct("<I")

DEFAULT_DATA_DIR = os.environ.get(
    "PDT_IMAGENET_DIR", os.path.expanduser("~/datasets/imagenet-tprc")
)


def write_imagenet_split(
    path: str,
    samples: Iterable[Tuple[bytes, int]],
    with_crc: bool = True,
) -> int:
    """Pack (jpeg_bytes, label) pairs into one TPRC split file."""
    count = 0
    with PackedRecordWriter(path, with_crc=with_crc) as w:
        for jpeg, label in samples:
            w.write(_LABEL.pack(label) + jpeg)
            count += 1
    return count


class ImageNet:
    """Packed-record ImageNet split with torch-Dataset-style indexing.

    ``dataset[i]`` decodes record i → (transformed image, label). Decode is
    host-side PIL (the loader parallelizes it across worker threads);
    transform is the reference's train/val pipeline by default.
    """

    def __init__(
        self,
        split: str = "train",
        transform: Optional[Callable] = None,
        data_dir: str = DEFAULT_DATA_DIR,
        use_native: bool | None = None,
        verify_crc: bool = False,
    ):
        self.split = split
        self.path = os.path.join(data_dir, f"{split}.tprc")
        if not os.path.exists(self.path):
            raise FileNotFoundError(
                f"packed split not found: {self.path} — build it with "
                "pytorch_distributed_tpu.data.imagenet.write_imagenet_split()"
            )
        self.reader = PackedRecordReader(self.path, use_native=use_native)
        # Per-read CRC costs ~3x read bandwidth (scripts/bench_data.py); the
        # atomic TPRC writer cannot publish torn files, so the hot loop
        # skips it by default. Opt in for integrity sweeps.
        self.verify_crc = verify_crc
        if transform is None:
            transform = (
                T.train_transform() if split == "train" else T.eval_transform()
            )
        self.transform = transform

    def __len__(self) -> int:
        return len(self.reader)

    def _decode(self, record: bytes, rng: np.random.Generator):
        (label,) = _LABEL.unpack(record[: _LABEL.size])
        from PIL import Image

        img = Image.open(io.BytesIO(record[_LABEL.size :]))
        img = img.convert("RGB")
        if self.transform is not None:
            img = self.transform(img, rng)
        return np.asarray(img, np.float32), int(label)

    def getitem_rng(self, i: int, rng: np.random.Generator):
        """Deterministic-augmentation entry point: the loader derives ``rng``
        from (seed, epoch, index), so resumed runs see identical crops/flips."""
        return self._decode(self.reader.read(int(i), self.verify_crc), rng)

    def __getitem__(self, i: int):
        return self.getitem_rng(i, np.random.default_rng())

    def loader(
        self,
        batch_size: int,
        sampler=None,
        num_workers: int = 4,
        drop_last: bool = True,
        prefetch: int = 2,
        **_compat,
    ) -> DataLoader:
        """Reference-shaped loader factory (``train_dataset.loader(...)``,
        ``restnet_ddp.py:109``). ``pin_memory`` etc. are accepted and ignored
        (device transfer is handled by the trainer's prefetcher)."""
        return DataLoader(
            self,
            batch_size=batch_size,
            sampler=sampler,
            num_workers=num_workers,
            drop_last=drop_last,
            prefetch=prefetch,
        )
