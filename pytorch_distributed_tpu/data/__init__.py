from pytorch_distributed_tpu.data.sampler import DistributedSampler
from pytorch_distributed_tpu.data.loader import DataLoader
from pytorch_distributed_tpu.data.synthetic import SyntheticImageClassification
from pytorch_distributed_tpu.data.imagenet import ImageNet
from pytorch_distributed_tpu.data.raw import RawImageNet, write_imagenet_raw_split
from pytorch_distributed_tpu.data.tokens import SyntheticTokens, TokenArrayDataset
from pytorch_distributed_tpu.data.packed_record import (
    PackedRecordWriter,
    PackedRecordReader,
)

__all__ = [
    "DistributedSampler",
    "DataLoader",
    "SyntheticImageClassification",
    "ImageNet",
    "RawImageNet",
    "write_imagenet_raw_split",
    "SyntheticTokens",
    "TokenArrayDataset",
    "PackedRecordWriter",
    "PackedRecordReader",
]
