"""Per-replica index sharding with exact ``torch.utils.data.DistributedSampler``
semantics, plus seekability.

Replaces D10 (``restnet_ddp.py:9,108,118,137``):
- pad the index list to a ``num_replicas``-divisible length by repeating
  indices from the front (torch's non-drop_last behavior), or truncate when
  ``drop_last``;
- stride the padded list by rank (``indices[rank::num_replicas]``);
- reshuffle each epoch with a ``seed + epoch``-seeded permutation
  (``set_epoch``, ref ``restnet_ddp.py:137``).

Improvement over the reference (SURVEY.md §3.5): the sampler is
*index-seekable*. The reference resumes mid-epoch by reading and discarding
``start_step`` batches through the real loader (``restnet_ddp.py:22-23``) —
cost proportional to the skipped data. Here ``iter_from(start_batch)`` slices
the precomputed index list, so resume costs nothing.

Parity is verified directly against torch's sampler in
tests/test_sampler.py.
"""

from __future__ import annotations

import numpy as np


class DistributedSampler:
    """Deterministic epoch-seeded shard of ``range(dataset_size)``."""

    def __init__(
        self,
        dataset_size: int,
        num_replicas: int = 1,
        rank: int = 0,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
    ):
        if not 0 <= rank < num_replicas:
            raise ValueError(f"rank {rank} out of range for {num_replicas} replicas")
        self.dataset_size = dataset_size
        self.num_replicas = num_replicas
        self.rank = rank
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0

        if drop_last and dataset_size % num_replicas:
            self.num_samples = dataset_size // num_replicas
        else:
            self.num_samples = -(-dataset_size // num_replicas)  # ceil
        self.total_size = self.num_samples * num_replicas

    def set_epoch(self, epoch: int) -> None:
        """Advance the shuffle seed (ref ``train_datasampler.set_epoch(epoch)``,
        ``restnet_ddp.py:137``) so every replica draws the same permutation."""
        self.epoch = epoch

    def _global_indices(self) -> np.ndarray:
        if self.shuffle:
            # torch uses a generator seeded with seed + epoch; we mirror the
            # *semantics* (same permutation on every replica, different per
            # epoch), not torch's RNG bitstream.
            rng = np.random.default_rng(self.seed + self.epoch)
            indices = rng.permutation(self.dataset_size)
        else:
            indices = np.arange(self.dataset_size)
        if self.drop_last:
            indices = indices[: self.total_size]
        elif self.total_size > len(indices):
            # pad by wrapping from the front, torch-style
            pad = self.total_size - len(indices)
            reps = -(-pad // max(len(indices), 1))
            indices = np.concatenate([indices] + [indices] * reps)[: self.total_size]
        return indices

    def local_indices(self) -> np.ndarray:
        """This replica's index shard (``indices[rank::num_replicas]``)."""
        return self._global_indices()[self.rank :: self.num_replicas]

    def __iter__(self):
        return iter(self.local_indices().tolist())

    def __len__(self) -> int:
        return self.num_samples

    def local_padding_mask(self) -> np.ndarray:
        """Boolean [num_samples]: True where this replica's position holds a
        wrap-padding duplicate (torch's non-drop_last padding repeats
        indices from the front to reach a ``num_replicas``-divisible
        total). Torch counts those duplicates in val metrics; metric code
        here can zero their weight instead so psum'd reductions aren't
        biased when the val size isn't divisible by the replica count."""
        global_pos = np.arange(self.rank, self.total_size, self.num_replicas)
        return global_pos >= self.dataset_size

    def iter_from(self, start_index: int):
        """Seekable iteration: skip the first ``start_index`` samples without
        touching the dataset (replaces the reference's read-and-discard
        fast-forward, ``restnet_ddp.py:22-23``)."""
        return iter(self.local_indices()[start_index:].tolist())
