"""Token-sequence datasets for LM training.

Same sample protocol as the image datasets (``__len__`` /
``getitem_rng(i, rng)`` → sample) so ``DistributedSampler`` + ``DataLoader``
drive LM training with the exact epoch/shard/seek semantics the image
trainer has (torch-parity sampler, seekable resume).

A sample is one fixed-length token sequence ``[L] int32``; the LM trainer
builds labels/weights via ``train.lm.shift_labels`` at collate time.

- ``TokenArrayDataset``: windows over one flat token array (memmap-friendly
  — the standard packed-corpus layout).
- ``SyntheticTokens``: deterministic per-index random sequences for
  tests/benchmarks (same index ⇒ same sequence, like
  ``SyntheticImageClassification``).
"""

from __future__ import annotations

import numpy as np


class TokenArrayDataset:
    """Non-overlapping ``seq_len`` windows over a flat token array.

    ``tokens`` may be any 1-D integer array-like, including ``np.memmap``
    over a packed corpus file; nothing is copied until a window is read.
    """

    def __init__(self, tokens, seq_len: int):
        self.tokens = tokens
        self.seq_len = int(seq_len)
        if self.seq_len < 1:
            raise ValueError(f"seq_len must be >= 1, got {seq_len}")
        self._n = len(tokens) // self.seq_len
        if self._n == 0:
            raise ValueError(
                f"token array ({len(tokens)}) shorter than seq_len {seq_len}"
            )

    def __len__(self) -> int:
        return self._n

    def getitem_rng(self, i: int, rng=None):
        lo = int(i) * self.seq_len
        return np.asarray(self.tokens[lo : lo + self.seq_len], np.int32)

    def __getitem__(self, i: int):
        return self.getitem_rng(i)


class SyntheticTokens:
    """Deterministic fake token sequences (seeded per index)."""

    def __init__(self, size: int, seq_len: int, vocab_size: int, seed: int = 0):
        self.size = int(size)
        self.seq_len = int(seq_len)
        self.vocab_size = int(vocab_size)
        self.seed = seed

    def __len__(self) -> int:
        return self.size

    def getitem_rng(self, i: int, rng=None):
        r = np.random.default_rng([self.seed, int(i)])
        # token 0 is reserved as the pad/ignore id by shift_labels
        return r.integers(1, self.vocab_size, self.seq_len).astype(np.int32)

    def __getitem__(self, i: int):
        return self.getitem_rng(i)
