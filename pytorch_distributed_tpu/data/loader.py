"""Batching data loader with background workers and seekable resume.

Replaces the reference's DataLoader usage (D2's ``.loader(batch_size,
sampler, num_workers, pin_memory)``, ``restnet_ddp.py:109,119``) with a
thread-pool design suited to TPU hosts:

- worker *threads*, not processes: decode (PIL JPEG) releases the GIL, and
  one process per host is the JAX multi-controller model — forking workers
  per chip (reference D11, ``hfai.multiprocessing.spawn``) has no TPU
  analog;
- ``start_batch`` seek: resume mid-epoch without reading and discarding
  skipped batches (fixes the reference's fast-forward cost,
  ``restnet_ddp.py:22-23``, SURVEY.md §3.5);
- deterministic per-sample augmentation RNG derived from (seed, epoch,
  sample index) so a resumed run sees the same augmentations as an
  uninterrupted one;
- bounded prefetch queue overlapping host data work with device steps.

Batches are dicts of stacked numpy arrays: ``{"image": [B,H,W,C], "label":
[B] i32}``. Image dtype follows the dataset: float32 for host-normalized
pipelines (``ImageNet`` transforms), **uint8** for the raw fast path
(``data.raw.RawImageNet``) — uint8 batches are normalized on device by the
compiled step (``train/step.prepare_image``).
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Iterator, Optional

import numpy as np

from pytorch_distributed_tpu.data.sampler import DistributedSampler
from pytorch_distributed_tpu.resilience.faults import fault_point
from pytorch_distributed_tpu.resilience.retry import retry_call


def _collate(samples) -> dict:
    images = np.stack([s[0] for s in samples])
    if images.dtype != np.uint8:
        # float pipelines collate to f32; uint8 (raw fast path) stays uint8 —
        # 4x fewer H2D bytes, normalized on device (train/step.prepare_image)
        images = images.astype(np.float32)
    labels = np.asarray([s[1] for s in samples], np.int32)
    return {"image": images, "label": labels}


class DataLoader:
    def __init__(
        self,
        dataset,
        batch_size: int,
        sampler: Optional[DistributedSampler] = None,
        num_workers: int = 0,
        drop_last: bool = True,
        prefetch: int = 2,
        seed: int = 0,
        collate_fn=None,
        retries: int = 2,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.sampler = sampler or DistributedSampler(
            len(dataset), num_replicas=1, rank=0, shuffle=False
        )
        self.num_workers = num_workers
        self.drop_last = drop_last
        self.prefetch = max(prefetch, 1)
        self.seed = seed
        self.retries = retries  # bounded re-fetch on transient OSError
        # default: image-classification (image, label) stacking; LM loaders
        # pass train.lm_trainer.lm_collate
        self.collate_fn = collate_fn or _collate

    def __len__(self) -> int:
        n = len(self.sampler)
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def _batches(self, start_batch: int) -> Iterator[np.ndarray]:
        indices = np.fromiter(
            self.sampler.iter_from(start_batch * self.batch_size), np.int64
        )
        usable = len(indices)
        if self.drop_last:
            usable -= usable % self.batch_size
        for lo in range(0, usable, self.batch_size):
            yield indices[lo : lo + self.batch_size]

    def _make_rng(self, i: int) -> np.random.Generator:
        """THE per-sample augmentation rng: derived from (loader seed,
        epoch, dataset index) so resumed runs reproduce the same
        crops/flips. Single definition — the per-sample path and the
        whole-batch fast path must draw from identical streams for their
        bit-parity guarantee to hold."""
        epoch = getattr(self.sampler, "epoch", 0)
        return np.random.default_rng([self.seed, epoch, i])

    def _getitem(self, i: int):
        """Fetch sample i with the deterministic augmentation RNG."""
        dataset = self.dataset
        if hasattr(dataset, "getitem_rng"):
            return dataset.getitem_rng(i, self._make_rng(i))
        return dataset[i]

    def _fetch(self, batch_indices: np.ndarray, pool) -> dict:
        # injection site "data.fetch" (resilience.faults): a raise here is
        # a transient read failure, absorbed by _fetch_retried's bounded
        # retry — the deterministic per-sample RNG makes a re-fetch
        # bit-identical to the first attempt
        fault_point("data.fetch")
        ints = [int(i) for i in batch_indices]
        if hasattr(self.dataset, "collate_batch") and self.collate_fn is _collate:
            # Whole-batch fast path (e.g. RawImageNet's native C crop+
            # collate); a custom collate_fn disables it — the caller's
            # collate must always run. _make_rng is shared with _getitem
            # (and only called if the path applies), so the two paths draw
            # identical augmentation streams.
            batch = self.dataset.collate_batch(ints, self._make_rng)
            if batch is not None:
                return batch
        if pool is not None:
            samples = list(pool.map(self._getitem, ints))
        else:
            samples = [self._getitem(i) for i in ints]
        return self.collate_fn(samples)

    def _fetch_retried(self, batch_indices: np.ndarray, pool) -> dict:
        """``_fetch`` under bounded backoff: a transient read failure
        (OSError; injected faults included) re-fetches the SAME batch —
        augmentation RNG derives from (seed, epoch, index), so the retry
        reproduces it exactly. Non-OSError bugs propagate on first raise."""
        return retry_call(
            self._fetch, batch_indices, pool,
            retries=self.retries, seed=self.seed, what="batch fetch",
        )

    def iter_batches(self, start_batch: int = 0) -> Iterator[dict]:
        """Iterate batches of the current epoch, optionally seeking past the
        first ``start_batch`` batches at zero cost (step-resume). Each call
        owns its worker pool, so concurrent iterators don't interfere."""
        pool = (
            ThreadPoolExecutor(max_workers=self.num_workers)
            if self.num_workers > 0
            else None
        )
        try:
            if self.prefetch <= 1:
                for idx in self._batches(start_batch):
                    yield self._fetch_retried(idx, pool)
                return
            # Bounded producer/consumer: host decode overlaps device compute.
            q: queue.Queue = queue.Queue(maxsize=self.prefetch)
            stop = threading.Event()
            _END = object()

            def producer():
                try:
                    for idx in self._batches(start_batch):
                        if stop.is_set():
                            return
                        q.put(self._fetch_retried(idx, pool))
                except BaseException as e:  # surfaced by consumer
                    q.put(e)
                    return
                q.put(_END)

            t = threading.Thread(target=producer, daemon=True)
            t.start()
            try:
                while True:
                    item = q.get()
                    if item is _END:
                        return
                    if isinstance(item, BaseException):
                        raise item
                    yield item
            finally:
                stop.set()
                # Unblock the producer, then BLOCK on join: after stop is
                # set it can enqueue at most one in-flight batch plus the
                # _END/exception sentinel, and the queue (maxsize >= 2 on
                # this path) absorbs both once drained — so the join
                # terminates without the old 100 ms get_nowait poll spin.
                while True:
                    try:
                        q.get_nowait()
                    except queue.Empty:
                        break
                t.join()
        finally:
            if pool is not None:
                # cancel_futures: a cancelled iterator must not leave
                # decode futures running against a dataset the caller may
                # be about to close (teardown hardening)
                pool.shutdown(wait=False, cancel_futures=True)

    def __iter__(self) -> Iterator[dict]:
        return self.iter_batches(0)


def measure_throughput(loader: DataLoader, epochs: int = 1) -> float:
    """Unbiased items/s of a loader epoch.

    Times COMPLETE fresh epochs: each ``iter_batches`` call starts with an
    empty prefetch queue and its own worker pool, so consuming a whole epoch
    measures production time end to end — no pre-filled batches inflate the
    window (timing a partially-consumed iterator would count queued batches
    as instantaneous). Used by bench.py's ``data_pipeline_img_s`` and
    scripts/bench_data.py so the two report the same methodology.
    """
    import time

    total = 0
    t0 = time.perf_counter()
    for _ in range(max(epochs, 1)):
        for batch in loader.iter_batches(0):
            total += len(batch["label"])
    dt = time.perf_counter() - t0
    if total == 0:
        raise ValueError("loader produced no batches; nothing to measure")
    return total / dt
