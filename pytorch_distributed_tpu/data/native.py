"""ctypes bridge to the C++ recordio core (csrc/recordio.cpp).

Compiles the shared library on first use with g++ (the image has no
pybind11; the C ABI + ctypes keeps the binding dependency-free). Falls back
gracefully: ``available()`` returns False when no toolchain is present and
the pure-Python reader takes over.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Sequence

import numpy as np

_CSRC = os.path.join(os.path.dirname(os.path.dirname(__file__)), "csrc")
_SRC = os.path.join(_CSRC, "recordio.cpp")
_BUILD_DIR = os.path.join(_CSRC, "build")
_SO = os.path.join(_BUILD_DIR, "librecordio.so")

_lock = threading.Lock()
_lib = None
_lib_error: str | None = None


def _build() -> str | None:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return _SO
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread", _SRC,
           "-o", _SO + ".tmp"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (OSError, subprocess.SubprocessError) as e:
        global _lib_error
        _lib_error = f"native recordio build failed: {e}"
        return None
    os.replace(_SO + ".tmp", _SO)
    return _SO


def _load():
    global _lib, _lib_error
    with _lock:
        if _lib is not None or _lib_error is not None:
            return _lib
        so = _build()
        if so is None:
            return None
        lib = ctypes.CDLL(so)
        lib.tpr_open.restype = ctypes.c_void_p
        lib.tpr_open.argtypes = [ctypes.c_char_p]
        lib.tpr_close.argtypes = [ctypes.c_void_p]
        lib.tpr_count.restype = ctypes.c_int64
        lib.tpr_count.argtypes = [ctypes.c_void_p]
        lib.tpr_size.restype = ctypes.c_int64
        lib.tpr_size.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.tpr_read.restype = ctypes.c_int64
        lib.tpr_read.argtypes = [
            ctypes.c_void_p,
            ctypes.c_uint64,
            ctypes.c_char_p,
            ctypes.c_int,
        ]
        lib.tpr_read_batch.restype = ctypes.c_int64
        lib.tpr_read_batch.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int64,
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int,
        ]
        lib.tpr_crop_batch.restype = ctypes.c_int64
        lib.tpr_crop_batch.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int,
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


class SizeMismatch(IOError):
    """A raw record's stored (h, w) differs from what the caller planned
    crop coordinates for — fall back to the per-record-size path."""


class NativeReader:
    def __init__(self, path: str):
        lib = _load()
        if lib is None:
            raise RuntimeError(_lib_error or "native recordio unavailable")
        self._lib = lib
        self._h = lib.tpr_open(path.encode())
        if not self._h:
            raise IOError(f"tpr_open failed for {path}")
        self.n = int(lib.tpr_count(self._h))

    def size(self, i: int) -> int:
        return int(self._lib.tpr_size(self._h, i))

    def read(self, i: int, verify_crc: bool = True) -> bytes:
        size = self.size(i)
        if size < 0:
            raise IndexError(i)
        buf = ctypes.create_string_buffer(size)
        status = self._lib.tpr_read(self._h, i, buf, int(verify_crc))
        if status == -2:
            raise IOError(f"crc mismatch in record {i}")
        if status < 0:
            raise IOError(f"read failed for record {i}")
        return buf.raw[:size]

    def read_batch(self, indices: Sequence[int], verify_crc: bool = True) -> list[bytes]:
        idx = np.asarray(indices, np.uint64)
        sizes = np.asarray([self.size(int(i)) for i in idx], np.int64)
        if (sizes < 0).any():
            raise IndexError("index out of range in batch")
        offsets = np.concatenate([[0], np.cumsum(sizes[:-1])]).astype(np.uint64)
        total = int(sizes.sum())
        buf = ctypes.create_string_buffer(total)
        status = self._lib.tpr_read_batch(
            self._h,
            idx.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            len(idx),
            buf,
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            int(verify_crc),
        )
        if status == -2:
            raise IOError("crc mismatch in batch read")
        if status < 0:
            raise IOError("batch read failed")
        raw = buf.raw
        return [
            raw[int(o) : int(o) + int(s)] for o, s in zip(offsets, sizes)
        ]

    def crop_batch(
        self,
        indices: Sequence[int],
        tops: Sequence[int],
        lefts: Sequence[int],
        flips: Sequence[bool],
        crop: int,
        expect_h: int,
        expect_w: int,
        n_threads: int = 0,
    ):
        """Read RAW image records (data/raw.py layout) and return
        (images [B, crop, crop, 3] uint8, labels [B] int32) with the crop
        windows and horizontal flips applied in C — one copy, no GIL.

        ``expect_h``/``expect_w`` pin the stored size the crop coordinates
        were drawn for; a record whose header disagrees raises
        ``SizeMismatch`` (caller falls back to the per-record-size path).
        """
        idx = np.ascontiguousarray(indices, np.uint64)
        t = np.ascontiguousarray(tops, np.int32)
        l = np.ascontiguousarray(lefts, np.int32)
        f = np.ascontiguousarray(flips, np.uint8)
        b = len(idx)
        images = np.empty((b, crop, crop, 3), np.uint8)
        labels = np.empty((b,), np.int32)
        if n_threads <= 0:
            n_threads = min(os.cpu_count() or 1, 8)
        status = self._lib.tpr_crop_batch(
            self._h,
            idx.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            b,
            t.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            l.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            f.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            crop,
            expect_h,
            expect_w,
            images.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            n_threads,
        )
        if status == -3:
            raise SizeMismatch(
                f"record size differs from expected {expect_h}x{expect_w}"
            )
        if status < 0:
            raise IOError(
                "native crop_batch failed (bad index, truncated record, or "
                "crop window out of bounds)"
            )
        return images, labels

    def close(self):
        if self._h:
            self._lib.tpr_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
