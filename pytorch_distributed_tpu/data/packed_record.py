"""TPRC packed-record container: high-throughput sequential storage for
variable-length records (JPEG bytes, serialized samples).

TPU-native replacement for ffrecord (reference dependency D2 —
``hfai.datasets.ImageNet`` over ``/public_dataset/1/ImageNet/{train,val}.ffr``,
``README.md:14-18``): millions of small files collapse into a few large
sequential files so the cluster filesystem sees large reads, with O(1)
random access via an in-memory offset table — exactly the property the
reference leaned on for its 5 500 img/s input pipeline.

Layout (little-endian):

    magic "TPRC" | version u32 | n u64 | flags u64
    offsets u64[n+1]      payload-relative record boundaries
    crcs u32[n]           iff flags & 1
    payload               concatenated record bytes

Two readers share the format:
- ``PackedRecordReader`` — pure numpy/mmap-free Python (portable fallback);
- the C++ core in ``csrc/recordio.cpp`` (pread-based, thread-safe batch
  gather), loaded via ctypes when a toolchain is available. The Python and
  native readers are interchangeable and parity-tested.
"""

from __future__ import annotations

import os
import shutil
import struct
import zlib
from typing import Iterable, Sequence

import numpy as np

from pytorch_distributed_tpu.data import native
from pytorch_distributed_tpu.resilience.retry import retry_call

_MAGIC = b"TPRC"
_VERSION = 1
_FLAG_CRC = 1
_HEADER = struct.Struct("<4sIQQ")


class PackedRecordWriter:
    """Streaming writer; records are raw ``bytes``.

    Payload streams to a temp file as records arrive (memory stays O(record
    count), not O(payload) — the ImageNet train split is ~150 GB); the final
    file (header + tables + payload) is assembled and atomically published at
    ``close()``. An exception inside the ``with`` block abandons the write:
    nothing is published and temp files are removed, so a crashed pack can
    never be mistaken for a complete split.
    """

    def __init__(self, path: str | os.PathLike, with_crc: bool = True):
        self.path = os.fspath(path)
        self.with_crc = with_crc
        self._payload_tmp = self.path + ".payload.tmp"
        self._payload = open(self._payload_tmp, "wb")
        self._offsets = [0]
        self._crcs: list[int] = []
        self._closed = False

    def write(self, record: bytes) -> int:
        """Append one record; returns its index."""
        if self._closed:
            raise ValueError("writer is closed")
        self._payload.write(record)
        self._offsets.append(self._offsets[-1] + len(record))
        if self.with_crc:
            self._crcs.append(zlib.crc32(record) & 0xFFFFFFFF)
        return len(self._offsets) - 2

    def write_all(self, records: Iterable[bytes]) -> None:
        for r in records:
            self.write(r)

    def abort(self) -> None:
        """Discard everything written; publish nothing."""
        if self._closed:
            return
        self._closed = True
        self._payload.close()
        for p in (self._payload_tmp, self.path + ".tmp"):
            try:
                os.remove(p)
            except FileNotFoundError:
                pass

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._payload.close()
        n = len(self._offsets) - 1
        flags = _FLAG_CRC if self.with_crc else 0
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "wb") as f, open(self._payload_tmp, "rb") as payload:
                f.write(_HEADER.pack(_MAGIC, _VERSION, n, flags))
                f.write(np.asarray(self._offsets, "<u8").tobytes())
                if self.with_crc:
                    f.write(np.asarray(self._crcs, "<u4").tobytes())
                shutil.copyfileobj(payload, f, length=16 * 1024 * 1024)
            os.replace(tmp, self.path)  # atomic publish
        finally:
            try:
                os.remove(self._payload_tmp)
            except FileNotFoundError:
                pass

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self.abort()
        else:
            self.close()


class _PyReader:
    """Pure-Python pread reader (fallback when no native library)."""

    def __init__(self, path: str):
        self._f = open(path, "rb", buffering=0)
        header = self._f.read(_HEADER.size)
        if len(header) < _HEADER.size:
            raise ValueError(f"{path}: truncated TPRC header")
        magic, version, n, flags = _HEADER.unpack(header)
        if magic != _MAGIC or version != _VERSION:
            raise ValueError(f"{path}: not a TPRC v{_VERSION} file")
        self.n = n
        self.flags = flags
        raw = self._f.read(8 * (n + 1))
        if len(raw) < 8 * (n + 1):
            raise ValueError(f"{path}: truncated TPRC offset table")
        self.offsets = np.frombuffer(raw, "<u8")
        self.crcs = None
        payload_start = _HEADER.size + 8 * (n + 1)
        if flags & _FLAG_CRC:
            raw = self._f.read(4 * n)
            if len(raw) < 4 * n:
                raise ValueError(f"{path}: truncated TPRC crc table")
            self.crcs = np.frombuffer(raw, "<u4")
            payload_start += 4 * n
        self.payload_start = payload_start

    def read(self, i: int, verify_crc: bool = True) -> bytes:
        start, end = int(self.offsets[i]), int(self.offsets[i + 1])
        data = os.pread(self._f.fileno(), end - start, self.payload_start + start)
        if verify_crc and self.crcs is not None:
            if zlib.crc32(data) & 0xFFFFFFFF != int(self.crcs[i]):
                raise IOError(f"crc mismatch in record {i}")
        return data

    def close(self):
        self._f.close()


class PackedRecordReader:
    """O(1) random access over a TPRC file.

    Uses the C++ pread core when available (``use_native=None`` auto-detects),
    the Python fallback otherwise. Thread-safe for concurrent reads either
    way (stateless pread in both).
    """

    def __init__(self, path: str | os.PathLike, use_native: bool | None = None):
        self.path = os.fspath(path)
        self._native = None
        self._py = None
        if use_native is None:
            use_native = native.available()
        if use_native:
            self._native = native.NativeReader(self.path)
            self.n = self._native.n
        else:
            self._py = _PyReader(self.path)
            self.n = self._py.n

    def __len__(self) -> int:
        return self.n

    def read(self, i: int, verify_crc: bool = True) -> bytes:
        """One record. Transient read failures (a cluster-fs pread during
        failover, a CRC mismatch from an in-flight page) get a bounded
        seeded-backoff retry — both readers are stateless preads, so a
        retry is a clean re-read."""
        if not 0 <= i < self.n:
            raise IndexError(i)
        reader = self._native if self._native is not None else self._py
        return retry_call(
            reader.read, i, verify_crc, what=f"record read {i}"
        )

    def read_batch(self, indices: Sequence[int], verify_crc: bool = True) -> list[bytes]:
        """Gather many records (single native call when available), with
        the same bounded retry as ``read``."""
        if self._native is not None:
            return retry_call(
                self._native.read_batch, indices, verify_crc,
                what="record batch read",
            )
        return [self.read(int(i), verify_crc) for i in indices]

    def verify_all(self) -> None:
        """Full-file CRC integrity sweep; raises IOError on the first
        corrupt record.

        Per-read CRC costs ~3x read bandwidth (scripts/bench_data.py), so
        the dataset hot loops skip it by default (``ImageNet``/
        ``RawImageNet`` ``verify_crc=False``) — media/transfer corruption of
        long-lived split files is instead caught by running this sweep after
        packing, after copying between filesystems, or on a schedule.
        """
        for lo in range(0, self.n, 1024):
            self.read_batch(range(lo, min(lo + 1024, self.n)), verify_crc=True)

    def close(self) -> None:
        if self._native is not None:
            self._native.close()
        if self._py is not None:
            self._py.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
