"""Host-side image transforms (numpy/PIL), NHWC.

Replaces the reference's torchvision pipelines (train:
RandomResizedCrop(224)+RandomHorizontalFlip+ToTensor+Normalize, val:
Resize(256)+CenterCrop(224)+ToTensor+Normalize — ``restnet_ddp.py:101-116``)
with numpy implementations that match torchvision's sampling semantics.
Normalization itself is deferred to the device (fused into the compiled step
by XLA) when used through the trainer — host work stays decode + crop + flip,
which is what keeps the input pipeline off the critical path (SURVEY.md §7
hard part (a)).

Output convention: float32 NHWC in [0,1] before ``Normalize``; channel stats
are the same ImageNet constants the reference uses.
"""

from __future__ import annotations

import math
from typing import Sequence

import numpy as np

try:
    from PIL import Image

    _HAVE_PIL = True
except ImportError:  # pragma: no cover
    _HAVE_PIL = False

IMAGENET_MEAN = np.asarray([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.asarray([0.229, 0.224, 0.225], np.float32)


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, x, rng: np.random.Generator | None = None):
        rng = rng if rng is not None else np.random.default_rng()
        for t in self.transforms:
            x = t(x, rng)
        return x


def _to_pil(x):
    if _HAVE_PIL and isinstance(x, Image.Image):
        return x
    raise TypeError(f"expected PIL image, got {type(x)}")


class RandomResizedCrop:
    """torchvision RandomResizedCrop: area in [0.08, 1.0], aspect in
    [3/4, 4/3], 10 tries then center-crop fallback."""

    def __init__(self, size: int, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3)):
        self.size = size
        self.scale = scale
        self.ratio = ratio

    def _sample_box(self, width, height, rng):
        area = width * height
        log_ratio = (math.log(self.ratio[0]), math.log(self.ratio[1]))
        for _ in range(10):
            target_area = area * rng.uniform(*self.scale)
            aspect = math.exp(rng.uniform(*log_ratio))
            w = int(round(math.sqrt(target_area * aspect)))
            h = int(round(math.sqrt(target_area / aspect)))
            if 0 < w <= width and 0 < h <= height:
                i = rng.integers(0, height - h + 1)
                j = rng.integers(0, width - w + 1)
                return int(i), int(j), h, w
        # fallback: center crop at clamped aspect
        in_ratio = width / height
        if in_ratio < self.ratio[0]:
            w = width
            h = int(round(w / self.ratio[0]))
        elif in_ratio > self.ratio[1]:
            h = height
            w = int(round(h * self.ratio[1]))
        else:
            w, h = width, height
        i = (height - h) // 2
        j = (width - w) // 2
        return i, j, h, w

    def __call__(self, img, rng: np.random.Generator):
        img = _to_pil(img)
        i, j, h, w = self._sample_box(img.width, img.height, rng)
        img = img.resize(
            (self.size, self.size),
            Image.BILINEAR,
            box=(j, i, j + w, i + h),
        )
        return img


class RandomHorizontalFlip:
    def __init__(self, p: float = 0.5):
        self.p = p

    def __call__(self, img, rng: np.random.Generator):
        img = _to_pil(img)
        if rng.random() < self.p:
            return img.transpose(Image.FLIP_LEFT_RIGHT)
        return img


class Resize:
    """Resize the short side to ``size`` keeping aspect (torchvision int arg)."""

    def __init__(self, size: int):
        self.size = size

    def __call__(self, img, rng=None):
        img = _to_pil(img)
        w, h = img.width, img.height
        if w <= h:
            new_w, new_h = self.size, max(int(round(h * self.size / w)), 1)
        else:
            new_h, new_w = self.size, max(int(round(w * self.size / h)), 1)
        return img.resize((new_w, new_h), Image.BILINEAR)


class CenterCrop:
    def __init__(self, size: int):
        self.size = size

    def __call__(self, img, rng=None):
        img = _to_pil(img)
        left = (img.width - self.size) // 2
        top = (img.height - self.size) // 2
        return img.crop((left, top, left + self.size, top + self.size))


class ToArray:
    """PIL → float32 HWC in [0,1] (torchvision ToTensor minus the CHW flip —
    TPU convs want NHWC)."""

    def __call__(self, img, rng=None):
        arr = np.asarray(_to_pil(img).convert("RGB"), np.float32) / 255.0
        return arr


class Normalize:
    def __init__(self, mean=IMAGENET_MEAN, std=IMAGENET_STD):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def __call__(self, arr, rng=None):
        return (arr - self.mean) / self.std


def train_transform(size: int = 224, normalize: bool = True) -> Compose:
    """Reference train pipeline (``restnet_ddp.py:101-106``)."""
    ts = [RandomResizedCrop(size), RandomHorizontalFlip(), ToArray()]
    if normalize:
        ts.append(Normalize())
    return Compose(ts)


def eval_transform(size: int = 224, resize: int = 256, normalize: bool = True) -> Compose:
    """Reference val pipeline (``restnet_ddp.py:111-116``)."""
    ts = [Resize(resize), CenterCrop(size), ToArray()]
    if normalize:
        ts.append(Normalize())
    return Compose(ts)
