"""Synthetic classification dataset for tests/CI and input-free benchmarks.

The reference validates only on the real cluster with real ImageNet
(SURVEY.md §4); a deterministic synthetic stand-in is what makes this
framework testable anywhere. Samples are generated on demand from the index
(no storage), labels are derived from the index, and the image content
correlates with the label so a model can actually learn — loss-goes-down
tests stay meaningful.
"""

from __future__ import annotations

import numpy as np


class SyntheticImageClassification:
    """Deterministic fake image classification data.

    ``dataset[i]`` → ``(image HWC float32, label int)``; same index always
    yields the same sample (seeded per-index), so resume/parity tests can
    compare runs bit-for-bit.
    """

    def __init__(
        self,
        size: int = 1024,
        image_size: int = 224,
        num_classes: int = 1000,
        seed: int = 0,
    ):
        self.size = size
        self.image_size = image_size
        self.num_classes = num_classes
        self.seed = seed

    def __len__(self) -> int:
        return self.size

    def __getitem__(self, i: int):
        if not 0 <= i < self.size:
            raise IndexError(i)
        label = i % self.num_classes
        rng = np.random.default_rng(self.seed * 1_000_003 + i)
        img = rng.normal(0.0, 1.0, (self.image_size, self.image_size, 3))
        # class-dependent mean shift so the task is learnable
        img += (label / max(self.num_classes - 1, 1)) - 0.5
        return img.astype(np.float32), label
