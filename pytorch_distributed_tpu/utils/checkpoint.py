"""Checkpoint serialization and the latest/best artifact contract.

Replaces ``torch.save(state, 'latest.pt')`` / ``torch.load(...,
map_location='cpu')`` (D4; ``restnet_ddp.py:45,127-132,150``) with an atomic
msgpack pytree checkpoint:

- one canonical layout shared by every parallelism mode (the reference keeps
  this invariant by always saving the unwrapped ``model.module.state_dict()``,
  ``restnet_ddp.py:38``): ``{state: TrainState pytree, epoch, step,
  best_acc}`` — restores from a 1-chip run onto a pod and back;
- atomic: write to a temp file in the same directory, fsync, rename — a
  preemption mid-write can never corrupt ``latest.ckpt`` (torch.save has the
  same failure mode the reference ignores);
- rank-0-gated by the caller (ref ``restnet_ddp.py:36,145``) — parameters
  are replicated, so one host's copy is the global truth;
- optional background-thread save so the step loop doesn't stall on disk
  (the suspend path saves synchronously — it's about to yield anyway).

Artifacts mirror the reference: ``latest.ckpt`` = full training state,
written on suspend (not periodic — same policy, SURVEY.md §5);
``best.ckpt`` = written on validation improvement (``restnet_ddp.py:145-150``).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Optional

import jax
import numpy as np
from flax import serialization

LATEST = "latest.ckpt"
BEST = "best.ckpt"


def gather_global(tree: Any) -> Any:
    """Materialize every leaf as a host numpy array of the GLOBAL value.

    Locally-readable leaves (fully addressable, or fully replicated across
    hosts) are a straight ``device_get``. A leaf SHARDED across processes
    (multi-host TP/EP/FSDP) is gathered with ``process_allgather`` — a
    COLLECTIVE: every process in the job must call ``gather_global``
    together, even ranks that will discard the result. The trainer
    therefore builds checkpoint payloads on all ranks and gates only the
    disk write on rank 0 (``restnet_ddp.py:36,145`` semantics). For plain
    replicated DP (every reference mode) no collective runs and this is
    exactly the old fast path.
    """

    def leaf_to_host(x):
        if _needs_gather(x):
            from jax.experimental import multihost_utils

            return np.asarray(multihost_utils.process_allgather(x, tiled=True))
        return np.asarray(jax.device_get(x))

    return jax.tree.map(leaf_to_host, tree)


def _needs_gather(x) -> bool:
    """True for arrays whose global value is NOT locally readable: sharded
    across processes and not replicated. Fully-replicated multi-host arrays
    are readable from any single process (``device_get`` uses the local
    copy), so plain multi-host DP never needs the collective."""
    return (
        isinstance(x, jax.Array)
        and not x.is_fully_addressable
        and not x.is_fully_replicated
    )


def _to_host(tree: Any) -> Any:
    """Host-side snapshot for serialization. NOT a collective: leaves must
    be locally readable (pass trees through ``gather_global`` first in
    multi-host sharded runs — calling this from a rank-gated branch with
    cross-process-sharded arrays would otherwise hang the job in a
    one-sided collective)."""

    def leaf_to_host(x):
        if _needs_gather(x):
            raise ValueError(
                "checkpoint payload contains an array sharded across "
                "processes; gather it on ALL processes with "
                "utils.checkpoint.gather_global(tree) before the rank-0 "
                "save call (process_allgather is a collective)."
            )
        return np.asarray(jax.device_get(x))

    return jax.tree.map(leaf_to_host, tree)


def save_checkpoint(path: str | os.PathLike, payload: Any) -> None:
    """Atomically serialize a pytree payload to ``path``."""
    path = os.fspath(path)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    state_dict = serialization.to_state_dict(_to_host(payload))
    blob = serialization.msgpack_serialize(state_dict)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def load_checkpoint(path: str | os.PathLike, template: Any) -> Any:
    """Restore a payload saved by ``save_checkpoint`` into the structure of
    ``template`` (≙ ``load_state_dict``, ``restnet_ddp.py:128-132``).
    Arrays come back as numpy on host — the trainer re-places them onto the
    mesh with the right sharding (≙ ``map_location='cpu'`` then ``.cuda()``).
    """
    with open(os.fspath(path), "rb") as f:
        state_dict = serialization.msgpack_restore(f.read())
    return serialization.from_state_dict(template, state_dict)


MANIFEST = "manifest.json"


def _tree_paths(tree):
    import jax.tree_util as jtu

    flat, treedef = jtu.tree_flatten_with_path(tree)
    paths = []
    for path, leaf in flat:
        parts = []
        for p in path:
            name = getattr(p, "key", None)
            if name is None:
                name = getattr(p, "name", None)
            if name is None:
                name = str(getattr(p, "idx", p))
            parts.append(str(name))
        paths.append("/".join(parts))
    return paths, [leaf for _, leaf in flat], treedef


def _canonical_blocks(x: jax.Array):
    """Deterministic global block layout of a jax.Array: one canonical
    owner device per distinct index tuple. Ownership round-robins over the
    processes holding replicas of each block (a min-device-id rule would
    pile every replicated block onto process 0 — the model axis is the
    innermost, so process 0 holds a replica of everything). Every process
    computes the SAME layout from sharding metadata alone — that is what
    lets rank 0 write a complete manifest without any communication."""
    groups: dict = {}
    for dev, idx in x.sharding.devices_indices_map(x.shape).items():
        key = tuple(
            (sl.start or 0, sl.stop if sl.stop is not None else dim)
            for sl, dim in zip(idx, x.shape)
        )
        groups.setdefault(key, []).append(dev)
    owners = {}
    for i, (key, devs) in enumerate(sorted(groups.items())):
        procs = sorted({d.process_index for d in devs})
        proc = procs[i % len(procs)]
        owners[key] = min(
            (d for d in devs if d.process_index == proc), key=lambda d: d.id
        )
    return owners  # {((start, stop), ...): owner_device}


def save_sharded(dirpath: str | os.PathLike, payload: Any) -> None:
    """Per-process sharded checkpoint: NO process materializes the global
    state (the scaling fix for ``gather_global``'s full host gather —
    VERDICT r2 missing #5).

    Layout: ``<dirpath>/shard-NNNNN.npz`` (uncompressed zip of raw block
    buffers — msgpack measured 8.7x slower than the disk) holds the blocks
    whose canonical owner device lives on process NNNNN; ``manifest.json``
    (rank 0) records every leaf's dtype/shape and block table, computed
    from sharding metadata identically on every process. Replicated
    leaves, numpy arrays, and scalars are rank-0-owned single blocks.
    COLLECTIVE in the weak sense: every process must call it (each writes
    its own file); a cross-host barrier at the end guarantees all files
    landed before anyone proceeds to yield/exit. Atomic per file
    (tmp+rename, like ``save_checkpoint``).
    """
    import json

    dirpath = os.fspath(dirpath)
    if os.path.isfile(dirpath):
        try:  # a legacy single-file checkpoint of the same name; every
            os.remove(dirpath)  # process races on a shared fs — one wins
        except FileNotFoundError:
            pass
    os.makedirs(dirpath, exist_ok=True)
    pidx = jax.process_index()

    # Save token: guards against TORN saves. A crash mid-save can leave a
    # directory mixing this save's shard files with a previous save's (the
    # per-file tmp+rename is atomic per FILE, not per checkpoint). Every
    # shard embeds the token; the manifest — written LAST, after a barrier
    # on the data files — records it; load refuses a mismatch. The token
    # is agreed via broadcast so it needs no shared clock.
    token = os.urandom(8).hex()
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        token_arr = np.frombuffer(bytes.fromhex(token), np.uint8)
        token = bytes(
            np.asarray(
                multihost_utils.broadcast_one_to_all(token_arr)
            ).tobytes()
        ).hex()
    paths, leaves, _ = _tree_paths(payload)

    my_blocks: dict[str, np.ndarray] = {}
    manifest: dict[str, Any] = {"version": 1,
                                "n_processes": jax.process_count(),
                                "leaves": {}}
    for path, leaf in zip(paths, leaves):
        # Block-decompose every non-replicated array (not just the
        # cross-process ones): the single-process save then exercises the
        # same layout/assembly path the pod uses, and blocks never exceed
        # one device's shard.
        if (
            isinstance(leaf, jax.Array)
            and leaf.ndim > 0
            and not leaf.is_fully_replicated
        ):
            layout = _canonical_blocks(leaf)
            local = {
                tuple(
                    (sl.start or 0,
                     sl.stop if sl.stop is not None else dim)
                    for sl, dim in zip(sh.index, leaf.shape)
                ): sh
                for sh in leaf.addressable_shards
            }
            blocks = []
            for i, (key, dev) in enumerate(sorted(layout.items())):
                entry = {
                    "file": f"shard-{dev.process_index:05d}.npz",
                    "key": f"{path}#{i}",
                    "start": [s for s, _ in key],
                    "stop": [e for _, e in key],
                }
                blocks.append(entry)
                if dev.process_index == pidx:
                    my_blocks[entry["key"]] = np.asarray(local[key].data)
            arr_like = leaf
        else:
            arr = np.asarray(
                jax.device_get(leaf) if isinstance(leaf, jax.Array) else leaf
            )
            blocks = [{
                "file": "shard-00000.npz",
                "key": f"{path}#0",
                "start": [0] * arr.ndim,
                "stop": list(arr.shape),
            }]
            if pidx == 0:
                my_blocks[f"{path}#0"] = arr
            arr_like = arr
        manifest["leaves"][path] = {
            "dtype": str(np.dtype(arr_like.dtype)),
            "shape": list(arr_like.shape),
            "blocks": blocks,
        }

    manifest["token"] = token
    # raw byte views (bf16 etc. have no numpy descr; the manifest carries
    # the true dtype) — np.savez streams each buffer straight to disk
    fname = os.path.join(dirpath, f"shard-{pidx:05d}.npz")
    tmp = f"{fname}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        np.savez(
            f,
            __token__=np.frombuffer(bytes.fromhex(token), np.uint8),
            **{
                k: np.ascontiguousarray(v).reshape(-1).view(np.uint8)
                for k, v in my_blocks.items()
            },
        )
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, fname)

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        # all data files on disk BEFORE the manifest makes the save valid
        multihost_utils.sync_global_devices(f"ckpt-data:{dirpath}")

    if pidx == 0:
        mtmp = os.path.join(dirpath, f"{MANIFEST}.tmp.{os.getpid()}")
        with open(mtmp, "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(mtmp, os.path.join(dirpath, MANIFEST))

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"ckpt:{dirpath}")


def load_sharded(
    dirpath: str | os.PathLike, template: Any, shardings: Any = None
) -> Any:
    """Restore a ``save_sharded`` directory into ``template``'s structure.

    With a ``shardings`` pytree (template-shaped, leaves
    ``jax.sharding.Sharding`` or None), array leaves are built with
    ``jax.make_array_from_callback`` reading ONLY the blocks overlapping
    each local device shard — no process assembles a full copy of a
    sharded leaf. Without it, leaves come back as full numpy (the
    single-process / legacy-compatible path).
    """
    import json

    import jax.tree_util as jtu

    dirpath = os.fspath(dirpath)
    with open(os.path.join(dirpath, MANIFEST)) as f:
        manifest = json.load(f)

    shard_cache: dict[str, dict] = {}

    token = manifest.get("token")

    def _file(fname):
        if fname not in shard_cache:
            # NpzFile is lazy: only the members a process actually needs
            # are read and decompressed (store is uncompressed anyway)
            npz = np.load(os.path.join(dirpath, fname), allow_pickle=False)
            if token is not None:
                got = bytes(np.asarray(npz["__token__"]).tobytes()).hex()
                if got != token:
                    raise RuntimeError(
                        f"torn checkpoint at {dirpath}: {fname} belongs to "
                        f"save {got}, manifest says {token} — a crash "
                        "interrupted a save; restore an older checkpoint"
                    )
            shard_cache[fname] = npz
        return shard_cache[fname]

    def _read_region(meta, start, stop):
        """Assemble [start, stop) of a leaf from overlapping blocks."""
        for b in meta["blocks"]:
            if b["start"] == list(start) and b["stop"] == list(stop):
                # exact-match fast path (same sharding at restore): no
                # assembly copy
                bshape = [e - s for s, e in zip(b["start"], b["stop"])]
                return (
                    _file(b["file"])[b["key"]]
                    .view(np.dtype(meta["dtype"]))
                    .reshape(bshape)
                )
        out = np.empty(
            [e - s for s, e in zip(start, stop)], np.dtype(meta["dtype"])
        )
        for b in meta["blocks"]:
            lo = [max(s, bs) for s, bs in zip(start, b["start"])]
            hi = [min(e, be) for e, be in zip(stop, b["stop"])]
            if any(l >= h for l, h in zip(lo, hi)):
                continue
            bshape = [e - s for s, e in zip(b["start"], b["stop"])]
            block = (
                _file(b["file"])[b["key"]]
                .view(np.dtype(meta["dtype"]))
                .reshape(bshape)
            )
            src = tuple(
                slice(l - bs, h - bs)
                for l, h, bs in zip(lo, hi, b["start"])
            )
            dst = tuple(
                slice(l - s, h - s) for l, h, s in zip(lo, hi, start)
            )
            out[dst] = block[src] if out.ndim else block
        return out

    paths, t_leaves, treedef = _tree_paths(template)
    if shardings is None:
        s_leaves = [None] * len(t_leaves)
    else:
        s_paths, s_leaves, _ = _tree_paths(shardings)

    restored = []
    for path, tleaf, sleaf in zip(paths, t_leaves, s_leaves):
        meta = manifest["leaves"].get(path)
        if meta is None:
            raise KeyError(
                f"checkpoint at {dirpath} has no leaf {path!r}; the "
                "template's structure must match the saved payload"
            )
        shape = tuple(meta["shape"])
        if isinstance(sleaf, jax.sharding.Sharding) and shape:
            arr = jax.make_array_from_callback(
                shape, sleaf,
                lambda idx, meta=meta, shape=shape: _read_region(
                    meta,
                    [sl.start or 0 for sl in idx],
                    [sl.stop if sl.stop is not None else d
                     for sl, d in zip(idx, shape)],
                ),
            )
        else:
            arr = _read_region(meta, [0] * len(shape), list(shape))
        restored.append(arr)
    return jtu.tree_unflatten(treedef, restored)


class Checkpointer:
    """latest/best artifact manager for a save directory.

    ``save_latest`` optionally runs in a background thread (``wait()`` to
    join — the suspend path does); ``save_best`` is called on metric
    improvement only, like ``restnet_ddp.py:145-150``.
    """

    def __init__(self, save_dir: str | os.PathLike):
        self.save_dir = os.fspath(save_dir)
        self._thread: Optional[threading.Thread] = None

    def _path(self, name: str) -> str:
        return os.path.join(self.save_dir, name)

    @property
    def latest_path(self) -> str:
        return self._path(LATEST)

    @property
    def best_path(self) -> str:
        return self._path(BEST)

    def has_latest(self) -> bool:
        if os.path.isdir(self.latest_path):
            return self.latest_is_sharded()
        return os.path.exists(self.latest_path)

    def latest_is_sharded(self) -> bool:
        # a dir without a manifest is a save that died before completion —
        # not a restorable checkpoint
        return os.path.isdir(self.latest_path) and os.path.exists(
            os.path.join(self.latest_path, MANIFEST)
        )

    def save_latest_sharded(self, payload: Any) -> None:
        """Per-process sharded save of latest (call on ALL processes; see
        ``save_sharded``). Synchronous — the suspend path is about to
        yield, and the cross-host barrier must not run on a thread."""
        self.wait()
        save_sharded(self.latest_path, payload)

    def save_best_sharded(self, payload: Any) -> None:
        save_sharded(self.best_path, payload)

    def load_latest_sharded(self, template: Any, shardings: Any = None) -> Any:
        return load_sharded(self.latest_path, template, shardings)

    def save_latest(self, payload: Any, block: bool = True) -> None:
        if block:
            save_checkpoint(self.latest_path, payload)
            return
        payload = _to_host(payload)  # snapshot before handing to the thread
        self.wait()
        self._thread = threading.Thread(
            target=save_checkpoint, args=(self.latest_path, payload), daemon=True
        )
        self._thread.start()

    def save_best(self, payload: Any) -> None:
        save_checkpoint(self.best_path, payload)

    def load_latest(self, template: Any) -> Any:
        if self.latest_is_sharded():
            return load_sharded(self.latest_path, template)
        return load_checkpoint(self.latest_path, template)

    def load_best(self, template: Any) -> Any:
        if os.path.isdir(self.best_path):
            return load_sharded(self.best_path, template)
        return load_checkpoint(self.best_path, template)

    def wait(self) -> None:
        if self._thread is not None:
            self._thread.join()
            self._thread = None
