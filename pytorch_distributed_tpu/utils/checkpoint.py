"""Checkpoint serialization and the latest/best artifact contract.

Replaces ``torch.save(state, 'latest.pt')`` / ``torch.load(...,
map_location='cpu')`` (D4; ``restnet_ddp.py:45,127-132,150``) with an atomic
msgpack pytree checkpoint:

- one canonical layout shared by every parallelism mode (the reference keeps
  this invariant by always saving the unwrapped ``model.module.state_dict()``,
  ``restnet_ddp.py:38``): ``{state: TrainState pytree, epoch, step,
  best_acc}`` — restores from a 1-chip run onto a pod and back;
- atomic: write to a temp file in the same directory, fsync, rename — a
  preemption mid-write can never corrupt ``latest.ckpt`` (torch.save has the
  same failure mode the reference ignores);
- rank-0-gated by the caller (ref ``restnet_ddp.py:36,145``) — parameters
  are replicated, so one host's copy is the global truth;
- optional background-thread save so the step loop doesn't stall on disk
  (the suspend path saves synchronously — it's about to yield anyway).

Artifacts mirror the reference: ``latest.ckpt`` = full training state,
written on suspend (not periodic — same policy, SURVEY.md §5);
``best.ckpt`` = written on validation improvement (``restnet_ddp.py:145-150``).
"""

from __future__ import annotations

import os
import re
import threading
from typing import Any, Optional

import jax
import numpy as np
from flax import serialization

from pytorch_distributed_tpu.resilience.faults import fault_point
from pytorch_distributed_tpu.resilience.retry import retry_call

LATEST = "latest.ckpt"
BEST = "best.ckpt"


def gather_global(tree: Any) -> Any:
    """Materialize every leaf as a host numpy array of the GLOBAL value.

    Locally-readable leaves (fully addressable, or fully replicated across
    hosts) are a straight ``device_get``. A leaf SHARDED across processes
    (multi-host TP/EP/FSDP) is gathered with ``process_allgather`` — a
    COLLECTIVE: every process in the job must call ``gather_global``
    together, even ranks that will discard the result. The trainer
    therefore builds checkpoint payloads on all ranks and gates only the
    disk write on rank 0 (``restnet_ddp.py:36,145`` semantics). For plain
    replicated DP (every reference mode) no collective runs and this is
    exactly the old fast path.
    """

    def leaf_to_host(x):
        if _needs_gather(x):
            from jax.experimental import multihost_utils

            return np.asarray(multihost_utils.process_allgather(x, tiled=True))
        return np.asarray(jax.device_get(x))

    return jax.tree.map(leaf_to_host, tree)


def _needs_gather(x) -> bool:
    """True for arrays whose global value is NOT locally readable: sharded
    across processes and not replicated. Fully-replicated multi-host arrays
    are readable from any single process (``device_get`` uses the local
    copy), so plain multi-host DP never needs the collective."""
    return (
        isinstance(x, jax.Array)
        and not x.is_fully_addressable
        and not x.is_fully_replicated
    )


def _owned_host_copy(x) -> np.ndarray:
    """Host numpy array that OWNS its memory. On TPU ``device_get``
    already copies; on the CPU backend ``np.asarray(jax_array)`` returns a
    zero-copy VIEW of the live buffer — which the next donated train step
    would reuse under a background writer's feet. Copy whenever numpy
    doesn't own the data."""
    arr = np.asarray(x)
    if not arr.flags["OWNDATA"] and not isinstance(x, np.ndarray):
        arr = np.array(arr)
    return arr


def _to_host(tree: Any) -> Any:
    """Host-side snapshot for serialization. NOT a collective: leaves must
    be locally readable (pass trees through ``gather_global`` first in
    multi-host sharded runs — calling this from a rank-gated branch with
    cross-process-sharded arrays would otherwise hang the job in a
    one-sided collective)."""

    def leaf_to_host(x):
        if _needs_gather(x):
            raise ValueError(
                "checkpoint payload contains an array sharded across "
                "processes; gather it on ALL processes with "
                "utils.checkpoint.gather_global(tree) before the rank-0 "
                "save call (process_allgather is a collective)."
            )
        return _owned_host_copy(x)

    return jax.tree.map(leaf_to_host, tree)


def save_checkpoint(path: str | os.PathLike, payload: Any) -> None:
    """Atomically serialize a pytree payload to ``path``."""
    path = os.fspath(path)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    state_dict = serialization.to_state_dict(_to_host(payload))
    blob = serialization.msgpack_serialize(state_dict)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def load_checkpoint(path: str | os.PathLike, template: Any) -> Any:
    """Restore a payload saved by ``save_checkpoint`` into the structure of
    ``template`` (≙ ``load_state_dict``, ``restnet_ddp.py:128-132``).
    Arrays come back as numpy on host — the trainer re-places them onto the
    mesh with the right sharding (≙ ``map_location='cpu'`` then ``.cuda()``).
    """
    with open(os.fspath(path), "rb") as f:
        state_dict = serialization.msgpack_restore(f.read())
    return serialization.from_state_dict(template, state_dict)


MANIFEST = "manifest.json"

# shard-<token>-NNNNN.npz (current) or shard-NNNNN.npz (pre-r4 legacy)
_SHARD_RE = re.compile(r"^shard-(?:([0-9a-f]+)-)?(\d{5})\.npz$")


def _shard_name(token: str, pidx: int) -> str:
    return f"shard-{token}-{pidx:05d}.npz"


def _tree_paths(tree):
    import jax.tree_util as jtu

    flat, treedef = jtu.tree_flatten_with_path(tree)
    paths = []
    for path, leaf in flat:
        parts = []
        for p in path:
            name = getattr(p, "key", None)
            if name is None:
                name = getattr(p, "name", None)
            if name is None:
                name = str(getattr(p, "idx", p))
            parts.append(str(name))
        paths.append("/".join(parts))
    return paths, [leaf for _, leaf in flat], treedef


def _check_unique_paths(paths, where: str) -> None:
    """Two distinct leaves flattening to one path string (a dict key
    containing '/', or an int key colliding with a name) would silently
    share one manifest entry and corrupt the second leaf on restore."""
    if len(set(paths)) != len(paths):
        from collections import Counter

        dups = sorted(p for p, c in Counter(paths).items() if c > 1)
        raise ValueError(
            f"{where}: pytree flattens to duplicate leaf paths {dups!r} "
            "(a '/' inside a dict key collides with the path separator); "
            "rename the offending keys"
        )


def _payload_mesh_meta(leaves) -> Optional[dict]:
    """``{"axes": [...], "shape": [...]}`` of the mesh the payload's
    arrays live on (the first ``NamedSharding`` leaf wins — one payload is
    placed on one mesh), or None for host-only payloads. Recorded in the
    manifest so a restore onto a different topology is detectable."""
    for leaf in leaves:
        sharding = getattr(leaf, "sharding", None)
        mesh = getattr(sharding, "mesh", None)
        axis_names = getattr(mesh, "axis_names", None)
        if axis_names:
            return {
                "axes": [str(a) for a in axis_names],
                "shape": [int(mesh.shape[a]) for a in axis_names],
            }
    return None


def _canonical_blocks(x: jax.Array):
    """Deterministic global block layout of a jax.Array: one canonical
    owner device per distinct index tuple. Ownership round-robins over the
    processes holding replicas of each block (a min-device-id rule would
    pile every replicated block onto process 0 — the model axis is the
    innermost, so process 0 holds a replica of everything). Every process
    computes the SAME layout from sharding metadata alone — that is what
    lets rank 0 write a complete manifest without any communication."""
    groups: dict = {}
    for dev, idx in x.sharding.devices_indices_map(x.shape).items():
        key = tuple(
            (sl.start or 0, sl.stop if sl.stop is not None else dim)
            for sl, dim in zip(idx, x.shape)
        )
        groups.setdefault(key, []).append(dev)
    owners = {}
    for i, (key, devs) in enumerate(sorted(groups.items())):
        procs = sorted({d.process_index for d in devs})
        proc = procs[i % len(procs)]
        owners[key] = min(
            (d for d in devs if d.process_index == proc), key=lambda d: d.id
        )
    return owners  # {((start, stop), ...): owner_device}


class _Arena:
    """Reusable host snapshot buffer for sharded saves.

    The snapshot must COPY every local block (the live buffers are donated
    into the next train step), and on this kernel first-touch page faults
    dominate that copy: 377 separate leaf allocations held live measured
    12.4 s for a 1.5 GB state, vs 0.65 s for the same copies into reused
    pages (4 KB write-faults run ~100 MB/s here once the process maps
    jax's heap; MAP_POPULATE makes it WORSE — it pre-faults the private
    mapping read-only against the zero page and every write still CoW
    faults). One arena with ``MADV_HUGEPAGE`` (THP is in madvise mode)
    faults at 2 MB granularity — measured ~1 s/1.5 GB first fill — and
    the ``Checkpointer`` reuses it across saves, so steady-state
    best-save stalls are pure memcpy (~0.3 s/1.5 GB)."""

    def __init__(self):
        self._mm = None
        self._size = 0

    def ensure(self, nbytes: int) -> np.ndarray:
        if nbytes > self._size or self._mm is None:
            import mmap

            self._mm = mmap.mmap(
                -1, max(nbytes, 1),
                flags=mmap.MAP_PRIVATE | mmap.MAP_ANONYMOUS,
            )
            if hasattr(self._mm, "madvise") and hasattr(mmap, "MADV_HUGEPAGE"):
                self._mm.madvise(mmap.MADV_HUGEPAGE)
            self._size = max(nbytes, 1)
        return np.frombuffer(self._mm, np.uint8, count=self._size)

    def warm(self, nbytes: int) -> None:
        """Pre-fault ``nbytes`` of arena by dirtying every page. The fault
        cost is unavoidable ONCE per arena growth (~10 s/1.5 GB on this
        kernel even with THP — compaction stalls); trainers run this on a
        background thread at init, overlapped with the first XLA compile,
        so even the FIRST non-blocking save stalls only for the memcpy."""
        buf = self.ensure(nbytes)
        buf[0::4096] = 1  # one write per 4 KB page


class _ShardedSave:
    """One in-flight sharded save, split into three stages so the step
    loop only pays for the first:

    1. ``__init__`` — SNAPSHOT (synchronous, collective): broadcast-agree
       the save token, compute the block layout + manifest from sharding
       metadata, and ``device_get`` this process's blocks to host numpy.
       This must happen before the trainer's next step because the state
       arrays are donated into it.
    2. ``write`` — pure file I/O (token-named shard file, tmp+rename);
       safe on a background thread. A save NEVER overwrites the previous
       checkpoint's data files: they are named by the OLD token and stay
       referenced by the OLD manifest until step 3 replaces it — a crash
       any time before then leaves the previous checkpoint fully
       restorable (the durability fix over the r3 in-place layout).
    3. ``finalize`` — MAIN THREAD ONLY (cross-host barriers are jax
       collectives): barrier on the data files, rank-0 atomic manifest
       replace (the commit point), barrier, then GC this process's
       stale-token shard files.

    ``save_sharded`` runs all three synchronously;
    ``Checkpointer.save_*_sharded(block=False)`` runs 2 on a thread and
    defers 3 to ``Checkpointer.wait()`` — which every rank reaches at the
    same collective-ordered point (epoch end / suspend / next save).
    """

    def __init__(self, dirpath: str | os.PathLike, payload: Any,
                 arena: Optional[_Arena] = None, snapshot: bool = True):
        self.dirpath = os.fspath(dirpath)
        if os.path.isfile(self.dirpath):
            try:  # a legacy single-file checkpoint of the same name; every
                os.remove(self.dirpath)  # process races on shared fs — one wins
            except FileNotFoundError:
                pass
        os.makedirs(self.dirpath, exist_ok=True)
        self.pidx = jax.process_index()

        # Save token: names this save's files and guards against TORN
        # saves (manifest written LAST records it; load refuses any
        # manifest-referenced file carrying a different token). Agreed via
        # broadcast so it needs no shared clock.
        token = os.urandom(8).hex()
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            token_arr = np.frombuffer(bytes.fromhex(token), np.uint8)
            token = bytes(
                np.asarray(
                    multihost_utils.broadcast_one_to_all(token_arr)
                ).tobytes()
            ).hex()
        self.token = token
        self.fname = _shard_name(token, self.pidx)

        paths, leaves, _ = _tree_paths(payload)
        _check_unique_paths(paths, "save_sharded")
        mesh_meta = _payload_mesh_meta(leaves)

        # Pass 1 — metadata only: block layout + manifest + the list of
        # local blocks to snapshot (no copies yet).
        specs: list = []  # (key, src, shape, np.dtype)
        manifest: dict[str, Any] = {"version": 2,
                                    "n_processes": jax.process_count(),
                                    "leaves": {}}
        for path, leaf in zip(paths, leaves):
            # Block-decompose every non-replicated array (not just the
            # cross-process ones): the single-process save then exercises
            # the same layout/assembly path the pod uses, and blocks never
            # exceed one device's shard.
            if (
                isinstance(leaf, jax.Array)
                and leaf.ndim > 0
                and not leaf.is_fully_replicated
            ):
                layout = _canonical_blocks(leaf)
                local = {
                    tuple(
                        (sl.start or 0,
                         sl.stop if sl.stop is not None else dim)
                        for sl, dim in zip(sh.index, leaf.shape)
                    ): sh
                    for sh in leaf.addressable_shards
                }
                blocks = []
                for i, (key, dev) in enumerate(sorted(layout.items())):
                    entry = {
                        "file": _shard_name(token, dev.process_index),
                        "key": f"{path}#{i}",
                        "start": [s for s, _ in key],
                        "stop": [e for _, e in key],
                    }
                    blocks.append(entry)
                    if dev.process_index == self.pidx:
                        specs.append((
                            entry["key"], local[key].data,
                            tuple(e - s for s, e in key),
                            np.dtype(leaf.dtype),
                        ))
                arr_like = leaf
            else:
                arr_like = (
                    leaf if isinstance(leaf, jax.Array) else np.asarray(leaf)
                )
                blocks = [{
                    "file": _shard_name(token, 0),
                    "key": f"{path}#0",
                    "start": [0] * arr_like.ndim,
                    "stop": list(arr_like.shape),
                }]
                if self.pidx == 0:
                    specs.append((
                        f"{path}#0", arr_like, tuple(arr_like.shape),
                        np.dtype(arr_like.dtype),
                    ))
            manifest["leaves"][path] = {
                "dtype": str(np.dtype(arr_like.dtype)),
                "shape": list(arr_like.shape),
                "blocks": blocks,
            }
        manifest["token"] = token
        if mesh_meta is not None:
            # writer topology, for elastic resume: lets a restore onto a
            # DIFFERENT mesh shape announce itself (reshard/) and lets
            # tools refuse/permit cross-topology restores explicitly.
            # Absent for host-only payloads and pre-round-9 checkpoints.
            manifest["mesh"] = mesh_meta
        self.manifest = manifest

        # Pass 2 — SNAPSHOT: one bulk copy of every local block into a
        # single (reusable) arena. The copy is mandatory for the
        # NON-BLOCKING path — the live buffers are donated into the next
        # train step, and on the CPU backend ``np.asarray(jax_array)`` is
        # a zero-copy view of them. See ``_Arena`` for why one buffer
        # instead of per-leaf copies. BLOCKING saves (``snapshot=False``)
        # skip the copy entirely and stream straight from the sources in
        # ``write()``: the caller cannot run its next (donating) step
        # until the save returns, so there is nothing to race — this
        # removes both the memcpy and the arena's first-touch page-fault
        # cost (~10 s/1.5 GB cold, memory notes in ``_Arena``) from the
        # suspend path.
        if not snapshot:
            self.my_blocks = {
                key: src for key, src, _shape, _dtype in specs
            }
            self._arena_buf = None
            self._thread: Optional[threading.Thread] = None
            self._write_err: Optional[BaseException] = None
            self._done = False
            return
        total = 0
        offs = []
        for _key, _src, shape, dtype in specs:
            total = -(-total // 128) * 128  # 128-byte align each block
            offs.append(total)
            total += int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
        self._arena_buf = (arena or _Arena()).ensure(total)
        my_blocks: dict[str, np.ndarray] = {}
        for (key, src, shape, dtype), off in zip(specs, offs):
            nb = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
            dst = self._arena_buf[off:off + nb].view(dtype).reshape(shape)
            np.copyto(dst, np.asarray(src))
            my_blocks[key] = dst
        self.my_blocks = my_blocks
        self._thread: Optional[threading.Thread] = None
        self._write_err: Optional[BaseException] = None
        self._done = False

    def write(self) -> None:
        """Write this process's token-named shard file. Pure file I/O —
        thread-safe, no jax calls. Transient I/O errors are retried with
        bounded backoff (each attempt rewrites the tmp file from the still
        -held snapshot, so a partial attempt is never published)."""
        retry_call(self._write_once, what=f"shard write {self.fname}")
        self.my_blocks = {}  # release the host snapshot

    def _write_once(self) -> None:
        # raw byte views (bf16 etc. have no numpy descr; the manifest
        # carries the true dtype) — np.savez streams each buffer to disk
        fname = os.path.join(self.dirpath, self.fname)
        tmp = f"{fname}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            np.savez(
                f,
                __token__=np.frombuffer(
                    bytes.fromhex(self.token), np.uint8
                ),
                **{
                    # np.asarray: no-snapshot blocks are still live jax
                    # arrays (or numpy scalars) at write time
                    k: np.ascontiguousarray(np.asarray(v))
                    .reshape(-1).view(np.uint8)
                    for k, v in self.my_blocks.items()
                },
            )
            f.flush()
            os.fsync(f.fileno())
        # mid-shard-write hazard: the tmp file is complete but the shard
        # is not published — a kill here must leave the previous
        # checkpoint's manifest + files fully restorable
        fault_point("ckpt.shard_write")
        os.replace(tmp, fname)

    def _write_guarded(self) -> None:
        try:
            self.write()
        except BaseException as e:  # surfaced at finalize()
            self._write_err = e  # jaxlint: disable=thread-unsynced-mutation -- single-owner handoff: finalize() joins the writer thread before reading, so the store happens-before the only read

    def start(self) -> None:
        self._thread = threading.Thread(target=self._write_guarded,
                                        daemon=True)
        self._thread.start()

    def finalize(self) -> None:
        """Join the writer, barrier, commit the manifest, GC stale files.
        Call from the MAIN thread on every process at the same
        collectively-ordered point."""
        import json

        if self._done:
            return
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._write_err is not None:
            raise self._write_err

        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            # all data files on disk BEFORE the manifest makes them live
            multihost_utils.sync_global_devices(
                f"ckpt-data:{self.dirpath}:{self.token}"
            )

        if self.pidx == 0:
            # THE commit point: os.replace is atomic, and the old
            # manifest's files are untouched until the GC below.
            mtmp = os.path.join(self.dirpath,
                                f"{MANIFEST}.tmp.{os.getpid()}")
            with open(mtmp, "w") as f:
                json.dump(self.manifest, f)
                f.flush()
                os.fsync(f.fileno())
            # pre-commit hazard: every data file landed, manifest not yet
            # replaced — a kill here must restore the OLD checkpoint
            fault_point("ckpt.pre_commit")
            os.replace(mtmp, os.path.join(self.dirpath, MANIFEST))
            # post-commit hazard: the new checkpoint is live but stale-
            # token GC has not run — a kill here must restore the NEW one
            fault_point("ckpt.post_commit")

        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(
                f"ckpt:{self.dirpath}:{self.token}"
            )

        # GC: every process removes ITS OWN rank's shard files from
        # superseded saves (older tokens + pre-r4 tokenless names) and any
        # orphaned tmp files. Only after the commit barrier — a reader
        # before it was reading the old manifest's files.
        for name in os.listdir(self.dirpath):
            m = _SHARD_RE.match(name)
            stale_shard = (
                m is not None
                and int(m.group(2)) == self.pidx
                and (m.group(1) or "") != self.token
            )
            stale_tmp = (
                f".npz.tmp." in name
                and f"-{self.pidx:05d}.npz.tmp." in name
                and not name.startswith(f"shard-{self.token}-")
            )
            if stale_shard or stale_tmp:
                try:
                    os.remove(os.path.join(self.dirpath, name))
                except OSError:
                    pass
        self._done = True


def save_sharded(dirpath: str | os.PathLike, payload: Any) -> None:
    """Per-process sharded checkpoint: NO process materializes the global
    state (the scaling fix for ``gather_global``'s full host gather —
    VERDICT r2 missing #5).

    Layout: ``<dirpath>/shard-<token>-NNNNN.npz`` (uncompressed zip of raw
    block buffers — msgpack measured 8.7x slower than the disk) holds the
    blocks whose canonical owner device lives on process NNNNN;
    ``manifest.json`` (rank 0, written last, atomic replace) records every
    leaf's dtype/shape and block table, computed from sharding metadata
    identically on every process. Replicated leaves, numpy arrays, and
    scalars are rank-0-owned single blocks. COLLECTIVE in the weak sense:
    every process must call it (each writes its own file); a cross-host
    barrier before the manifest guarantees all files landed. Atomic at
    CHECKPOINT granularity: files are token-named, so a crash mid-save
    leaves the previous save's manifest + files intact and restorable
    (see ``_ShardedSave``). Synchronous; for the non-stalling trainer
    path use ``Checkpointer.save_*_sharded(block=False)`` + ``wait()``.
    """
    s = _ShardedSave(dirpath, payload, snapshot=False)
    s.write()
    s.finalize()


class _RawNpz:
    """Zero-copy reader for the uncompressed ``.npz`` files ``np.savez``
    writes: mmap the zip once, resolve each member's raw-data offset from
    the local file headers, and serve members as ``np.frombuffer`` views.
    Skips the per-member stream+CRC pass ``np.load`` does — restore cost
    becomes the assembly copies / ``device_put`` alone, with cold pages
    faulted in by the kernel during the copy. Views are READ-ONLY;
    ``load_sharded`` copies on any path that hands arrays to the caller
    unsharded. Raises on anything unexpected (compressed members, odd npy
    headers); the caller falls back to ``np.load``."""

    def __init__(self, path: str):
        import mmap
        import zipfile

        self._f = open(path, "rb")
        self._mm = mmap.mmap(self._f.fileno(), 0, access=mmap.ACCESS_READ)
        self._members: dict[str, tuple[int, int]] = {}
        with zipfile.ZipFile(self._f) as zf:
            for info in zf.infolist():
                if info.compress_type != zipfile.ZIP_STORED:
                    raise ValueError("compressed member")
                ho = info.header_offset
                if self._mm[ho:ho + 4] != b"PK\x03\x04":
                    raise ValueError("bad local header")
                # local-header extra field length can differ from the
                # central directory's — read it from the local header
                fn = int.from_bytes(self._mm[ho + 26:ho + 28], "little")
                ex = int.from_bytes(self._mm[ho + 28:ho + 30], "little")
                name = info.filename
                if name.endswith(".npy"):
                    name = name[:-4]
                self._members[name] = (ho + 30 + fn + ex, info.file_size)

    def __contains__(self, key: str) -> bool:
        return key in self._members

    def __getitem__(self, key: str) -> np.ndarray:
        import io

        try:
            off, size = self._members[key]
            bio = io.BytesIO(self._mm[off:min(off + 4096, off + size)])
            version = np.lib.format.read_magic(bio)
            if version == (1, 0):
                shape, fortran, dtype = (
                    np.lib.format.read_array_header_1_0(bio)
                )
            elif version == (2, 0):
                shape, fortran, dtype = (
                    np.lib.format.read_array_header_2_0(bio)
                )
            else:
                raise ValueError(f"npy version {version}")
            if fortran:
                raise ValueError("fortran-order member")
            if bio.tell() >= 4096:
                raise ValueError("npy header exceeds the 4096-byte window")
            count = int(np.prod(shape)) if shape else 1
            arr = np.frombuffer(
                self._mm, dtype=dtype, count=count, offset=off + bio.tell()
            )
            return arr.reshape(shape)
        except KeyError:
            raise
        except Exception:
            # Constructor-time validation can't see per-member npy
            # quirks (format 3.0, oversized headers): fall back to a
            # lazy np.load for THIS file rather than failing the
            # restore (ADVICE r4 #1).
            if not hasattr(self, "_np_fallback"):
                self._np_fallback = np.load(
                    self._f.name, allow_pickle=False
                )
            return self._np_fallback[key]


class ManifestReader:
    """Block-table access to one sharded checkpoint directory.

    The engine behind :func:`load_sharded` and the ``reshard/`` subsystem:
    parses the manifest once, opens shard files through the mmap-backed
    zero-copy zip reader (``_RawNpz``, with the ``np.load`` fall-through
    and save-token verification), and assembles ANY ``[start, stop)``
    region of any leaf from the blocks that overlap it — the primitive
    that makes restore independent of the mesh that wrote the checkpoint.
    Regions are cached (``make_array_from_callback`` asks once per
    addressable device; replicated leaves repeat identical regions).

    Counters (for restore telemetry / the reshard bench): ``exact_blocks``
    regions served by the no-copy exact-match fast path,
    ``assembled_regions`` regions stitched from partially-overlapping
    blocks, ``bytes_assembled`` copied in doing so.
    """

    def __init__(self, dirpath: str | os.PathLike):
        import json

        self.dirpath = os.fspath(dirpath)
        with open(os.path.join(self.dirpath, MANIFEST)) as f:
            self.manifest = json.load(f)
        self.token = self.manifest.get("token")
        self._shard_cache: dict[str, Any] = {}
        self._region_cache: dict = {}
        self.exact_blocks = 0
        self.assembled_regions = 0
        self.bytes_assembled = 0

    @property
    def mesh_meta(self) -> Optional[dict]:
        """Writer topology ``{"axes": [...], "shape": [...]}`` or None
        (host-only payload / pre-round-9 checkpoint)."""
        return self.manifest.get("mesh")

    def leaf_paths(self) -> list:
        return list(self.manifest.get("leaves", {}))

    def leaf_meta(self, path: str) -> dict:
        meta = self.manifest.get("leaves", {}).get(path)
        if meta is None:
            raise KeyError(
                f"checkpoint at {self.dirpath} has no leaf {path!r}; the "
                "template's structure must match the saved payload"
            )
        return meta

    def _file(self, fname):
        if fname not in self._shard_cache:
            fpath = os.path.join(self.dirpath, fname)
            try:
                npz = _RawNpz(fpath)
            except OSError:
                # transient read failure (cluster fs): bounded retry before
                # falling back; np.load below re-raises hard failures
                npz = retry_call(
                    np.load, fpath, allow_pickle=False,
                    what=f"checkpoint read {fname}",
                )
            except Exception:
                # NpzFile is lazy: only members actually accessed are read
                npz = np.load(fpath, allow_pickle=False)
            if self.token is not None:
                got = bytes(np.asarray(npz["__token__"]).tobytes()).hex()
                if got != self.token:
                    raise RuntimeError(
                        f"torn checkpoint at {self.dirpath}: {fname} "
                        f"belongs to save {got}, manifest says "
                        f"{self.token} — a crash interrupted a save; "
                        "restore an older checkpoint"
                    )
            self._shard_cache[fname] = npz
        return self._shard_cache[fname]

    def _block(self, meta, b) -> np.ndarray:
        bshape = [e - s for s, e in zip(b["start"], b["stop"])]
        return (
            self._file(b["file"])[b["key"]]
            .view(np.dtype(meta["dtype"]))
            .reshape(bshape)
        )

    def read_region(self, path: str, start, stop) -> np.ndarray:
        """Assemble ``[start, stop)`` of leaf ``path`` from overlapping
        blocks (cached). Exact block matches are zero-copy mmap views —
        READ-ONLY; callers handing arrays out unsharded must copy."""
        key = (path, tuple(start), tuple(stop))
        if key not in self._region_cache:
            self._region_cache[key] = self._read_region(
                self.leaf_meta(path), start, stop
            )
        return self._region_cache[key]

    def _read_region(self, meta, start, stop):
        for b in meta["blocks"]:
            if b["start"] == list(start) and b["stop"] == list(stop):
                # exact-match fast path (the writer's sharding and the
                # reader's agree on this region): no assembly copy
                self.exact_blocks += 1
                return self._block(meta, b)
        out = np.empty(
            [e - s for s, e in zip(start, stop)], np.dtype(meta["dtype"])
        )
        for b in meta["blocks"]:
            lo = [max(s, bs) for s, bs in zip(start, b["start"])]
            hi = [min(e, be) for e, be in zip(stop, b["stop"])]
            if any(l >= h for l, h in zip(lo, hi)):
                continue
            block = self._block(meta, b)
            src = tuple(
                slice(l - bs, h - bs)
                for l, h, bs in zip(lo, hi, b["start"])
            )
            dst = tuple(
                slice(l - s, h - s) for l, h, s in zip(lo, hi, start)
            )
            out[dst] = block[src] if out.ndim else block
        self.assembled_regions += 1
        self.bytes_assembled += out.nbytes
        return out


def load_sharded(
    dirpath: str | os.PathLike, template: Any, shardings: Any = None,
    reader: Optional[ManifestReader] = None,
) -> Any:
    """Restore a ``save_sharded`` directory into ``template``'s structure.

    With a ``shardings`` pytree (template-shaped, leaves
    ``jax.sharding.Sharding`` or None), array leaves are built with
    ``jax.make_array_from_callback`` reading ONLY the blocks overlapping
    each local device shard — no process assembles a full copy of a
    sharded leaf, whether or not the target sharding matches the layout
    the writer used (cross-mesh restores stitch partially-overlapping
    blocks per shard; ``reshard/``). Without it, leaves come back as full
    numpy (the single-process / legacy-compatible path). Reads go through
    :class:`ManifestReader` (mmap-backed zero-copy zip access with a
    per-region cache); pass ``reader`` to reuse one across calls or to
    harvest its exact/assembled counters afterwards.
    """
    import jax.tree_util as jtu

    if reader is None:
        reader = ManifestReader(dirpath)

    paths, t_leaves, treedef = _tree_paths(template)
    _check_unique_paths(paths, "load_sharded")
    if shardings is None:
        s_leaves = [None] * len(t_leaves)
    else:
        s_paths, s_leaves, _ = _tree_paths(shardings)

    restored = []
    for path, tleaf, sleaf in zip(paths, t_leaves, s_leaves):
        meta = reader.leaf_meta(path)
        shape = tuple(meta["shape"])
        if isinstance(sleaf, jax.sharding.Sharding) and shape:
            arr = jax.make_array_from_callback(
                shape, sleaf,
                lambda idx, path=path, shape=shape:
                reader.read_region(
                    path,
                    [sl.start or 0 for sl in idx],
                    [sl.stop if sl.stop is not None else d
                     for sl, d in zip(idx, shape)],
                ),
            )
        else:
            arr = reader.read_region(path, [0] * len(shape), list(shape))
            if not arr.flags.writeable:
                # _RawNpz exact-match views are read-only mmap windows;
                # arrays handed to the caller unsharded must own their
                # memory (and not pin the map open)
                arr = np.array(arr)
        restored.append(arr)
    return jtu.tree_unflatten(treedef, restored)


def peek_leaf(dirpath: str | os.PathLike, leaf_path: str):
    """Read ONE leaf from a sharded checkpoint without a template —
    cheap metadata probes (e.g. which of several checkpoints is newest
    by its ``state/step``). Single-block leaves only (scalars and
    replicated arrays — block 0 carries the whole value)."""
    import json

    dirpath = os.fspath(dirpath)
    with open(os.path.join(dirpath, MANIFEST)) as f:
        manifest = json.load(f)
    meta = manifest["leaves"][leaf_path]
    if len(meta["blocks"]) != 1:
        raise ValueError(
            f"peek_leaf reads single-block leaves; {leaf_path!r} has "
            f"{len(meta['blocks'])} blocks"
        )
    b = meta["blocks"][0]
    npz = np.load(os.path.join(dirpath, b["file"]), allow_pickle=False)
    arr = npz[b["key"]].view(np.dtype(meta["dtype"]))
    return arr.reshape(meta["shape"])


def validate_checkpoint(dirpath: str | os.PathLike) -> list:
    """Problems preventing ``dirpath`` from restoring; ``[]`` means valid.

    The cheap completeness sweep behind fallback restore: manifest parses,
    every referenced shard file exists and opens as a zip (a torn write
    truncates the tail, which holds the zip central directory — so
    truncation fails the open), carries the manifest's save token, and
    contains every block key the manifest assigns to it. Does NOT read
    array payloads — cost is one directory scan plus one tiny member read
    per shard file, safe to run on every resume."""
    import json

    dirpath = os.fspath(dirpath)
    mpath = os.path.join(dirpath, MANIFEST)
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except FileNotFoundError:
        return [f"no {MANIFEST} (save died before its commit point)"]
    except (OSError, ValueError) as e:
        return [f"unreadable {MANIFEST}: {e}"]

    token = manifest.get("token")
    by_file: dict[str, set] = {}
    for leaf, meta in manifest.get("leaves", {}).items():
        for b in meta.get("blocks", []):
            by_file.setdefault(b["file"], set()).add(b["key"])

    problems = []
    for fname, keys in sorted(by_file.items()):
        fpath = os.path.join(dirpath, fname)
        try:
            with np.load(fpath, allow_pickle=False) as npz:
                members = set(npz.files)
                if token is not None:
                    got = bytes(
                        np.asarray(npz["__token__"]).tobytes()
                    ).hex()
                    if got != token:
                        problems.append(
                            f"{fname}: token {got} != manifest {token} "
                            "(torn save)"
                        )
                        continue
        except FileNotFoundError:
            problems.append(f"{fname}: missing shard file")
            continue
        except Exception as e:
            problems.append(f"{fname}: unreadable ({e})")
            continue
        lost = keys - members
        if lost:
            problems.append(
                f"{fname}: {len(lost)} manifest block(s) absent "
                f"(e.g. {sorted(lost)[0]!r})"
            )
    return problems


STEP_CKPT_RE = re.compile(r"^step-(\d{8,})\.ckpt$")  # 8+: :08d overflows


def legacy_checkpoint_step(path: str | os.PathLike) -> int:
    """``state/step`` of a LEGACY single-file msgpack checkpoint.

    The sharded ranking reads the step with a cheap ``peek_leaf``; the
    legacy format has no manifest, so this restores the msgpack blob and
    digs out ``state/step`` (falling back to the top-level ``step`` the
    payload also carries). Before round 6 the ranking hardcoded legacy
    files to step 0 — a single-file suspend save at step 1000 would LOSE
    resume to a step-100 interval checkpoint (ADVICE r5 #1)."""
    with open(os.fspath(path), "rb") as f:
        sd = serialization.msgpack_restore(f.read())
    node = sd.get("state", {})
    step = node.get("step") if isinstance(node, dict) else None
    if step is None:
        step = sd["step"]  # KeyError → caller logs and discards
    return int(np.asarray(step))


class Checkpointer:
    """latest/best artifact manager for a save directory.

    Sharded saves can run non-blocking: ``save_*_sharded(payload,
    block=False)`` pays only the device→host snapshot on the calling
    thread, writes the token-named shard file on a background thread, and
    defers the commit (cross-host barrier + manifest replace + GC) to
    ``wait()`` — which trainers call at epoch end, on suspend, and before
    any subsequent save, points every rank reaches in the same collective
    order. Until ``wait()`` commits, the previous checkpoint stays fully
    restorable (token-named files are never overwritten). ``save_best``
    fires on metric improvement only, like ``restnet_ddp.py:145-150``.
    """

    def __init__(self, save_dir: str | os.PathLike):
        from pytorch_distributed_tpu.telemetry import NULL_TRACER

        self.save_dir = os.fspath(save_dir)
        self._thread: Optional[threading.Thread] = None
        self._pending: Optional[_ShardedSave] = None
        self._arena = _Arena()  # snapshot pages reused across saves
        self._warm_thread: Optional[threading.Thread] = None
        self._step_keep: Optional[int] = None  # GC request, runs at wait()
        # span hook (telemetry/spans.py): trainers point this at their
        # tracer so snapshot/commit phases show up in the Chrome trace
        # next to data_wait/step_dispatch; default no-op
        self.tracer = NULL_TRACER

    def _path(self, name: str) -> str:
        return os.path.join(self.save_dir, name)

    @property
    def latest_path(self) -> str:
        return self._path(LATEST)

    @property
    def best_path(self) -> str:
        return self._path(BEST)

    def warm_for(self, payload: Any) -> None:
        """Pre-fault the snapshot arena for ``payload``-sized saves on a
        background thread. Call once at trainer init, after the state is
        built — the page-fault cost (the dominant cost of a first
        snapshot) then overlaps the first compile instead of the first
        best-save. Size is the full local payload footprint — exact for
        single-process runs, an over-estimate (harmless: virtual memory)
        for cross-process-sharded states."""
        def _aligned(nb: int) -> int:
            return -(-nb // 128) * 128  # mirror _ShardedSave's alignment

        nbytes = 0
        for leaf in jax.tree.leaves(payload):
            if (
                isinstance(leaf, jax.Array)
                and leaf.ndim > 0
                and not leaf.is_fully_replicated
            ):
                # sharded branch: one block per canonically-owned shard;
                # addressable shards are an upper bound on ownership
                itemsize = np.dtype(leaf.dtype).itemsize
                for s in leaf.addressable_shards:
                    nbytes += _aligned(
                        int(np.prod(s.data.shape, dtype=np.int64)) * itemsize
                    )
            elif isinstance(leaf, jax.Array):
                # replicated: snapshotted ONCE as a rank-0 block, never
                # once per device copy
                nbytes += _aligned(
                    int(np.prod(leaf.shape, dtype=np.int64))
                    * np.dtype(leaf.dtype).itemsize
                )
            else:
                nbytes += _aligned(np.asarray(leaf).nbytes)
        # the live save payload wraps the state with epoch/step/best
        # scalars the caller doesn't pass here — leave aligned headroom so
        # ensure() never discards the pre-faulted map over a few leaves
        nbytes += 64 * 1024
        self._warm_thread = threading.Thread(
            target=self._arena.warm, args=(nbytes,), daemon=True
        )
        self._warm_thread.start()

    def has_latest(self) -> bool:
        if os.path.isdir(self.latest_path):
            return self.latest_is_sharded()
        return os.path.exists(self.latest_path)

    def latest_is_sharded(self) -> bool:
        # a dir without a manifest is a save that died before completion —
        # not a restorable checkpoint
        return os.path.isdir(self.latest_path) and os.path.exists(
            os.path.join(self.latest_path, MANIFEST)
        )

    def has_best(self) -> bool:
        if os.path.isdir(self.best_path):
            return self.best_is_sharded()
        return os.path.exists(self.best_path)

    def best_is_sharded(self) -> bool:
        return os.path.isdir(self.best_path) and os.path.exists(
            os.path.join(self.best_path, MANIFEST)
        )

    def _save_sharded(self, path: str, payload: Any, block: bool) -> None:
        self.wait()  # one in-flight save at a time; commit the previous
        if block:
            # blocking: stream from the live buffers — no snapshot copy,
            # no arena (the caller waits, so donation can't race)
            with self.tracer.span("ckpt_write", blocking=True):
                s = _ShardedSave(path, payload, snapshot=False)
                s.write()
                s.finalize()
        else:
            # snapshot only (fast: bulk copy into the reused arena)
            with self.tracer.span("ckpt_snapshot"):
                s = _ShardedSave(path, payload, arena=self._arena)
            s.start()  # file write on a thread
            self._pending = s  # commit deferred to wait()

    def save_latest_sharded(self, payload: Any, block: bool = True) -> None:
        """Per-process sharded save of latest (call on ALL processes; see
        ``save_sharded``). The suspend path keeps ``block=True`` — it is
        about to yield, and the commit barrier must run before it does."""
        self._save_sharded(self.latest_path, payload, block)

    def save_best_sharded(self, payload: Any, block: bool = True) -> None:
        self._save_sharded(self.best_path, payload, block)

    # ---- step-interval checkpoints (save_every_n_steps, round 5) ----

    def step_path(self, step: int) -> str:
        return self._path(f"step-{int(step):08d}.ckpt")

    def step_checkpoints(self) -> list:
        """Completed (manifest-bearing) step checkpoints, oldest→newest
        by the step number in the name."""
        out = []
        if not os.path.isdir(self.save_dir):
            return out
        for name in os.listdir(self.save_dir):
            m = STEP_CKPT_RE.match(name)
            p = os.path.join(self.save_dir, name)
            if m and os.path.exists(os.path.join(p, MANIFEST)):
                out.append((int(m.group(1)), p))
        return sorted(out)  # numeric, not lexicographic (9+-digit steps)

    def save_step_sharded(self, payload: Any, step: int,
                          keep_last: int = 3, block: bool = False) -> None:
        """Interval checkpoint ``step-<step>.ckpt`` on the non-stalling
        sharded path (the reference saves only on suspend and on val
        improvement, ``restnet_ddp.py:37-45,145-150`` — a multi-day run
        between val epochs has zero durability; this is the missing
        ``save_every_n_steps`` policy, VERDICT r4 next #6). Retention:
        after this save COMMITS (at ``wait()``), completed step
        checkpoints beyond the newest ``keep_last`` are removed —
        incomplete ones (no manifest) are never counted as kept, and the
        GC runs only after the new save's manifest landed, so it can
        never delete the only complete checkpoint."""
        if keep_last < 1:
            raise ValueError(f"keep_last must be >= 1, got {keep_last}")
        self._save_sharded(self.step_path(step), payload, block)
        self._step_keep = keep_last
        if block:
            self._gc_steps()

    def _gc_steps(self) -> None:
        """Remove completed step checkpoints beyond the newest
        ``_step_keep``, and incomplete step dirs older than the newest
        completed one (debris from crashed saves). Rank 0 only, AFTER the
        commit barrier (shared-fs model, same as the manifest)."""
        import shutil

        keep, self._step_keep = self._step_keep, None
        if keep is None or jax.process_index() != 0:
            return
        done = self.step_checkpoints()
        for _step, path in done[:-keep] if len(done) > keep else []:
            shutil.rmtree(path, ignore_errors=True)
        if done:
            newest_done = done[-1][0]
            for name in os.listdir(self.save_dir):
                m = STEP_CKPT_RE.match(name)
                p = os.path.join(self.save_dir, name)
                if (
                    m and int(m.group(1)) < newest_done
                    and not os.path.exists(os.path.join(p, MANIFEST))
                ):
                    shutil.rmtree(p, ignore_errors=True)

    def restorable_paths(self) -> list:
        """Every VALIDATED restorable checkpoint, newest-first by saved
        ``state/step`` (ties prefer ``latest.ckpt``). Candidates that fail
        :func:`validate_checkpoint` — truncated shard, token mismatch,
        missing blocks — are logged and skipped, so a run whose newest
        save was torn by a crash falls back to the newest *complete* one
        instead of refusing to start (the fallback-restore contract;
        ANALYSIS.md "Failure model & recovery guarantees")."""
        from pytorch_distributed_tpu.utils.logging import rank0_print

        candidates = [p for _s, p in self.step_checkpoints()]
        if self.has_latest():
            candidates.append(self.latest_path)
            if not os.path.isdir(self.latest_path) and len(candidates) > 1:
                rank0_print(
                    f"checkpoint fallback: legacy single-file "
                    f"{self.latest_path} coexists with sharded step "
                    "checkpoints; ranking it by its recorded state/step"
                )
        ranked = []  # (step, tie_rank, path): later candidates win ties
        for rank, p in enumerate(candidates):
            try:
                if os.path.isdir(p):
                    s = int(np.asarray(peek_leaf(p, "state/step")))
                else:
                    # legacy single-file latest: rank by its REAL step
                    # (hardcoding 0 here let an older interval save win
                    # resume over a newer suspend save — ADVICE r5 #1)
                    s = legacy_checkpoint_step(p)
            except Exception as e:
                rank0_print(
                    f"checkpoint fallback: discarding {p} "
                    f"(unreadable step leaf: {e})"
                )
                continue
            ranked.append((s, rank, p))
        out = []
        for s, _rank, p in sorted(ranked, reverse=True):
            if os.path.isdir(p):
                problems = validate_checkpoint(p)
                if problems:
                    rank0_print(
                        f"checkpoint fallback: discarding {p} at step {s}: "
                        + "; ".join(problems)
                    )
                    continue
            out.append(p)
        return out

    def newest_restorable(self) -> Optional[str]:
        """The newest restorable checkpoint that passes validation:
        ``latest.ckpt`` (suspend save) or a step-interval checkpoint,
        whichever carries the highest ``state/step`` — scanning back past
        corrupt candidates (see ``restorable_paths``)."""
        paths = self.restorable_paths()
        return paths[0] if paths else None

    def load_latest_sharded(self, template: Any, shardings: Any = None) -> Any:
        self.wait()
        return load_sharded(self.latest_path, template, shardings)

    def save_latest(self, payload: Any, block: bool = True) -> None:
        if block:
            save_checkpoint(self.latest_path, payload)
            return
        payload = _to_host(payload)  # snapshot before handing to the thread
        self.wait()
        self._thread = threading.Thread(
            target=save_checkpoint, args=(self.latest_path, payload), daemon=True
        )
        self._thread.start()

    def save_best(self, payload: Any) -> None:
        save_checkpoint(self.best_path, payload)

    def load_latest(self, template: Any, shardings: Any = None) -> Any:
        """Same signature as ``load_latest_sharded``/``load_best``: the
        ``shardings`` pytree reaches the sharded reader, so callers get
        placed ``jax.Array`` leaves instead of full-host numpy. (Before
        round 9 this method simply didn't accept the argument — callers
        that passed one to the sibling loaders and then switched to
        ``load_latest`` silently lost their placement and materialized
        the whole state on host.) The legacy single-file branch restores
        host numpy regardless — one msgpack blob has no block table —
        and the caller re-places it (``reshard.load_elastic`` does the
        slice-wise placement when given shardings)."""
        self.wait()
        if self.latest_is_sharded():
            return load_sharded(self.latest_path, template, shardings)
        return load_checkpoint(self.latest_path, template)

    def load_best(self, template: Any, shardings: Any = None) -> Any:
        self.wait()
        if self.best_is_sharded():
            return load_sharded(self.best_path, template, shardings)
        if os.path.isdir(self.best_path):
            raise FileNotFoundError(
                f"{self.best_path} is a directory without a manifest — a "
                "best-save died before its commit point; no completed best "
                "checkpoint exists"
            )
        return load_checkpoint(self.best_path, template)

    def wait(self) -> None:
        """Join any background write and COMMIT any pending sharded save
        (cross-host barrier + manifest + GC). Collective when a sharded
        save is pending multi-process — call at the same point on every
        rank (trainers: epoch end, suspend, before the next save)."""
        if self._warm_thread is not None:
            self._warm_thread.join()  # never race a save into the arena
            self._warm_thread = None
        if self._thread is not None:
            with self.tracer.span("ckpt_commit_wait"):
                self._thread.join()
            self._thread = None
        if self._pending is not None:
            pending, self._pending = self._pending, None
            with self.tracer.span("ckpt_commit"):
                pending.finalize()
        self._gc_steps()  # retention only after the new manifest landed
