"""Checkpoint serialization and the latest/best artifact contract.

Replaces ``torch.save(state, 'latest.pt')`` / ``torch.load(...,
map_location='cpu')`` (D4; ``restnet_ddp.py:45,127-132,150``) with an atomic
msgpack pytree checkpoint:

- one canonical layout shared by every parallelism mode (the reference keeps
  this invariant by always saving the unwrapped ``model.module.state_dict()``,
  ``restnet_ddp.py:38``): ``{state: TrainState pytree, epoch, step,
  best_acc}`` — restores from a 1-chip run onto a pod and back;
- atomic: write to a temp file in the same directory, fsync, rename — a
  preemption mid-write can never corrupt ``latest.ckpt`` (torch.save has the
  same failure mode the reference ignores);
- rank-0-gated by the caller (ref ``restnet_ddp.py:36,145``) — parameters
  are replicated, so one host's copy is the global truth;
- optional background-thread save so the step loop doesn't stall on disk
  (the suspend path saves synchronously — it's about to yield anyway).

Artifacts mirror the reference: ``latest.ckpt`` = full training state,
written on suspend (not periodic — same policy, SURVEY.md §5);
``best.ckpt`` = written on validation improvement (``restnet_ddp.py:145-150``).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Optional

import jax
import numpy as np
from flax import serialization

LATEST = "latest.ckpt"
BEST = "best.ckpt"


def gather_global(tree: Any) -> Any:
    """Materialize every leaf as a host numpy array of the GLOBAL value.

    Locally-readable leaves (fully addressable, or fully replicated across
    hosts) are a straight ``device_get``. A leaf SHARDED across processes
    (multi-host TP/EP/FSDP) is gathered with ``process_allgather`` — a
    COLLECTIVE: every process in the job must call ``gather_global``
    together, even ranks that will discard the result. The trainer
    therefore builds checkpoint payloads on all ranks and gates only the
    disk write on rank 0 (``restnet_ddp.py:36,145`` semantics). For plain
    replicated DP (every reference mode) no collective runs and this is
    exactly the old fast path.
    """

    def leaf_to_host(x):
        if _needs_gather(x):
            from jax.experimental import multihost_utils

            return np.asarray(multihost_utils.process_allgather(x, tiled=True))
        return np.asarray(jax.device_get(x))

    return jax.tree.map(leaf_to_host, tree)


def _needs_gather(x) -> bool:
    """True for arrays whose global value is NOT locally readable: sharded
    across processes and not replicated. Fully-replicated multi-host arrays
    are readable from any single process (``device_get`` uses the local
    copy), so plain multi-host DP never needs the collective."""
    return (
        isinstance(x, jax.Array)
        and not x.is_fully_addressable
        and not x.is_fully_replicated
    )


def _to_host(tree: Any) -> Any:
    """Host-side snapshot for serialization. NOT a collective: leaves must
    be locally readable (pass trees through ``gather_global`` first in
    multi-host sharded runs — calling this from a rank-gated branch with
    cross-process-sharded arrays would otherwise hang the job in a
    one-sided collective)."""

    def leaf_to_host(x):
        if _needs_gather(x):
            raise ValueError(
                "checkpoint payload contains an array sharded across "
                "processes; gather it on ALL processes with "
                "utils.checkpoint.gather_global(tree) before the rank-0 "
                "save call (process_allgather is a collective)."
            )
        return np.asarray(jax.device_get(x))

    return jax.tree.map(leaf_to_host, tree)


def save_checkpoint(path: str | os.PathLike, payload: Any) -> None:
    """Atomically serialize a pytree payload to ``path``."""
    path = os.fspath(path)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    state_dict = serialization.to_state_dict(_to_host(payload))
    blob = serialization.msgpack_serialize(state_dict)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def load_checkpoint(path: str | os.PathLike, template: Any) -> Any:
    """Restore a payload saved by ``save_checkpoint`` into the structure of
    ``template`` (≙ ``load_state_dict``, ``restnet_ddp.py:128-132``).
    Arrays come back as numpy on host — the trainer re-places them onto the
    mesh with the right sharding (≙ ``map_location='cpu'`` then ``.cuda()``).
    """
    with open(os.fspath(path), "rb") as f:
        state_dict = serialization.msgpack_restore(f.read())
    return serialization.from_state_dict(template, state_dict)


class Checkpointer:
    """latest/best artifact manager for a save directory.

    ``save_latest`` optionally runs in a background thread (``wait()`` to
    join — the suspend path does); ``save_best`` is called on metric
    improvement only, like ``restnet_ddp.py:145-150``.
    """

    def __init__(self, save_dir: str | os.PathLike):
        self.save_dir = os.fspath(save_dir)
        self._thread: Optional[threading.Thread] = None

    def _path(self, name: str) -> str:
        return os.path.join(self.save_dir, name)

    @property
    def latest_path(self) -> str:
        return self._path(LATEST)

    @property
    def best_path(self) -> str:
        return self._path(BEST)

    def has_latest(self) -> bool:
        return os.path.exists(self.latest_path)

    def save_latest(self, payload: Any, block: bool = True) -> None:
        if block:
            save_checkpoint(self.latest_path, payload)
            return
        payload = _to_host(payload)  # snapshot before handing to the thread
        self.wait()
        self._thread = threading.Thread(
            target=save_checkpoint, args=(self.latest_path, payload), daemon=True
        )
        self._thread.start()

    def save_best(self, payload: Any) -> None:
        save_checkpoint(self.best_path, payload)

    def load_latest(self, template: Any) -> Any:
        return load_checkpoint(self.latest_path, template)

    def load_best(self, template: Any) -> Any:
        return load_checkpoint(self.best_path, template)

    def wait(self) -> None:
        if self._thread is not None:
            self._thread.join()
            self._thread = None
