"""Rank-aware logging.

The reference gates all user-visible output on rank 0 and flushes every print
(``restnet_ddp.py:66-70,145-146``, ``resnet_single_gpu.py:23-24``). Here that
policy lives in one place instead of being re-implemented per script.
"""

from __future__ import annotations

import logging
import sys


def get_logger(name: str = "pytorch_distributed_tpu") -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stdout)
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(name)s %(levelname)s %(message)s")
        )
        logger.addHandler(handler)
        logger.setLevel(logging.INFO)
    return logger


def _process_index() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:  # jax.distributed not initialised / no backend yet
        return 0


def is_rank0() -> bool:
    return _process_index() == 0


def rank0_print(*args, **kwargs) -> None:
    """``print(..., flush=True)`` on process 0 only (ref ``restnet_ddp.py:70``)."""
    if is_rank0():
        kwargs.setdefault("flush", True)
        print(*args, **kwargs)
