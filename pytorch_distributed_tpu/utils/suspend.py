"""Cooperative preemption: the suspend/checkpoint/yield protocol.

TPU-native replacement for the hfai cluster client (D3:
``hfai.client.receive_suspend_command()`` polled every step,
``restnet_ddp.py:36``; ``hfai.client.go_suspend()`` to yield,
``restnet_ddp.py:47``). The contract is identical — scheduler-initiated,
step-granular, checkpoint-then-yield, no elasticity (SURVEY.md §5) — but the
signal sources are the ones TPU/GKE jobs actually get:

- SIGTERM / SIGUSR1 (GKE pod eviction, `gcloud ... tpu-vm delete`, Borg
  preemption all deliver a signal with a grace window);
- a flag file (``SUSPEND_FLAG_FILE`` env or constructor arg) for cluster
  schedulers and tests that can only touch the filesystem;
- a programmatic ``request_suspend()`` for in-process injection (tests).

Polling is what the reference does per step; here a ``stat()`` every
``poll_interval`` seconds (signals need no polling at all) keeps the hot
loop free of syscalls.
"""

from __future__ import annotations

import logging
import os
import signal
import sys
import threading
import time
from typing import Optional

logger = logging.getLogger("pytorch_distributed_tpu")


class SuspendWatcher:
    """Non-blocking preemption watcher (≙ ``hfai.client``).

    ``receive_suspend_command()`` is safe to call every step; ``go_suspend``
    logs and exits with the given code after the caller has checkpointed
    (``restnet_ddp.py:45-47`` sleeps 5 s then yields; the sleep existed to
    let async work drain — here the checkpointer's ``wait()`` does that
    deterministically).
    """

    def __init__(
        self,
        flag_file: Optional[str] = None,
        signals=(signal.SIGTERM, signal.SIGUSR1),
        poll_interval: float = 1.0,
        install_handlers: bool = True,
    ):
        self.flag_file = flag_file or os.environ.get("SUSPEND_FLAG_FILE")
        self.poll_interval = poll_interval
        self._event = threading.Event()
        self._last_poll = 0.0
        # Chain, don't clobber: remember whatever handler was installed
        # before us and call it after latching — a nested trainer, pytest,
        # or a framework's own SIGTERM hook keeps working (and uninstall()
        # can restore it).
        self._prev_handlers: dict = {}
        if install_handlers:
            for sig in signals:
                try:
                    prev = signal.signal(sig, self._on_signal)
                except (ValueError, OSError):  # non-main thread / restricted env
                    logger.debug("could not install handler for %s", sig)
                else:
                    self._prev_handlers[sig] = prev

    def _on_signal(self, signum, frame) -> None:
        logger.warning("received signal %d: suspend requested", signum)
        self._event.set()
        prev = self._prev_handlers.get(signum)
        if callable(prev):  # SIG_DFL/SIG_IGN/None are ints or None
            prev(signum, frame)

    def uninstall(self) -> None:
        """Restore the handlers this watcher displaced (nested trainers,
        tests). Only unwinds signals still pointing at us — a handler
        someone installed on top stays."""
        for sig, prev in list(self._prev_handlers.items()):
            try:
                if signal.getsignal(sig) == self._on_signal:
                    signal.signal(sig, prev)
            except (ValueError, OSError):
                logger.debug("could not restore handler for %s", sig)
            del self._prev_handlers[sig]

    def request_suspend(self) -> None:
        """Programmatic injection point (tests, embedding schedulers)."""
        self._event.set()

    def receive_suspend_command(self) -> bool:
        """True once a suspend has been requested. Throttled flag-file poll;
        signal delivery is instant. Sticky: once set, stays set."""
        if self._event.is_set():
            return True
        if self.flag_file:
            now = time.monotonic()
            if now - self._last_poll >= self.poll_interval:
                self._last_poll = now
                if os.path.exists(self.flag_file):
                    logger.warning("suspend flag file %s present", self.flag_file)
                    self._event.set()
        return self._event.is_set()

    def go_suspend(self, exit_code: int = 0) -> None:
        """Yield back to the scheduler after checkpointing (≙
        ``hfai.client.go_suspend()``, ``restnet_ddp.py:47``). Exits the
        process; the scheduler relaunches later and the trainer resumes from
        ``latest.ckpt`` (SURVEY.md §3.5)."""
        logger.warning("suspending: yielding to scheduler (exit %d)", exit_code)
        sys.exit(exit_code)


class NullSuspendWatcher(SuspendWatcher):
    """Watcher that never fires — for benchmarks and environments without a
    scheduler. Same API, zero per-step cost."""

    def __init__(self):
        super().__init__(flag_file=None, install_handlers=False)

    def receive_suspend_command(self) -> bool:
        return False
