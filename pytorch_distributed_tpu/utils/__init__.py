from pytorch_distributed_tpu.utils.env import set_env
from pytorch_distributed_tpu.utils.logging import rank0_print, get_logger

__all__ = ["set_env", "rank0_print", "get_logger"]
