"""Versioned runtime-environment manifest.

TPU-native replacement for the reference's cluster environment pinning
(``import hf_env; hf_env.set_env('202111')`` — the first two lines of every
reference script). Instead of swapping a container image, we verify the
installed JAX/flax/optax stack against a named manifest and configure
TPU-friendly process-level defaults (compilation cache, preallocation).
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass, field

logger = logging.getLogger("pytorch_distributed_tpu")


@dataclass(frozen=True)
class EnvManifest:
    """Minimum-version pins for a named environment."""

    name: str
    min_versions: dict = field(default_factory=dict)
    env_defaults: dict = field(default_factory=dict)


# Manifests are named by YYYYMM like the reference's '202111'.
MANIFESTS = {
    "202607": EnvManifest(
        name="202607",
        min_versions={"jax": (0, 5), "flax": (0, 10), "optax": (0, 2)},
        env_defaults={
            # Persistent XLA compilation cache: first compile of a big step
            # function is ~20-40s on TPU; cache makes relaunches (and the
            # suspend/resume cycle) cheap.
            "JAX_COMPILATION_CACHE_DIR": os.path.expanduser(
                "~/.cache/pytorch_distributed_tpu/xla"
            ),
        },
    ),
}

_active_env: str | None = None


def _version_tuple(version: str) -> tuple:
    parts = []
    for piece in version.split(".")[:3]:
        digits = "".join(ch for ch in piece if ch.isdigit())
        parts.append(int(digits) if digits else 0)
    return tuple(parts)


def set_env(name: str = "202607", strict: bool = False) -> EnvManifest:
    """Pin and verify the runtime environment.

    Mirrors ``hf_env.set_env(version)`` (every reference script, lines 1-2):
    call once at program start, before heavy imports do real work.

    Args:
      name: manifest name (default the current one).
      strict: raise on a version pin violation instead of warning.
    """
    global _active_env
    manifest = MANIFESTS.get(name)
    if manifest is None:
        raise ValueError(
            f"unknown environment manifest {name!r}; known: {sorted(MANIFESTS)}"
        )

    for key, value in manifest.env_defaults.items():
        os.environ.setdefault(key, value)
    cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if cache_dir:
        os.makedirs(cache_dir, exist_ok=True)

    import importlib

    for mod_name, min_version in manifest.min_versions.items():
        try:
            mod = importlib.import_module(mod_name)
        except ImportError:
            msg = f"environment {name!r} requires {mod_name} but it is not installed"
            if strict:
                raise RuntimeError(msg)
            logger.warning(msg)
            continue
        have = _version_tuple(getattr(mod, "__version__", "0"))
        if have < tuple(min_version):
            msg = (
                f"environment {name!r} pins {mod_name}>="
                f"{'.'.join(map(str, min_version))}, found {mod.__version__}"
            )
            if strict:
                raise RuntimeError(msg)
            logger.warning(msg)

    _active_env = name
    return manifest


def active_env() -> str | None:
    return _active_env


def resolve_compile_cache_dir(cli_value: str | None = None) -> str | None:
    """The compile-cache directory a run should use: an explicit value
    (``--compile-cache-dir`` / ``TrainerConfig.compile_cache_dir``) wins,
    else the ``PDT_COMPILE_CACHE_DIR`` environment fallback, else None
    (persistent caching off — unless ``set_env`` already established the
    process-wide ``JAX_COMPILATION_CACHE_DIR`` default).

    This is the one resolution rule every entry point shares (recipes,
    trainers, ``scripts/warmup.py``, ``scripts/bench_coldstart.py``), so
    a cluster can point every job at a shared cache with one env var.
    """
    if cli_value:
        return cli_value
    return os.environ.get("PDT_COMPILE_CACHE_DIR") or None
