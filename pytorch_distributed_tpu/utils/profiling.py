"""Profiling and observability.

The reference's entire observability story is wall-clock epoch timing via
``time.time()`` prints (``restnet_ddp.py:136-146``; SURVEY.md §5 "tracing:
ABSENT" — GPU util/memory in result.png were measured externally by the
cluster). This module is the in-framework replacement:

- ``trace``: ``jax.profiler`` capture behind a flag/env — one context
  manager wraps any region (an epoch, N steps) and writes a TensorBoard-
  loadable trace with XLA op/fusion timelines (the TPU answer to nvprof);
- ``StepTimer``: wall-clock step/epoch statistics with warmup exclusion —
  honest throughput numbers (first steps include compilation);
- ``device_duty_cycle``: the TPU analog of nvidia-smi "GPU util" — the
  fraction of wall time the device spent executing, derived by comparing
  back-to-back synced step time against dispatch-gap-free time;
- ``MetricsLogger``: JSONL metrics stream (step, loss, acc, lr, img/s) so
  runs are machine-comparable, not print-scraped.
"""

from __future__ import annotations

import contextlib
import json
import os
import time
from typing import Iterator, Optional

import numpy as np


@contextlib.contextmanager
def trace(log_dir: Optional[str] = None, enabled: Optional[bool] = None) -> Iterator[None]:
    """``jax.profiler`` trace region.

    Enabled when ``enabled`` is True or env ``PDT_TRACE_DIR`` is set; traces
    land in ``log_dir`` (default the env value). View with TensorBoard's
    profile plugin or xprof.
    """
    env_dir = os.environ.get("PDT_TRACE_DIR")
    if enabled is None:
        enabled = env_dir is not None or log_dir is not None
    if not enabled:
        yield
        return
    import jax

    target = log_dir or env_dir or "/tmp/pdt_trace"
    os.makedirs(target, exist_ok=True)
    with jax.profiler.trace(target):
        yield


class StepTimer:
    """Wall-clock step statistics with warmup exclusion.

    ``tick()`` per step; ``summary(items_per_step)`` → mean/p50/p95 step ms
    and items/s over the post-warmup window.
    """

    def __init__(self, warmup_steps: int = 2):
        self.warmup_steps = warmup_steps
        self._times: list[float] = []
        self._last: Optional[float] = None

    def tick(self) -> None:
        now = time.perf_counter()
        if self._last is not None:
            self._times.append(now - self._last)
        self._last = now

    def reset(self) -> None:
        self._times.clear()
        self._last = None

    @property
    def steps(self) -> int:
        return max(len(self._times) - self.warmup_steps, 0)

    def summary(self, items_per_step: Optional[int] = None) -> dict:
        times = np.asarray(self._times[self.warmup_steps:])
        if times.size == 0:
            return {"steps": 0}
        out = {
            "steps": int(times.size),
            "mean_ms": float(times.mean() * 1e3),
            "p50_ms": float(np.percentile(times, 50) * 1e3),
            "p95_ms": float(np.percentile(times, 95) * 1e3),
        }
        if items_per_step:
            out["items_per_s"] = float(items_per_step / times.mean())
        return out


def device_duty_cycle(step_fn, carry, *args, iters: int = 10) -> float:
    """Estimate the device-busy fraction for a compiled step (the TPU analog
    of the reference's "avg GPU util" column, result.png).

    ``step_fn(carry, *args)`` must return a tuple whose first element is the
    next carry (the TrainState convention) — chaining keeps donated buffers
    valid. Runs ``iters`` dependent executions twice: once timing only the
    async-dispatched chain (one sync at the end), once syncing every step
    (adds one host round-trip per step). busy ≈ chain_time / stepped_time;
    1.0 means the host never starves the device.
    """
    import jax

    def sync(x):
        leaf = jax.tree.leaves(x)[0]
        np.asarray(jax.device_get(leaf))  # a value fetch cannot lie

    out = step_fn(carry, *args)
    carry = out[0]
    sync(out[1:] if len(out) > 1 else out)

    t0 = time.perf_counter()
    for _ in range(iters):
        out = step_fn(carry, *args)
        carry = out[0]
    sync(carry)
    chain = time.perf_counter() - t0

    t0 = time.perf_counter()
    for _ in range(iters):
        out = step_fn(carry, *args)
        carry = out[0]
        sync(out[1] if len(out) > 1 else carry)
    stepped = time.perf_counter() - t0
    return min(chain / max(stepped, 1e-9), 1.0)


class MetricsLogger:
    """Append-only JSONL metrics (rank-0-gated by the caller, like every
    reference print)."""

    def __init__(self, path: Optional[str]):
        self.path = path
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._f = open(path, "a", buffering=1)
        else:
            self._f = None

    def log(self, **record) -> None:
        if self._f is None:
            return
        record.setdefault("ts", time.time())
        self._f.write(json.dumps(record) + "\n")

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None
