"""Profiling and observability.

The reference's entire observability story is wall-clock epoch timing via
``time.time()`` prints (``restnet_ddp.py:136-146``; SURVEY.md §5 "tracing:
ABSENT" — GPU util/memory in result.png were measured externally by the
cluster). This module is the in-framework replacement:

- ``trace``: ``jax.profiler`` capture behind a flag/env — one context
  manager wraps any region (an epoch, N steps) and writes a TensorBoard-
  loadable trace with XLA op/fusion timelines (the TPU answer to nvprof);
- ``StepTimer``: wall-clock step/epoch statistics with warmup exclusion —
  honest throughput numbers (first steps include compilation);
- ``device_duty_cycle``: the TPU analog of nvidia-smi "GPU util" — the
  fraction of wall time the device spent executing, derived by comparing
  back-to-back synced step time against dispatch-gap-free time;
- ``MetricsLogger``: JSONL metrics stream (step, loss, acc, lr, img/s) so
  runs are machine-comparable, not print-scraped.
"""

from __future__ import annotations

import atexit
import contextlib
import json
import os
import threading
import time
from typing import Iterator, Optional

import numpy as np


@contextlib.contextmanager
def trace(log_dir: Optional[str] = None, enabled: Optional[bool] = None) -> Iterator[None]:
    """``jax.profiler`` trace region.

    Enabled when ``enabled`` is True or env ``PDT_TRACE_DIR`` is set; traces
    land in ``log_dir`` (default the env value). View with TensorBoard's
    profile plugin or xprof.
    """
    env_dir = os.environ.get("PDT_TRACE_DIR")
    if enabled is None:
        enabled = env_dir is not None or log_dir is not None
    if not enabled:
        yield
        return
    import jax

    target = log_dir or env_dir or "/tmp/pdt_trace"
    os.makedirs(target, exist_ok=True)
    with jax.profiler.trace(target):
        yield


class StepTimer:
    """Wall-clock step statistics with warmup exclusion.

    ``tick()`` per step; ``summary(items_per_step)`` → mean/p50/p95 step ms
    and items/s over the post-warmup window.
    """

    def __init__(self, warmup_steps: int = 2):
        self.warmup_steps = warmup_steps
        self._times: list[float] = []
        self._last: Optional[float] = None

    def tick(self) -> None:
        now = time.perf_counter()
        if self._last is not None:
            self._times.append(now - self._last)
        self._last = now

    def reset(self) -> None:
        self._times.clear()
        self._last = None

    @property
    def steps(self) -> int:
        return max(len(self._times) - self.warmup_steps, 0)

    def summary(self, items_per_step: Optional[int] = None) -> dict:
        times = np.asarray(self._times[self.warmup_steps:])
        if times.size == 0:
            return {"steps": 0}
        out = {
            "steps": int(times.size),
            "mean_ms": float(times.mean() * 1e3),
            "p50_ms": float(np.percentile(times, 50) * 1e3),
            "p95_ms": float(np.percentile(times, 95) * 1e3),
        }
        if items_per_step:
            out["items_per_s"] = float(items_per_step / times.mean())
        return out


def _scalar_sync(tree) -> None:
    """Force completion by fetching the smallest DEVICE leaf.

    Through tunneled TPU runtimes, ``block_until_ready`` has been observed to
    return before device work drains, and device→host bandwidth can be as low
    as ~24 MB/s — so sync on a value fetch, but fetch the cheapest one.
    Non-array leaves (plain Python numbers) carry no device dependency and
    must not be chosen — fetching one would be a no-op "sync".
    """
    import jax

    device_leaves = [
        l for l in jax.tree.leaves(tree) if isinstance(l, jax.Array)
    ]
    if not device_leaves:
        return
    leaf = min(device_leaves, key=lambda l: l.size)
    np.asarray(jax.device_get(leaf))


def _file_busy_span_us(path: str):
    """(busy, span) microseconds for ONE profiler trace file, or None if
    it carries no device-track events."""
    import gzip

    with gzip.open(path, "rt") as f:
        data = json.load(f)
    events = data.get("traceEvents", [])
    pids = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pids[e["pid"]] = e.get("args", {}).get("name", "")
    dev_pids = {p for p, n in pids.items() if "/device:" in n and "CPU" not in n}
    if not dev_pids:
        return None
    intervals = sorted(
        (e["ts"], e["ts"] + e.get("dur", 0))
        for e in events
        if e.get("ph") == "X" and e.get("pid") in dev_pids
    )
    if not intervals:
        return None
    busy = 0.0
    cur_start, cur_end = intervals[0]
    for start, end in intervals[1:]:
        if start > cur_end:
            busy += cur_end - cur_start
            cur_start, cur_end = start, end
        else:
            cur_end = max(cur_end, end)
    busy += cur_end - cur_start
    span = max(end for _, end in intervals) - intervals[0][0]
    return busy, span


def trace_device_busy_s(trace_dir: str):
    """Device-busy and device-active-span seconds from the
    ``jax.profiler`` traces under ``trace_dir``.

    Parses the Chrome-trace JSON the profiler writes, takes every
    complete ("X") event on a device-named process track, and returns
    ``(busy, span)``: the length of the union of their time intervals
    (events nest, so summing durations would double-count) and the
    first-event-start → last-event-end span. A directory holding
    SEVERAL profiler runs (``plugins/profile/<run>/``) aggregates across
    all of them — per-run busy and span summed — instead of the old
    behavior of silently reading only the lexicographically newest run.
    Returns None if no trace/device events are found anywhere.
    """
    import glob

    paths = sorted(
        glob.glob(os.path.join(trace_dir, "plugins/profile/*/*.trace.json.gz"))
    )
    busy = span = 0.0
    found = False
    for path in paths:
        bs = _file_busy_span_us(path)
        if bs is None:
            continue
        found = True
        busy += bs[0]
        span += bs[1]
    if not found:
        return None
    # trace timestamps are microseconds
    return busy / 1e6, span / 1e6


def device_duty_cycle(step_fn, carry, *args, iters: int = 10) -> float:
    """Measure the device-busy fraction for a compiled step (the TPU analog
    of the reference's "avg GPU util" column, result.png).

    ``step_fn(carry, *args)`` must return a tuple whose first element is the
    next carry (the TrainState convention) — chaining keeps donated buffers
    valid. Runs ``iters`` dependent executions under a ``jax.profiler``
    trace and returns device_busy_time over the device-active span (first
    event start → last event end). This replaces the round-1 per-step-sync
    estimate, which on a tunneled runtime measured host round-trip latency
    (~95 ms each), not device idleness; wall clock around the trace context
    is also unusable because stopping the trace downloads the event buffer
    through the (slow) tunnel.

    Returns NaN when no device trace is available (e.g. CPU backend).
    """
    import tempfile

    import jax

    out = step_fn(carry, *args)
    carry = out[0]
    _scalar_sync(out[1] if len(out) > 1 else carry)

    with tempfile.TemporaryDirectory() as td:
        with jax.profiler.trace(td):
            for _ in range(iters):
                out = step_fn(carry, *args)
                carry = out[0]
            _scalar_sync(out[1] if len(out) > 1 else carry)
        busy_span = trace_device_busy_s(td)
    if busy_span is None:
        return float("nan")
    busy, span = busy_span
    return min(busy / max(span, 1e-9), 1.0)


class MetricsLogger:
    """Append-only JSONL metrics stream — the one schema every telemetry
    producer (trainers, serving scheduler, goodput ledger) writes.

    Hardened per ISSUE 4: rank-0 gating lives INSIDE the class (callers
    used to have to remember it; ``rank0_only=False`` opts out for
    per-process streams), the file handle is registered with ``atexit``
    so a crash mid-run flushes the tail instead of losing it, reopening
    a path APPENDS (mode "a" — a resumed run extends its history), and
    the logger is a context manager. Line-buffered writes: every record
    is durable as soon as ``log`` returns.

    Rotation (ISSUE 8): ``max_bytes`` caps the stream for long runs —
    once the active file passes the cap it rotates to ``<path>.1``
    (replacing the previous generation) and a fresh file continues, so
    total disk stays bounded by ~2×``max_bytes`` while the newest
    history is always intact. Rotation is record-aligned (checked after
    a complete line), so neither generation ever holds a torn record.

    Thread-safe (round 16): the async host runtime's worker threads
    emit per-request records concurrently with the main loop, so the
    serialize+write+rotate sequence holds one lock — records from any
    thread land as whole lines, and rotation can never interleave with
    a write.
    """

    def __init__(self, path: Optional[str], rank0_only: bool = True,
                 max_bytes: Optional[int] = None):
        self.path = path
        self.max_bytes = max_bytes
        self.rotations = 0
        self._f = None
        self._lock = threading.Lock()
        if path and (not rank0_only or self._is_rank0()):
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            # a SIGKILL can leave a torn final line with no newline;
            # seal it before appending so the NEXT record stays
            # parseable (readers skip the torn fragment as one bad
            # line instead of losing two records merged into it)
            torn = False
            try:
                with open(path, "rb") as existing:
                    existing.seek(-1, os.SEEK_END)
                    torn = existing.read(1) != b"\n"
            except OSError:
                pass  # missing or empty file: nothing to seal
            self._f = open(path, "a", buffering=1)
            if torn:
                self._f.write("\n")
            atexit.register(self.close)

    @staticmethod
    def _is_rank0() -> bool:
        try:
            import jax

            return jax.process_index() == 0
        except Exception:  # no jax / uninitialized backend: single process
            return True

    def log(self, **record) -> None:
        if self._f is None:
            return
        record.setdefault("ts", time.time())
        line = json.dumps(record) + "\n"
        with self._lock:
            if self._f is None:  # closed by another thread
                return
            self._f.write(line)
            if (self.max_bytes is not None
                    and self._f.tell() >= self.max_bytes):
                self._rotate()

    def _rotate(self) -> None:
        """Roll the full active file to ``<path>.1`` (one kept
        generation) and continue on a fresh one."""
        self._f.close()
        try:
            os.replace(self.path, f"{self.path}.1")
        except OSError:
            pass  # a racing cleanup removed it: just reopen fresh
        self._f = open(self.path, "a", buffering=1)
        self.rotations += 1

    def close(self) -> None:
        if self._f is not None:
            try:
                atexit.unregister(self.close)
            except Exception:
                pass
            with self._lock:
                if self._f is not None:
                    self._f.close()
                    self._f = None

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
