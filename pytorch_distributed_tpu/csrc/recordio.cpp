// Native packed-record reader core.
//
// TPU-native equivalent of ffrecord's C++ reader (reference dependency D2:
// `hfai.datasets.ImageNet` reads packed .ffr files through a C++ Linux-AIO
// core; call sites restnet_ddp.py:107-119). This is a fresh design for the
// TPRC container (see data/packed_record.py for the layout):
//
//   [0)   magic  "TPRC"            4 bytes
//   [4)   version u32              = 1
//   [8)   n       u64              record count
//   [16)  flags   u64              bit0: per-record crc32 table present
//   [24)  offsets u64 * (n+1)      payload-relative record boundaries
//   [..)  crcs    u32 * n          (iff flags & 1)
//   [..)  payload                  concatenated record bytes
//
// Reads use pread(2): stateless, thread-safe, no shared file offset — a pool
// of host threads (the Python loader's worker threads) can fetch a batch of
// records concurrently against one shared handle, which is what the
// ffrecord AIO design achieved. Optional crc32 verification per record
// (zlib-polynomial, slice-by-one table; no external deps).
//
// Exposed as a C ABI for ctypes (no pybind11 in the image).

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMagic = 0x43525054;  // "TPRC" little-endian
constexpr uint64_t kFlagCrc = 1;

struct Reader {
  int fd = -1;
  uint64_t n = 0;
  uint64_t flags = 0;
  uint64_t payload_start = 0;
  std::vector<uint64_t> offsets;  // n+1 entries, payload-relative
  std::vector<uint32_t> crcs;     // n entries iff (flags & kFlagCrc)
};

uint32_t crc32_table[256];
bool crc32_table_init_done = false;

void crc32_init() {
  if (crc32_table_init_done) return;
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc32_table[i] = c;
  }
  crc32_table_init_done = true;
}

uint32_t crc32(const uint8_t* data, size_t len) {
  crc32_init();
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < len; ++i)
    c = crc32_table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

bool read_exact(int fd, void* buf, size_t len, uint64_t offset) {
  uint8_t* p = static_cast<uint8_t*>(buf);
  while (len > 0) {
    ssize_t r = pread(fd, p, len, static_cast<off_t>(offset));
    if (r <= 0) return false;
    p += r;
    offset += static_cast<uint64_t>(r);
    len -= static_cast<size_t>(r);
  }
  return true;
}

}  // namespace

extern "C" {

// Returns an opaque handle, or nullptr on failure.
void* tpr_open(const char* path) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  auto* r = new Reader();
  r->fd = fd;
  struct stat st;
  uint8_t header[24];
  if (fstat(fd, &st) != 0) goto fail;
  if (!read_exact(fd, header, sizeof(header), 0)) goto fail;
  {
    uint32_t magic, version;
    memcpy(&magic, header, 4);
    memcpy(&version, header + 4, 4);
    memcpy(&r->n, header + 8, 8);
    memcpy(&r->flags, header + 16, 8);
    if (magic != kMagic || version != 1) goto fail;
    // A corrupt n must not reach resize(): the offset table alone needs
    // 8*(n+1) bytes, so n is bounded by the file size.
    uint64_t file_size = static_cast<uint64_t>(st.st_size);
    if (file_size < 24 || r->n > (file_size - 24) / 8) goto fail;
  }
  try {
    r->offsets.resize(r->n + 1);
    if (!read_exact(fd, r->offsets.data(), 8 * (r->n + 1), 24)) goto fail;
    r->payload_start = 24 + 8 * (r->n + 1);
    if (r->flags & kFlagCrc) {
      r->crcs.resize(r->n);
      if (!read_exact(fd, r->crcs.data(), 4 * r->n, r->payload_start)) goto fail;
      r->payload_start += 4 * r->n;
    }
  } catch (...) {
    goto fail;
  }
  return r;
fail:
  close(fd);
  delete r;
  return nullptr;
}

void tpr_close(void* handle) {
  auto* r = static_cast<Reader*>(handle);
  if (r == nullptr) return;
  close(r->fd);
  delete r;
}

int64_t tpr_count(void* handle) {
  return static_cast<int64_t>(static_cast<Reader*>(handle)->n);
}

// Byte size of record i, or -1 if out of range.
int64_t tpr_size(void* handle, uint64_t i) {
  auto* r = static_cast<Reader*>(handle);
  if (i >= r->n) return -1;
  return static_cast<int64_t>(r->offsets[i + 1] - r->offsets[i]);
}

// Read record i into buf (caller sized it via tpr_size). Returns bytes read,
// -1 on I/O error, -2 on crc mismatch.
int64_t tpr_read(void* handle, uint64_t i, uint8_t* buf, int verify_crc) {
  auto* r = static_cast<Reader*>(handle);
  if (i >= r->n) return -1;
  uint64_t len = r->offsets[i + 1] - r->offsets[i];
  if (!read_exact(r->fd, buf, len, r->payload_start + r->offsets[i])) return -1;
  if (verify_crc && (r->flags & kFlagCrc)) {
    if (crc32(buf, len) != r->crcs[i]) return -2;
  }
  return static_cast<int64_t>(len);
}

// Gather a batch: indices[k] → buf + buf_offsets[k]. Returns 0, or the
// negative status of the first failing record.
int64_t tpr_read_batch(void* handle, const uint64_t* indices, int64_t count,
                       uint8_t* buf, const uint64_t* buf_offsets,
                       int verify_crc) {
  for (int64_t k = 0; k < count; ++k) {
    int64_t status = tpr_read(handle, indices[k], buf + buf_offsets[k], verify_crc);
    if (status < 0) return status;
  }
  return 0;
}

// Batched crop/flip/collate over RAW image records (data/raw.py layout:
// label u32 | h u16 | w u16 | h*w*3 uint8 RGB). The whole batch — read,
// header parse, crop window copy, optional horizontal flip, label extract —
// happens here in one call with no per-sample Python work and no GIL
// (ctypes releases it): the native half of the decode-free input path.
//
// out_images is [count, crop, crop, 3] uint8, out_labels [count] int32;
// tops/lefts give each sample's crop origin, flips[k] != 0 mirrors
// horizontally. expect_h/expect_w pin the stored image size the CALLER
// drew the crop coordinates for: a record whose header disagrees fails
// with -3 (the Python side then falls back to the per-sample path, which
// reads true per-record sizes) instead of silently cropping with a wrong
// distribution. Work is split over n_threads (pread is stateless, so
// threads share the handle safely). Returns 0; -1 on I/O/bounds error;
// -3 on a size mismatch.
int64_t tpr_crop_batch(void* handle, const uint64_t* indices, int64_t count,
                       const int32_t* tops, const int32_t* lefts,
                       const uint8_t* flips, int32_t crop,
                       int32_t expect_h, int32_t expect_w,
                       uint8_t* out_images, int32_t* out_labels,
                       int n_threads) {
  auto* r = static_cast<Reader*>(handle);
  const uint64_t out_stride =
      static_cast<uint64_t>(crop) * static_cast<uint64_t>(crop) * 3;
  if (n_threads < 1) n_threads = 1;
  if (n_threads > count) n_threads = static_cast<int>(count);

  std::vector<int64_t> status(static_cast<size_t>(n_threads), 0);
  auto worker = [&](int t) {
    std::vector<uint8_t> scratch;
    for (int64_t k = t; k < count; k += n_threads) {
      uint64_t i = indices[k];
      if (i >= r->n) { status[t] = -1; return; }
      uint64_t len = r->offsets[i + 1] - r->offsets[i];
      if (len < 8) { status[t] = -1; return; }
      scratch.resize(len);
      if (!read_exact(r->fd, scratch.data(), len,
                      r->payload_start + r->offsets[i])) {
        status[t] = -1;
        return;
      }
      int32_t label;
      uint16_t h, w;
      memcpy(&label, scratch.data(), 4);
      memcpy(&h, scratch.data() + 4, 2);
      memcpy(&w, scratch.data() + 6, 2);
      if (h != expect_h || w != expect_w) { status[t] = -3; return; }
      const int32_t top = tops[k], left = lefts[k];
      if (top < 0 || left < 0 || top + crop > h || left + crop > w ||
          len < 8 + static_cast<uint64_t>(h) * w * 3) {
        status[t] = -1;
        return;
      }
      const uint8_t* img = scratch.data() + 8;
      uint8_t* dst = out_images + static_cast<uint64_t>(k) * out_stride;
      const uint64_t row_bytes = static_cast<uint64_t>(crop) * 3;
      for (int32_t y = 0; y < crop; ++y) {
        const uint8_t* src =
            img + (static_cast<uint64_t>(top + y) * w + left) * 3;
        uint8_t* drow = dst + static_cast<uint64_t>(y) * row_bytes;
        if (flips[k]) {
          for (int32_t x = 0; x < crop; ++x) {
            const uint8_t* px = src + static_cast<uint64_t>(crop - 1 - x) * 3;
            drow[3 * x + 0] = px[0];
            drow[3 * x + 1] = px[1];
            drow[3 * x + 2] = px[2];
          }
        } else {
          memcpy(drow, src, row_bytes);
        }
      }
      out_labels[k] = label;
    }
  };

  if (n_threads == 1) {
    worker(0);
    return status[0];
  }
  std::vector<std::thread> threads;
  threads.reserve(static_cast<size_t>(n_threads));
  for (int t = 0; t < n_threads; ++t) threads.emplace_back(worker, t);
  for (auto& th : threads) th.join();
  for (int t = 0; t < n_threads; ++t)
    if (status[t] < 0) return status[t];
  return 0;
}

}  // extern "C"
