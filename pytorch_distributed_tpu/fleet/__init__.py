"""Serving fleet layer: replica router, SLO-aware admission,
disaggregated prefill/decode (round 10 tentpole — ROADMAP item 3).

One ``serving.Scheduler`` + ``PagedEngine`` is one replica; millions of
users need N. This package is the layer above the single engine:

- ``router``    — ``FleetRouter``: N single-process replicas (each on
  its own ``jax.devices()`` slice), session-affinity routing with
  least-loaded fallback, one host loop driving every replica's ticks,
  and the prefill→decode handoff pump;
- ``admission`` — ``SLOGate``: admit / spill / queue / shed against the
  live TTFT/queue-wait percentiles each scheduler already computes
  (PR 4), plus ``recommend_replicas``, the goodput-fed autoscaler hook;
- ``traffic``   — seeded bursty heavy-tail traces (JSONL), the
  step-domain ``replay_trace`` driver, and ``prompt_for``'s
  deterministic token streams.

The CPU backend cannot run multi-process collectives (known jaxlib gap,
xfail'd since PR 1), so the fleet proof is single-process multi-mesh
plus trace-driven router simulation — exactly what ROADMAP item 3
prescribes. ANALYSIS.md "Serving fleet" documents the routing policy,
the SLO gate semantics, the KV handoff cost model, and the simulation's
caveats.
"""

from pytorch_distributed_tpu.fleet.admission import (
    ADMIT,
    PREEMPT,
    SHED,
    SPILL,
    Decision,
    SLOConfig,
    SLOGate,
    recommend_replicas,
    trace_decision,
)
from pytorch_distributed_tpu.fleet.router import FleetRouter
from pytorch_distributed_tpu.fleet.traffic import (
    TraceRequest,
    clamp_trace,
    generate_trace,
    iter_trace,
    load_trace,
    prompt_for,
    shared_prefix_prompt_for,
    replay_stream,
    replay_trace,
    save_trace,
)

__all__ = [
    "ADMIT",
    "PREEMPT",
    "SHED",
    "SPILL",
    "Decision",
    "SLOConfig",
    "SLOGate",
    "recommend_replicas",
    "trace_decision",
    "FleetRouter",
    "TraceRequest",
    "clamp_trace",
    "generate_trace",
    "iter_trace",
    "load_trace",
    "prompt_for",
    "shared_prefix_prompt_for",
    "replay_stream",
    "replay_trace",
    "save_trace",
]
