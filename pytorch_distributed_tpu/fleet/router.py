"""The replica router: N schedulers behind one front-end, one host loop.

``FleetRouter`` owns ``n_replicas`` ``Scheduler`` + ``PagedEngine``
replicas — single-process, each committed to its own device slice of
``jax.devices()`` (round-robin; on a one-device host they share it and
the router degrades to a pure scheduling simulation, which is exactly
the CPU-backend proof ROADMAP item 3 prescribes — multi-process
collectives are a known jaxlib CPU gap). Requests enter through
``submit`` with an optional session id:

- **session affinity**: a session's first request pins it to the
  replica the SLO gate picks; later requests follow — and with
  ``prefix_cache=True`` replicas (round 17) this IS the prefix-cache
  key: a session lands where its shared prefix is resident in the
  replica-local radix index, so the lookup hits without any cross-
  replica index. The table is LRU-bounded (``affinity_cap``; evictions
  counted) and the gate's ``prefix_sticky_depth`` rung keeps sessions
  on a merely-busy affinity replica a few requests longer before a
  spill trades their prefix locality for latency;
- **SLO-aware admission** (``fleet.admission.SLOGate``): admit / spill /
  queue / shed against the live per-replica TTFT/queue-wait percentiles
  and queue depths; sheds are explicit per-request JSONL records with
  ``rejected: true`` and a reason;
- **one host loop**: ``step()`` ticks every replica once — decode
  replicas first (their token sync never waits behind freshly dispatched
  prefill work), then prefill/mixed replicas, then the handoff pump.
  **Round 16 (``async_host=True``)** turns that loop into
  dispatch-then-collect: every replica's compiled tick is LAUNCHED
  back-to-back (JAX async dispatch — nothing materializes), results are
  drained one tick LAGGED (the PR 4 metrics-ring idiom), and the
  per-request host work rides a small ``HostWorkerPool`` — so replica
  B's device no longer sits idle for replica A's tokenize/JSONL/gate
  math. Greedy token streams are bit-identical between the two loops
  (per replica, collect(N−1) → dispatch(N) IS the synchronous
  schedule); ``async_host=False`` stays the step-domain reference.

Disaggregated prefill/decode (``disaggregate=True``): the first
``n_prefill`` replicas run ``prefill_only`` schedulers — chunk programs
only, requests parked in ``ready`` when their prompt is in the pool —
and the rest run decode. The handoff pump moves each ready request's KV
blocks into the least-loaded decode replica
(``PagedEngine.export_chain`` → ``import_chain``: an explicit
``jax.device_put`` block transfer plus a block-table remap in the
target pool), after which the request decodes exactly as if it had
prefilled there — token-identical greedy streams, proven in
tests/test_fleet.py. Decode token gaps stop paying for prefill bursts:
a mixed replica's decode tick is data-dependent on the chunk program
that precedes it in the same step (shared pool, same device), while a
decode replica's tick depends only on its own pool.

Replica geometry (config, slots, block_len, chunk) is uniform across
the fleet — the handoff requires pool-compatible blocks, and uniform
replicas keep the registry story simple: ``registries()`` builds one
``compilecache.serving_registry`` per replica (per-mesh/per-device) and
``assert_registry_covers()`` runs the coverage guard across all of
them.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from pytorch_distributed_tpu.fleet.admission import (
    ADMIT,
    PREEMPT,
    SHED,
    SPILL,
    SLOConfig,
    SLOGate,
    recommend_replicas,
    trace_decision,
)
from pytorch_distributed_tpu.serving.scheduler import Scheduler
from pytorch_distributed_tpu.telemetry import LatencySeries, percentiles


class FleetRouter:
    """Front-end over N single-process replicas.

    ``submit(prompt, max_new, session=...)`` routes (or sheds) one
    request and returns its fleet-wide rid; ``step()`` advances every
    replica one tick and returns ``[(rid, token)]``; ``drain()`` runs
    the fleet to empty. ``metrics()`` aggregates fleet percentiles,
    shed/spill rates, per-replica summaries, and the autoscaler's
    current recommendation.
    """

    def __init__(self, config, params, n_replicas: int = 2, *,
                 disaggregate: bool = False, n_prefill: int = 1,
                 decode_slots: Optional[int] = None,
                 handoffs_per_tick: Optional[int] = None,
                 slo: Optional[SLOConfig] = None, devices=None,
                 seed: int = 0, metrics_log=None, tracer=None,
                 flightrec=None, reqtrace=None, ledger=None,
                 async_host: bool = False, host_threads: int = 2,
                 affinity_cap: int = 4096,
                 **scheduler_kwargs):
        import jax

        from pytorch_distributed_tpu.telemetry import (
            NULL_LEDGER,
            NULL_RECORDER,
            NULL_REQTRACER,
        )

        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        if disaggregate:
            if n_replicas < 2:
                raise ValueError("disaggregation needs >= 2 replicas")
            if not 1 <= n_prefill < n_replicas:
                raise ValueError(
                    f"n_prefill must be in [1, {n_replicas - 1}], "
                    f"got {n_prefill}"
                )
        if devices is None:
            devices = jax.devices()
        self.gate = SLOGate(slo)
        self.metrics_log = metrics_log
        # fleet forensics (ISSUE 8): routing decisions — sheds, spills,
        # handoffs — land in the shared flight-recorder ring, so a
        # post-mortem dump shows WHY requests went where before death
        self.flightrec = flightrec if flightrec is not None else NULL_RECORDER
        # request-lifecycle tracing (round 14): ONE shared ReqTracer
        # across every replica, so a request's spans stay one tree as it
        # crosses the admission gate, the prefill replica, the handoff,
        # and the decode replica
        self.reqtrace = reqtrace if reqtrace is not None else NULL_REQTRACER
        # host–device overlap ledger (round 15): ONE shared
        # DispatchLedger across the fleet, so every replica's launches
        # land on one wall-clock axis and a gap on replica B can be
        # attributed to replica A's tick — the one-loop serialization
        # ROADMAP item 3's async refactor must remove
        self.ledger = ledger if ledger is not None else NULL_LEDGER
        # async host runtime (round 16; ROADMAP item 3): dispatch-then-
        # collect replica ticks + ONE worker pool shared by every
        # replica for the off-critical-path host work (JSONL emission,
        # gate-metric percentile math). async_host=False keeps the
        # synchronous loop bit-for-bit — the step-domain A/B reference.
        self.async_host = bool(async_host)
        self.host_pool = None
        if self.async_host:
            from pytorch_distributed_tpu.serving.host_worker import (
                HostWorkerPool,
            )

            self.host_pool = HostWorkerPool(n_threads=host_threads)
        # block-lifecycle sanitizer (analysis.blocksan; PDT_BLOCKSAN=1):
        # ONE sanitizer shared by every replica, so handoff pins and
        # violations aggregate fleet-wide and one assert_clean() covers
        # the whole pool population. None (the default) end to end.
        from pytorch_distributed_tpu.analysis.blocksan import maybe_sanitizer

        self.blocksan = maybe_sanitizer(metrics_log=metrics_log)
        self.replicas: List[Scheduler] = []
        self.roles: List[str] = []
        for i in range(n_replicas):
            role = (
                ("prefill" if i < n_prefill else "decode")
                if disaggregate else "mixed"
            )
            # one device per replica, round-robin over the host's slice
            # of jax.devices(); on a single-device host all replicas
            # share it (placement left implicit — bit-identical to a
            # plain Scheduler)
            dev = devices[i % len(devices)] if len(devices) > 1 else None
            # disaggregation sizes roles independently (the DistServe
            # argument): a request holds a prefill slot for
            # ceil(prompt/chunk) ticks but a decode slot for max_new
            # ticks, so decode replicas usually want MORE lanes — pool
            # block geometry stays uniform (the handoff requires it),
            # only the lane count differs
            kw = dict(scheduler_kwargs)
            if role == "decode" and decode_slots is not None:
                kw["n_slots"] = decode_slots
            self.replicas.append(Scheduler(
                config, params, replica_id=i, seed=seed + i,
                prefill_only=(role == "prefill"), device=dev,
                handoff=disaggregate, metrics_log=metrics_log,
                tracer=tracer, flightrec=self.flightrec,
                reqtrace=self.reqtrace, ledger=self.ledger,
                host_pool=self.host_pool, blocksan=self.blocksan, **kw,
            ))
            self.roles.append(role)
        self.disaggregated = disaggregate
        #: max KV handoffs per tick (None = unbounded). The handoff's
        #: host-driven gather/put/scatter runs between decode ticks in
        #: the one-loop simulation; budgeting it bounds how much a
        #: prefill burst can stretch resident streams' token gaps —
        #: trading a little TTFT for decode p95, same as a transfer-
        #: bandwidth cap would on real interconnect
        self.handoffs_per_tick = handoffs_per_tick
        #: replicas requests enter through (mixed, or prefill in disagg)
        self.entry_group = [
            i for i, r in enumerate(self.roles) if r != "decode"
        ]
        self.decode_group = [
            i for i, r in enumerate(self.roles) if r == "decode"
        ]
        self._next_rid = 0
        # session -> replica, LRU-bounded (round 17 fix: this mapping
        # grew one entry per session forever — a fleet fed from a
        # 100k-session trace leaked the table. An OrderedDict capped at
        # ``affinity_cap`` evicts the least-recently-ROUTED session;
        # an evicted session that returns simply re-pins wherever the
        # gate sends it, exactly like a new session. The cap also
        # bounds the prefix-locality loss: a session idle long enough
        # to fall off the affinity table has usually had its index
        # blocks LRU-evicted too.)
        if affinity_cap < 1:
            raise ValueError(f"affinity_cap must be >= 1, got {affinity_cap}")
        self.affinity_cap = affinity_cap
        self._affinity: "OrderedDict[int, int]" = OrderedDict()
        self._affinity_evictions = 0
        self.placement: Dict[int, int] = {}  # rid -> current replica
        self.rejected: Dict[int, str] = {}  # rid -> shed reason
        self.results: Dict[int, List[int]] = {}
        self._spilled = 0
        self._preempt_routes = 0
        self._handoff_count = 0
        self.handoff_lat = LatencySeries("handoff")
        self._start_time: Optional[float] = None
        self._tick = 0
        # the autoscaler signal is only meaningful UNDER load — a
        # drained fleet always says "hold" — so the router samples the
        # recommendation as it runs and keeps the high-water mark
        self._recommend_peak = len(self.entry_group)

    # ---- routing ----

    def _group_metrics(self, group: List[int]) -> Dict[int, dict]:
        # gate_metrics == metrics() on the synchronous loop; under the
        # async loop it is the worker-refreshed snapshot + live cheap
        # counters, so per-submit routing stops paying the O(n log n)
        # percentile math on the critical path
        return {i: self.replicas[i].gate_metrics() for i in group}

    def submit(self, prompt: np.ndarray, max_new_tokens: int, *,
               session: Optional[int] = None) -> int:
        """Route one request; returns its fleet rid. A shed request gets
        a rid too — ``rejected[rid]`` holds the reason and no tokens
        will ever stream for it (the explicit fast-reject contract)."""
        rid = self._next_rid
        self._next_rid += 1
        preferred = None
        if session is not None:
            preferred = self._affinity.get(session)
            if preferred is not None:
                self._affinity.move_to_end(session)  # LRU touch
        with self.ledger.host("admission/gate"):
            decision = self.gate.route(
                self._group_metrics(self.entry_group), preferred
            )
        if self.reqtrace.enabled:
            # the gate decision opens the request's root span — the
            # first causal fact of its lifecycle (a shed closes it
            # right here: complete trace, outcome=shed)
            trace_decision(
                self.reqtrace, rid, decision, session=session,
                preferred=preferred,
                prompt_len=int(np.asarray(prompt).size),
            )
        if decision.action == SHED:
            self.rejected[rid] = decision.reason
            self.flightrec.record("shed", rid=rid, reason=decision.reason)
            if self.metrics_log is not None:
                self.metrics_log.log(
                    kind="request", rid=rid,
                    replica_id=(preferred if preferred is not None else -1),
                    rejected=True, reject_reason=decision.reason,
                    session=session,
                    prompt_len=int(np.asarray(prompt).size),
                    new_tokens=0,
                )
            return rid
        target = decision.replica
        if session is not None and session not in self._affinity:
            self._affinity[session] = target
            while len(self._affinity) > self.affinity_cap:
                self._affinity.popitem(last=False)
                self._affinity_evictions += 1
        if decision.action == SPILL:
            self._spilled += 1
            self.flightrec.record(
                "spill", rid=rid, to=target, reason=decision.reason
            )
        elif decision.action == PREEMPT:
            # the pressure rung: park one LRU chain on the target, then
            # queue this request in the capacity it frees. A victim can
            # vanish between the gate's metrics read and now — the
            # request still queues there (backpressure, not failure).
            victim = self.replicas[target].preempt_lru(
                reason=decision.reason or "pressure"
            )
            self._preempt_routes += 1
            self.flightrec.record(
                "preempt_route", rid=rid, to=target, victim=victim,
                reason=decision.reason,
            )
        self.replicas[target].submit(
            prompt, max_new_tokens, session=session,
            spilled=(decision.action == SPILL), rid=rid,
        )
        self.placement[rid] = target
        return rid

    # ---- the host loop ----

    def _pump_handoffs(self) -> None:
        """Move every ready request's KV blocks prefill→decode. Targets
        are tried least-loaded-first; a full decode fleet leaves the
        request parked (blocks intact on the prefill replica) for the
        next tick — the same queue-don't-crash contract as admission."""
        budget = (
            self.handoffs_per_tick
            if self.handoffs_per_tick is not None else float("inf")
        )
        order = sorted(
            self.decode_group,
            key=lambda i: (len(self.replicas[i].resident),
                           len(self.replicas[i].queue)),
        )
        preempted_this_pump = False
        for pi in self.entry_group:
            ps = self.replicas[pi]
            for rid in ps.ready_rids():
                if budget <= 0:
                    return
                req, export = ps.peek_ready(rid)
                t0 = time.perf_counter()
                adopted_by = next(
                    (di for di in order
                     if self.replicas[di].adopt(req, export)), None,
                )
                if adopted_by is None:
                    # no decode capacity this tick. Under the pressure
                    # tier, park ONE idle decode chain (LRU) so next
                    # tick's pump can adopt — the handoff twin of the
                    # SLO gate's preempt rung: a prefill-complete
                    # request stalling on a full decode pool is the same
                    # over-commit the admission path preempts for. One
                    # victim per pump (anti-thrash); the request stays
                    # parked here, blocks intact, and retries.
                    if not preempted_this_pump:
                        for di in order:
                            if not self.replicas[di].offload:
                                continue
                            victim = self.replicas[di].preempt_lru(
                                reason="handoff-pressure"
                            )
                            if victim is not None:
                                preempted_this_pump = True
                                self._preempt_routes += 1
                                self.flightrec.record(
                                    "preempt_route", rid=rid, to=di,
                                    victim=victim,
                                    reason="handoff-pressure",
                                )
                                break
                    break
                ps.complete_handoff(rid)
                wall = time.perf_counter() - t0
                self.handoff_lat.observe(wall)
                self.placement[rid] = adopted_by
                self._handoff_count += 1
                if self.reqtrace.enabled:
                    # the handoff as a span of its own (backdated to the
                    # export), plus a flow link to the decode window it
                    # enabled on the other replica — peek/adopt/complete
                    # become visible parent→child structure in the trace
                    h = self.reqtrace.begin(
                        rid, "handoff", replica=pi, t=t0, src=pi,
                        dst=adopted_by, blocks=export.n_blocks,
                        bytes=ps.engine.chain_bytes(export.n_blocks),
                    )
                    self.reqtrace.end(h, wall_s=round(wall, 6))
                    self.reqtrace.link(rid, h, req.span_decode,
                                       "handoff")
                self.flightrec.record(
                    "handoff", rid=rid, src=pi, dst=adopted_by
                )
                budget -= 1

    def step(self) -> List[Tuple[int, int]]:
        """One fleet tick. Synchronous loop: tick each replica fully —
        decode replicas first (their token sync stays clear of this
        tick's fresh prefill dispatches), then prefill/mixed replicas,
        then the handoff pump. Async loop (``async_host=True``):
        **dispatch-then-collect** — first COLLECT every replica's
        previous tick (lagged: those ticks have been in flight across
        the pump and all inter-step host work), then DISPATCH every
        replica's next tick back-to-back so every compiled program is
        enqueued before any of this step's host work runs, then the
        pump. Per replica the order collect(N−1) → dispatch(N) is the
        synchronous schedule, so greedy token streams are bit-identical
        between modes; only cross-replica interleaving (and the wall
        clock) changes."""
        if self._start_time is None:
            self._start_time = time.perf_counter()
        out: List[Tuple[int, int]] = []
        order = self.decode_group + self.entry_group
        if self.async_host:
            # interleaved collect/dispatch: while replica i's freshly
            # dispatched tick N is in flight, the loop is already
            # collecting replica i+1's tick N−1 and building its tick N
            # — every replica's dispatch-side host work (admissions,
            # chunk batch build, table masking) overlaps some OTHER
            # replica's device work, which a collect-all-then-
            # dispatch-all phasing would leave serialized against an
            # idle device
            for i in order:
                out.extend(self.replicas[i].collect_tick())
                self.replicas[i].dispatch_tick()
        else:
            for i in order:
                out.extend(self.replicas[i].step())
        if self.decode_group:
            with self.ledger.host("handoff-pump"):
                self._pump_handoffs()
        for rid, tok in out:
            self.results.setdefault(rid, []).append(tok)
        self._tick += 1
        if self._tick % 16 == 0:  # sampled: metrics() per tick is waste
            self._recommend_peak = max(self._recommend_peak,
                                       self.recommend_replicas())
        return out

    @property
    def idle(self) -> bool:
        # Scheduler.idle counts parked and mid-swap requests as
        # in-flight work, so a drain never strands a preempted stream;
        # has_uncollected keeps the async loop stepping until every
        # in-flight tick's tokens have been collected AND delivered
        return all(
            s.idle and not s.has_uncollected for s in self.replicas
        )

    def drain(self, max_steps: int = 100_000) -> Dict[int, List[int]]:
        """Step until every replica is empty; returns ``{rid: [tokens]}``
        for every request that produced output (shed rids absent)."""
        for _ in range(max_steps):
            if self.idle:
                if self.host_pool is not None:
                    # barrier: offloaded JSONL/metric work settles with
                    # the drain, same as the synchronous loop's contract
                    for s in self.replicas:
                        s.flush_host_work()
                    self.host_pool.flush()
                if self.blocksan is not None:
                    # fleet quiesce: every replica's ledger must equal
                    # its allocator with no chains, swap windows, or
                    # handoff pins outstanding (the drain retired or
                    # adopted everything; index-retained blocks are
                    # legitimately live)
                    for s in self.replicas:
                        if s._san is not None:
                            s._san.verify_quiesce()
                return dict(self.results)
            self.step()
        raise RuntimeError(
            f"fleet drain did not converge within {max_steps} steps"
        )

    def cancel(self, rid: int, reason: str = "client-cancel") -> bool:
        """Fleet cancellation: abort ``rid`` on whichever replica holds
        it (queued, resident, parked, mid-swap, or handoff-ready).
        Returns False when no replica knows the rid — already retired,
        shed, or never submitted; cancellation is idempotent."""
        return any(s.cancel(rid, reason=reason) for s in self.replicas)

    # ---- compile-cache integration ----

    def registries(self):
        """One ``compilecache.serving_registry`` per replica — the
        programs each replica can ever compile, enumerated on ITS
        mesh/device placement."""
        from pytorch_distributed_tpu.compilecache import serving_registry

        return [
            serving_registry(s.engine, extra=(f"replica={s.replica_id}",
                                              f"role={role}"))
            for s, role in zip(self.replicas, self.roles)
        ]

    def assert_registry_covers(self) -> None:
        """Fleet-wide coverage guard: every compiled program on every
        replica must have been predicted by that replica's registry."""
        for reg, s in zip(self.registries(), self.replicas):
            reg.assert_covers(s.engine.compiled_program_names())

    def warmup(self, background: bool = False) -> None:
        """Compile every replica's programs before traffic (decode
        replicas only ever need the decode tick, but uniform warmup
        keeps role changes free)."""
        for s in self.replicas:
            s.warmup(background=background)

    # ---- metrics ----

    def recommend_replicas(self) -> int:
        """The autoscaler hook (``fleet.admission.recommend_replicas``)
        over the ENTRY group's live metrics — decode replicas scale with
        prefill replicas, not independently, in this round."""
        return recommend_replicas(
            len(self.entry_group),
            list(self._group_metrics(self.entry_group).values()),
            self.gate,
        )

    def metrics(self) -> dict:
        """Fleet rollup: totals, shed/spill rates, fleet-wide latency
        percentiles (replica series concatenated — every request appears
        in exactly one replica's series), handoff stats, the autoscaler
        recommendation, and flat per-replica key summaries."""
        per = [s.metrics() for s in self.replicas]
        submitted = self._next_rid
        shed = len(self.rejected)
        placed = submitted - shed
        elapsed = (
            time.perf_counter() - self._start_time
            if self._start_time is not None else 0.0
        )
        out: dict = {
            "replicas": len(self.replicas),
            "disaggregated": self.disaggregated,
            "submitted": submitted,
            "shed": shed,
            "spilled": self._spilled,
            "shed_rate": shed / submitted if submitted else 0.0,
            "spill_rate": self._spilled / placed if placed else 0.0,
            "completed": sum(m["completed"] for m in per),
            "tokens_out": sum(m["tokens_out"] for m in per),
            "tokens_per_s": (
                sum(m["tokens_out"] for m in per) / elapsed
                if elapsed else 0.0
            ),
            "handoffs": self._handoff_count,
            # pressure tier rollup (round 13): fleet-wide preemptions,
            # restores, parked chains, and swap traffic — shed stays the
            # headline failure count these exist to zero out
            "preempt_routes": self._preempt_routes,
            "preempts": sum(m["preempts"] for m in per),
            "restores": sum(m["restores"] for m in per),
            "parked": sum(m["parked"] for m in per),
            "swap_bytes": sum(m["swap_bytes"] for m in per),
            "swap_aborts": sum(m["swap_aborts"] for m in per),
            "preempt_rate": (
                sum(m["preempts"] for m in per) / placed if placed else 0.0
            ),
            # prefix-cache rollup (round 17): fleet-wide hit rate over
            # per-replica lookups (each admission looks up exactly once
            # on its replica, so concatenating series is exact), the
            # sharing/COW/eviction totals, and the affinity table's LRU
            # accounting (the round-17 unbounded-growth fix)
            "prefix_lookups": sum(m["prefix_lookups"] for m in per),
            "prefix_hits": sum(m["prefix_hits"] for m in per),
            "prefix_hit_rate": (
                sum(m["prefix_hits"] for m in per)
                / max(sum(m["prefix_lookups"] for m in per), 1)
            ),
            "prefix_covered_tokens": sum(
                m["prefix_covered_tokens"] for m in per
            ),
            "admitted_prefill_tokens": sum(
                m["admitted_prefill_tokens"] for m in per
            ),
            "prefix_cow_copies": sum(m["prefix_cow_copies"] for m in per),
            "prefix_evictions": sum(m["prefix_evictions"] for m in per),
            "prefix_shared_blocks": sum(
                m["prefix_shared_blocks"] for m in per
            ),
            "affinity_sessions": len(self._affinity),
            "affinity_evictions": self._affinity_evictions,
            "cancelled": sum(m["cancelled"] for m in per),
            **(self.blocksan.summary()
               if self.blocksan is not None else {}),
            "recommended_replicas": self.recommend_replicas(),
            "recommended_replicas_peak": self._recommend_peak,
            "async_host": self.async_host,
        }
        # host–device overlap rollup (round 16): per-replica device-busy
        # fractions PLUS the interval-union fraction. On a shared device
        # (the CPU simulation) a replica's dispatch→completion window
        # includes time queued behind the other replicas, so per-replica
        # fractions overlap and must not be summed — the union is true
        # device utilization, backend-marked (gather_ab_backend pattern)
        if self.ledger.enabled:
            from pytorch_distributed_tpu.telemetry.overlap import (
                fleet_busy_summary,
            )

            fb = fleet_busy_summary(self.ledger.snapshot())
            if fb["replicas"]:
                import jax

                out["device_busy_frac_union"] = fb["union_busy_frac"]
                out["device_busy_backend"] = jax.default_backend()
                for rep, frac in sorted(fb["replicas"].items()):
                    out[f"r{rep}_device_busy_frac"] = frac
        out.update(self.handoff_lat.summary("handoff"))
        for name in ("ttft", "token_lat", "queue_wait"):
            vals: List[float] = []
            for s in self.replicas:
                vals.extend(getattr(s, name).values)
            for q, v in percentiles(vals).items():
                out[f"{name}_{q}_s"] = v
        for i, m in enumerate(per):
            for k in ("tokens_out", "completed", "queue_depth",
                      "occupancy_mean", "goodput_frac", "preempts",
                      "restores"):
                out[f"r{i}_{k}"] = m[k]
            for k in ("ttft_p95_s", "queue_wait_p95_s"):
                if k in m:
                    out[f"r{i}_{k}"] = m[k]
            out[f"r{i}_role"] = self.roles[i]
        return out

    def log_summary(self) -> None:
        """One ``kind="fleet_summary"`` JSONL record — the fleet half of
        what ``scripts/telemetry_report.py`` renders. Flushes the async
        host workers first so every offloaded per-request record lands
        before the summary that aggregates them."""
        if self.host_pool is not None:
            for s in self.replicas:
                s.flush_host_work()
            self.host_pool.flush()
        if self.metrics_log is not None:
            with self.ledger.host("jsonl-emit"):
                self.metrics_log.log(kind="fleet_summary", **self.metrics())
