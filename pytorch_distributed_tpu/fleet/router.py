"""The replica router: N schedulers behind one front-end, one host loop.

``FleetRouter`` owns ``n_replicas`` ``Scheduler`` + ``PagedEngine``
replicas — single-process, each committed to its own device slice of
``jax.devices()`` (round-robin; on a one-device host they share it and
the router degrades to a pure scheduling simulation, which is exactly
the CPU-backend proof ROADMAP item 3 prescribes — multi-process
collectives are a known jaxlib CPU gap). Requests enter through
``submit`` with an optional session id:

- **session affinity**: a session's first request pins it to the
  replica the SLO gate picks; later requests follow — and with
  ``prefix_cache=True`` replicas (round 17) this IS the prefix-cache
  key: a session lands where its shared prefix is resident in the
  replica-local radix index, so the lookup hits without any cross-
  replica index. The table is LRU-bounded (``affinity_cap``; evictions
  counted) and the gate's ``prefix_sticky_depth`` rung keeps sessions
  on a merely-busy affinity replica a few requests longer before a
  spill trades their prefix locality for latency;
- **SLO-aware admission** (``fleet.admission.SLOGate``): admit / spill /
  queue / shed against the live per-replica TTFT/queue-wait percentiles
  and queue depths; sheds are explicit per-request JSONL records with
  ``rejected: true`` and a reason;
- **one host loop**: ``step()`` ticks every replica once — decode
  replicas first (their token sync never waits behind freshly dispatched
  prefill work), then prefill/mixed replicas, then the handoff pump.
  **Round 16 (``async_host=True``)** turns that loop into
  dispatch-then-collect: every replica's compiled tick is LAUNCHED
  back-to-back (JAX async dispatch — nothing materializes), results are
  drained one tick LAGGED (the PR 4 metrics-ring idiom), and the
  per-request host work rides a small ``HostWorkerPool`` — so replica
  B's device no longer sits idle for replica A's tokenize/JSONL/gate
  math. Greedy token streams are bit-identical between the two loops
  (per replica, collect(N−1) → dispatch(N) IS the synchronous
  schedule); ``async_host=False`` stays the step-domain reference.

Disaggregated prefill/decode (``disaggregate=True``): the first
``n_prefill`` replicas run ``prefill_only`` schedulers — chunk programs
only, requests parked in ``ready`` when their prompt is in the pool —
and the rest run decode. The handoff pump moves each ready request's KV
blocks into the least-loaded decode replica
(``PagedEngine.export_chain`` → ``import_chain``: an explicit
``jax.device_put`` block transfer plus a block-table remap in the
target pool), after which the request decodes exactly as if it had
prefilled there — token-identical greedy streams, proven in
tests/test_fleet.py. Decode token gaps stop paying for prefill bursts:
a mixed replica's decode tick is data-dependent on the chunk program
that precedes it in the same step (shared pool, same device), while a
decode replica's tick depends only on its own pool.

Replica geometry (config, slots, block_len, chunk) is uniform across
the fleet — the handoff requires pool-compatible blocks, and uniform
replicas keep the registry story simple: ``registries()`` builds one
``compilecache.serving_registry`` per replica (per-mesh/per-device) and
``assert_registry_covers()`` runs the coverage guard across all of
them.

Failure plane (round 19; ANALYSIS.md "Failure model & recovery
guarantees"): every replica carries a health state machine —
``healthy → suspect → dead → draining → rejoining`` — driven by
exceptions escaping ``dispatch_tick``/``collect_tick``/the handoff
trio and by the serve-side watchdog's tick deadline
(``resilience.watchdog.FleetWatchdog``; a tick that overruns
``tick_deadline_s`` condemns its replica exactly like a crash — a
wedged device loop and a dead process are indistinguishable from the
control plane). A condemned replica is **drained of identity**: its
in-flight requests are harvested from their ``Request`` records
(``Scheduler.harvest_requests``), its device state torn down leak-free
(``Scheduler.abandon``; blocksan-verified), its affinity entries
invalidated, and the harvested requests re-dispatched to surviving
replicas with bounded deterministic backoff
(``resilience.retry.backoff_delays``) — each replay re-submits the
original prompt plus every token the router already DELIVERED, so the
prefix cache absorbs the replay cost and greedy client streams stay
append-consistent (token-identical to a fault-free run). An attempt
cap sheds the request with ``outcome="failed"`` instead of retrying
forever; a request whose deadline lapses anywhere in this machinery
expires with ``outcome="deadline"``. ``revive(i)`` re-admits a fresh
replica at a dead slot behind compile-cache warmup — survivors never
recompile (registry-fingerprint proof) and no request drops during
the rejoin.
"""

from __future__ import annotations

import logging
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from pytorch_distributed_tpu.fleet.admission import (
    ADMIT,
    PREEMPT,
    SHED,
    SPILL,
    Decision,
    SLOConfig,
    SLOGate,
    recommend_replicas,
    trace_decision,
)
from pytorch_distributed_tpu.resilience.retry import backoff_delays
from pytorch_distributed_tpu.resilience.watchdog import FleetWatchdog
from pytorch_distributed_tpu.serving.scheduler import Scheduler
from pytorch_distributed_tpu.telemetry import LatencySeries, percentiles

logger = logging.getLogger("pytorch_distributed_tpu")

#: the replica health state machine (round 19). ``draining`` is the
#: instant between condemnation and the end of harvest+abandon —
#: observable in the ``kind="health"`` JSONL even though the one-loop
#: simulation passes through it synchronously; ``rejoining`` is a
#: revived replica warming its compile cache before taking traffic.
HEALTH_STATES = ("healthy", "suspect", "dead", "draining", "rejoining")

#: health states the router routes traffic to (suspect replicas keep
#: serving — one failed tick is a warning, not a death sentence)
_ROUTABLE = ("healthy", "suspect")


class FleetRouter:
    """Front-end over N single-process replicas.

    ``submit(prompt, max_new, session=...)`` routes (or sheds) one
    request and returns its fleet-wide rid; ``step()`` advances every
    replica one tick and returns ``[(rid, token)]``; ``drain()`` runs
    the fleet to empty. ``metrics()`` aggregates fleet percentiles,
    shed/spill rates, per-replica summaries, and the autoscaler's
    current recommendation.
    """

    def __init__(self, config, params, n_replicas: int = 2, *,
                 disaggregate: bool = False, n_prefill: int = 1,
                 decode_slots: Optional[int] = None,
                 handoffs_per_tick: Optional[int] = None,
                 slo: Optional[SLOConfig] = None, devices=None,
                 seed: int = 0, metrics_log=None, tracer=None,
                 flightrec=None, reqtrace=None, ledger=None,
                 async_host: bool = False, host_threads: int = 2,
                 affinity_cap: int = 4096,
                 fail_threshold: int = 2,
                 tick_deadline_s: Optional[float] = None,
                 redispatch_max_attempts: int = 3,
                 redispatch_base_delay_s: float = 0.05,
                 retain_results: bool = True,
                 **scheduler_kwargs):
        import jax

        from pytorch_distributed_tpu.telemetry import (
            NULL_LEDGER,
            NULL_RECORDER,
            NULL_REQTRACER,
        )

        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        if disaggregate:
            if n_replicas < 2:
                raise ValueError("disaggregation needs >= 2 replicas")
            if not 1 <= n_prefill < n_replicas:
                raise ValueError(
                    f"n_prefill must be in [1, {n_replicas - 1}], "
                    f"got {n_prefill}"
                )
        if devices is None:
            devices = jax.devices()
        self.gate = SLOGate(slo)
        self.metrics_log = metrics_log
        # fleet forensics (ISSUE 8): routing decisions — sheds, spills,
        # handoffs — land in the shared flight-recorder ring, so a
        # post-mortem dump shows WHY requests went where before death
        self.flightrec = flightrec if flightrec is not None else NULL_RECORDER
        # request-lifecycle tracing (round 14): ONE shared ReqTracer
        # across every replica, so a request's spans stay one tree as it
        # crosses the admission gate, the prefill replica, the handoff,
        # and the decode replica
        self.reqtrace = reqtrace if reqtrace is not None else NULL_REQTRACER
        # host–device overlap ledger (round 15): ONE shared
        # DispatchLedger across the fleet, so every replica's launches
        # land on one wall-clock axis and a gap on replica B can be
        # attributed to replica A's tick — the one-loop serialization
        # ROADMAP item 3's async refactor must remove
        self.ledger = ledger if ledger is not None else NULL_LEDGER
        # async host runtime (round 16; ROADMAP item 3): dispatch-then-
        # collect replica ticks + ONE worker pool shared by every
        # replica for the off-critical-path host work (JSONL emission,
        # gate-metric percentile math). async_host=False keeps the
        # synchronous loop bit-for-bit — the step-domain A/B reference.
        self.async_host = bool(async_host)
        self.host_pool = None
        if self.async_host:
            from pytorch_distributed_tpu.serving.host_worker import (
                HostWorkerPool,
            )

            self.host_pool = HostWorkerPool(n_threads=host_threads)
        # block-lifecycle sanitizer (analysis.blocksan; PDT_BLOCKSAN=1):
        # ONE sanitizer shared by every replica, so handoff pins and
        # violations aggregate fleet-wide and one assert_clean() covers
        # the whole pool population. None (the default) end to end.
        from pytorch_distributed_tpu.analysis.blocksan import maybe_sanitizer

        self.blocksan = maybe_sanitizer(metrics_log=metrics_log)
        # construction inputs are retained so ``revive()`` can rebuild a
        # dead replica slot from scratch with identical geometry — the
        # handoff and the registry fingerprint both require it
        self._config = config
        self._params = params
        self._devices = devices
        self._seed = seed
        self._tracer = tracer
        self._disaggregate = disaggregate
        self._n_prefill = n_prefill
        self._decode_slots = decode_slots
        self._scheduler_kwargs = scheduler_kwargs
        self.replicas: List[Scheduler] = []
        self.roles: List[str] = []
        for i in range(n_replicas):
            role = (
                ("prefill" if i < n_prefill else "decode")
                if disaggregate else "mixed"
            )
            self.roles.append(role)
            self.replicas.append(self._make_replica(i))
        self.disaggregated = disaggregate
        #: max KV handoffs per tick (None = unbounded). The handoff's
        #: host-driven gather/put/scatter runs between decode ticks in
        #: the one-loop simulation; budgeting it bounds how much a
        #: prefill burst can stretch resident streams' token gaps —
        #: trading a little TTFT for decode p95, same as a transfer-
        #: bandwidth cap would on real interconnect
        self.handoffs_per_tick = handoffs_per_tick
        #: replicas requests enter through (mixed, or prefill in disagg)
        self.entry_group = [
            i for i, r in enumerate(self.roles) if r != "decode"
        ]
        self.decode_group = [
            i for i, r in enumerate(self.roles) if r == "decode"
        ]
        self._next_rid = 0
        # session -> replica, LRU-bounded (round 17 fix: this mapping
        # grew one entry per session forever — a fleet fed from a
        # 100k-session trace leaked the table. An OrderedDict capped at
        # ``affinity_cap`` evicts the least-recently-ROUTED session;
        # an evicted session that returns simply re-pins wherever the
        # gate sends it, exactly like a new session. The cap also
        # bounds the prefix-locality loss: a session idle long enough
        # to fall off the affinity table has usually had its index
        # blocks LRU-evicted too.)
        if affinity_cap < 1:
            raise ValueError(f"affinity_cap must be >= 1, got {affinity_cap}")
        self.affinity_cap = affinity_cap
        self._affinity: "OrderedDict[int, int]" = OrderedDict()
        self._affinity_evictions = 0
        self.placement: Dict[int, int] = {}  # rid -> current replica
        self.rejected: Dict[int, str] = {}  # rid -> shed reason
        self.results: Dict[int, List[int]] = {}
        # round 21 (scale observatory): retention mode. The default
        # keeps every rid's token list forever — ``drain()`` returns
        # the full results dict, the redispatch replay reads it as the
        # authoritative delivered stream, and benches assert equality
        # on it; all O(sessions ever). ``retain_results=False`` is the
        # soak/streaming mode: callers consume ``step()``'s (rid, tok)
        # pairs live, and the router drops a rid's results/placement
        # entries once it retires — host state stays O(live requests).
        # Trade-off: a replica death then re-delivers the tokens the
        # retired-entry replay would have skipped, so streaming mode is
        # for fault-free soaks and dedup-capable consumers. ``rejected``
        # and ``failed`` keep only the most recent ``_REJECT_CAP``
        # entries in this mode (counters stay exact).
        self.retain_results = bool(retain_results)
        self._retired_pending: List[int] = []
        # round 22 (HTTP front door): optional FLEET-level retire hook,
        # ``on_retire(rid, outcome)`` — one call per terminal transition
        # (complete / cancelled / deadline / failed), fired on the host-
        # loop thread from every terminal path: scheduler retire, failed
        # re-dispatch, router-side deadline expiry, redispatch-noop. The
        # gateway uses it to close SSE streams with the true outcome.
        # It fires mid-collect, BEFORE the final token lands in
        # ``results`` — consumers must drain queued tokens first.
        self.on_retire: Optional[Callable[[int, str], None]] = None
        self._results_dropped = 0
        self._spilled = 0
        self._preempt_routes = 0
        self._handoff_count = 0
        self.handoff_lat = LatencySeries("handoff")
        self._start_time: Optional[float] = None
        self._tick = 0
        # the autoscaler signal is only meaningful UNDER load — a
        # drained fleet always says "hold" — so the router samples the
        # recommendation as it runs and keeps the high-water mark
        self._recommend_peak = len(self.entry_group)
        # ---- failure plane (round 19) ----
        if fail_threshold < 1:
            raise ValueError(
                f"fail_threshold must be >= 1, got {fail_threshold}"
            )
        if redispatch_max_attempts < 1:
            raise ValueError(
                "redispatch_max_attempts must be >= 1, "
                f"got {redispatch_max_attempts}"
            )
        #: consecutive failed ticks before suspect escalates to dead —
        #: one transient exception marks the replica suspect and is
        #: forgiven by the next clean tick; ``fail_threshold`` in a row
        #: condemns it
        self.fail_threshold = fail_threshold
        #: wall-clock budget for one replica tick; a tick that overruns
        #: it condemns the replica immediately (a wedged device loop has
        #: no exception to catch — the deadline IS its failure signal).
        #: None disables hang detection.
        self.tick_deadline_s = tick_deadline_s
        self.redispatch_max_attempts = redispatch_max_attempts
        self.redispatch_base_delay_s = redispatch_base_delay_s
        #: per-replica health records (the state machine lives here, not
        #: on the Scheduler: a dead replica's scheduler object is torn
        #: down and replaced, but its health history must survive)
        self.health: List[dict] = [
            {"state": "healthy", "consecutive": 0, "failures": 0,
             "last_error": None, "since_tick": 0,
             "redispatched_away": 0, "deaths": 0}
            for _ in range(n_replicas)
        ]
        #: rid -> immutable origin facts captured at FIRST death:
        #: the true original prompt (tokens[:orig_len] before any
        #: replay widened it), budget, session, absolute deadline, and
        #: the attempt counter. Replays after later deaths rebuild from
        #: here + the delivered-token record, never from the dying
        #: scheduler's view.
        self._origin: Dict[int, dict] = {}
        #: harvested requests awaiting re-dispatch: each entry
        #: {rid, not_before, src} — not_before is the deterministic
        #: backoff instant (resilience.retry.backoff_delays, seeded by
        #: rid so the chaos matrix replays bit-identically)
        self._pending_redispatch: List[dict] = []
        #: rid -> reason, requests shed AFTER admission: the re-dispatch
        #: attempt cap was exhausted. Disjoint from ``rejected`` (never
        #: admitted) — a failed rid may have streamed partial tokens.
        self.failed: Dict[int, str] = {}
        # monotonic twins of ``len(rejected)``/``len(failed)``: the
        # streaming-mode trim drops old REASONS, so the headline shed/
        # failed counts must not be derived from table length (round 21
        # fix — metrics() undercounted past _REJECT_CAP sheds)
        self._shed_total = 0
        self._failed_total = 0
        self._redispatched = 0
        self._deadline_expired_redispatch = 0
        self._deadline_sheds = 0
        self._ticking: Optional[int] = None
        # serve-side watchdog: one heartbeat per replica, beaten at the
        # top of each tick. The one-loop simulation can only wedge
        # inside the CURRENTLY ticking replica, so the stall handler
        # ignores every other (merely aging) heartbeat; the thread is
        # the live-stall observer, while step() itself re-checks the
        # tick wall clock after the fact so hang condemnation is
        # deterministic under test (no thread timing in the loop).
        self.watchdog: Optional[FleetWatchdog] = None
        if tick_deadline_s is not None:
            self.watchdog = FleetWatchdog(
                tick_deadline_s, on_stall=self._on_stall,
                flightrec=self.flightrec,
            )
            for i in range(n_replicas):
                self.watchdog.watch(f"replica{i}")

    def _make_replica(self, i: int) -> Scheduler:
        """Build replica ``i``'s Scheduler from the retained
        construction inputs — used by ``__init__`` and by ``revive()``
        (a revived slot gets a FRESH scheduler/engine/pool with the
        same geometry, device placement, and seed as the dead one, so
        the registry fingerprint and greedy streams are unchanged)."""
        role = self.roles[i]
        # one device per replica, round-robin over the host's slice
        # of jax.devices(); on a single-device host all replicas
        # share it (placement left implicit — bit-identical to a
        # plain Scheduler)
        dev = (
            self._devices[i % len(self._devices)]
            if len(self._devices) > 1 else None
        )
        # disaggregation sizes roles independently (the DistServe
        # argument): a request holds a prefill slot for
        # ceil(prompt/chunk) ticks but a decode slot for max_new
        # ticks, so decode replicas usually want MORE lanes — pool
        # block geometry stays uniform (the handoff requires it),
        # only the lane count differs
        kw = dict(self._scheduler_kwargs)
        if role == "decode" and self._decode_slots is not None:
            kw["n_slots"] = self._decode_slots
        s = Scheduler(
            self._config, self._params, replica_id=i,
            seed=self._seed + i, prefill_only=(role == "prefill"),
            device=dev, handoff=self._disaggregate,
            metrics_log=self.metrics_log, tracer=self._tracer,
            flightrec=self.flightrec, reqtrace=self.reqtrace,
            ledger=self.ledger, host_pool=self.host_pool,
            blocksan=self.blocksan, **kw,
        )
        s.on_retire = self._note_retire
        return s

    # ---- health plane ----

    def _set_health(self, i: int, state: str, reason: str) -> None:
        rec = self.health[i]
        prev = rec["state"]
        if state == prev:
            return
        rec["state"] = state
        rec["since_tick"] = self._tick
        logger.info(
            "fleet health: replica %d %s -> %s (%s)", i, prev, state,
            reason,
        )
        self.flightrec.record(
            "health", replica=i, state=state, prev=prev, reason=reason
        )
        if self.metrics_log is not None:
            self.metrics_log.log(
                kind="health", replica_id=i, state=state, prev=prev,
                reason=reason, tick=self._tick,
            )

    def _alive(self, group: List[int]) -> List[int]:
        """Members of ``group`` the router still routes to. Suspect
        replicas stay routable (their next clean tick clears them);
        dead, draining, and rejoining ones do not."""
        return [i for i in group if self.health[i]["state"] in _ROUTABLE]

    def _on_stall(self, name: str, stalled_s: float, dump: str) -> None:
        # live-stall observer (watchdog thread): only the CURRENTLY
        # ticking replica can genuinely wedge in the one-loop
        # simulation — every other heartbeat merely ages while it runs.
        # The handler just records; condemnation happens in _run_tick's
        # deterministic wall-clock re-check so tests never race the
        # poller thread.
        ticking = self._ticking
        if ticking is None or name != f"replica{ticking}":
            return
        logger.error(
            "fleet watchdog: replica %d tick stalled %.3fs "
            "(deadline %.3fs)", ticking, stalled_s, self.tick_deadline_s,
        )

    def _note_success(self, i: int) -> None:
        rec = self.health[i]
        rec["consecutive"] = 0
        if rec["state"] == "suspect":
            self._set_health(i, "healthy", "tick-recovered")

    # ---- retention plane (round 21) ----

    #: most-recent shed/failed entries kept in streaming-retention mode
    _REJECT_CAP = 1024

    def _note_retire(self, rid: int, outcome: str) -> None:
        """Scheduler retire hook (complete/cancel/deadline). Cleanup is
        deferred to ``_drop_retired`` at the END of the step: the
        retirement fires mid-collect, and the router appends the final
        token to ``results`` after collect returns — popping here would
        resurrect a one-token entry per retired rid."""
        # a completed rid can never be harvested again; its re-dispatch
        # origin facts are dead weight in EVERY retention mode (real
        # leak: one entry per redispatched-then-completed rid, forever)
        self._origin.pop(rid, None)
        if not self.retain_results:
            self._retired_pending.append(rid)
        if self.on_retire is not None:
            self.on_retire(rid, outcome)

    def _drop_retired(self) -> None:
        if self.retain_results or not self._retired_pending:
            return
        for rid in self._retired_pending:
            if self.results.pop(rid, None) is not None:
                self._results_dropped += 1
            self.placement.pop(rid, None)
        self._retired_pending.clear()

    def _trim_rejects(self) -> None:
        """Streaming mode: ``rejected``/``failed`` keep reasons for
        recent rids only (counters remain exact)."""
        if self.retain_results:
            return
        for table in (self.rejected, self.failed):
            while len(table) > self._REJECT_CAP:
                table.pop(next(iter(table)))

    def live_requests(self) -> int:
        """Fleet-wide in-flight request count: every replica's queued +
        resident + parked + mid-swap population, plus harvested rids
        awaiting re-dispatch — the census sweep's O(live) audit axis."""
        return (sum(s.live_requests() for s in self.replicas)
                + len(self._pending_redispatch))

    def census_decls(self):
        """Round 21 scale observatory: every long-lived container on
        the router declares its bound (telemetry/census.py). The
        rid-keyed tables are the interesting ones — unbounded by design
        under the default drain() contract, proven O(live) in
        streaming-retention mode."""
        from pytorch_distributed_tpu.telemetry.census import Decl

        def _retention(kind_live):
            return lambda r: kind_live if not r.retain_results \
                else "unbounded"

        return [
            Decl("replicas", "replicas", cap=lambda r: len(r.health),
                 why="one Scheduler per replica slot"),
            Decl("roles", "replicas", cap=lambda r: len(r.health),
                 why="role string per replica slot"),
            Decl("entry_group", "replicas", cap=lambda r: len(r.health),
                 why="subset of replica indices"),
            Decl("decode_group", "replicas", cap=lambda r: len(r.health),
                 why="subset of replica indices"),
            Decl("health", "replicas", cap=lambda r: len(r.health),
                 why="health record per replica slot, survives revive"),
            Decl("_affinity", "fixed", cap=lambda r: r.affinity_cap,
                 why="session→replica LRU, capped since round 17 (the "
                     "round-21 census proves the cap holds under soak)"),
            Decl("placement", _retention("live"),
                 why="rid→replica for in-flight rids; streaming mode "
                     "drops entries at retire, default mode keeps them "
                     "for the drain()/replay contract"),
            Decl("results", _retention("live"), per_live=1,
                 why="delivered-token record; the redispatch replay's "
                     "authoritative stream in default mode, dropped at "
                     "retire in streaming mode"),
            Decl("rejected",
                 lambda r: "unbounded" if r.retain_results else "fixed",
                 cap=lambda r: None if r.retain_results
                 else r._REJECT_CAP + 64,
                 why="shed reasons; streaming mode keeps the most "
                     "recent _REJECT_CAP (sheds counter stays exact)"),
            Decl("failed",
                 lambda r: "unbounded" if r.retain_results else "fixed",
                 cap=lambda r: None if r.retain_results
                 else r._REJECT_CAP + 64,
                 why="redispatch-exhausted reasons; bounded like "
                     "rejected in streaming mode"),
            Decl("_origin", "live",
                 why="origin facts for harvested rids only; popped on "
                     "shed/expire AND on retire (round 21 fix — "
                     "previously leaked one entry per "
                     "redispatched-then-completed rid)"),
            Decl("_pending_redispatch", "live",
                 why="harvested rids waiting out backoff"),
            Decl("_retired_pending", "fixed", cap=lambda r: 16384,
                 why="retired rids queued for end-of-step cleanup; "
                     "drained every step() / _drop_retired call"),
            Decl("_devices", "fixed", cap=lambda r: len(r._devices) or 1,
                 why="jax.devices() snapshot taken at construction"),
            Decl("_scheduler_kwargs", "fixed", cap=64,
                 why="constructor kwargs retained for revive()"),
            Decl("_params", "fixed", cap=None,
                 why="model parameter pytree shared by every replica; "
                     "immutable after construction (no bound to audit, "
                     "declared so the undeclared sweep knows it was "
                     "considered)"),
            Decl("handoff_lat.values", "fixed",
                 cap=lambda r: 2 * r.handoff_lat.window,
                 why="LatencySeries percentile window (round 21 cap)"),
        ]

    def census_owners(self):
        """The swept (name, object) set for ``StructCensus.register_many``
        — the router, each replica scheduler with its allocator/prefix
        index/host store/sentinel, and the shared telemetry objects."""
        owners = [("router", self)]
        for i, s in enumerate(self.replicas):
            owners.append((f"sched{i}", s))
            owners.append((f"alloc{i}", s.engine.allocator))
            if s.engine.prefix is not None:
                owners.append((f"prefix{i}", s.engine.prefix))
            owners.append((f"host_store{i}", s.host_store))
            if s.sentinel is not None:
                owners.append((f"sentinel{i}", s.sentinel))
            owners.append((f"prog_times{i}", s.prog_times))
        if self.reqtrace.enabled:
            owners.append(("reqtrace", self.reqtrace))
        if self.flightrec.enabled:
            owners.append(("flightrec", self.flightrec))
        if self.ledger.enabled:
            owners.append(("ledger", self.ledger))
        return owners

    def _note_failure(self, i: int, exc: BaseException,
                      site: str = "tick") -> None:
        """One failed tick (or handoff touch): suspect on the first,
        condemned at ``fail_threshold`` consecutive."""
        rec = self.health[i]
        if rec["state"] in ("dead", "draining"):
            return
        rec["consecutive"] += 1
        rec["failures"] += 1
        rec["last_error"] = f"{type(exc).__name__}: {exc}"
        logger.warning(
            "fleet health: replica %d %s failure %d/%d: %s", i, site,
            rec["consecutive"], self.fail_threshold, rec["last_error"],
        )
        if rec["consecutive"] >= self.fail_threshold:
            self._condemn(i, f"{site}-failures:{rec['consecutive']}")
        else:
            self._set_health(i, "suspect", rec["last_error"])

    def _condemn(self, i: int, reason: str) -> None:
        """Declare replica ``i`` dead: harvest every in-flight request
        from its ``Request`` records, tear its device state down
        leak-free (``Scheduler.abandon``; the dead replica may lose
        tokens, never blocks), invalidate its affinity entries, and
        queue the survivors' replays with deterministic backoff."""
        rec = self.health[i]
        if rec["state"] in ("dead", "draining"):
            return
        self._set_health(i, "draining", reason)
        s = self.replicas[i]
        harvested = s.harvest_requests()
        s.abandon()
        now = time.perf_counter()
        for req in harvested:
            rid = req.rid
            if rid not in self._origin:
                # captured exactly ONCE, at FIRST death: here
                # tokens[:orig_len] IS the true original prompt. After
                # a re-dispatch the request's tokens already embed
                # previously delivered output, so a second capture
                # would double-count it in the next replay.
                self._origin[rid] = {
                    "prompt": np.asarray(
                        req.tokens[:req.orig_len], dtype=np.int32
                    ).copy(),
                    "max_new": req.max_new_tokens,
                    "session": req.session,
                    "deadline": req.deadline,
                    "attempts": 0,
                }
            origin = self._origin[rid]
            self.placement.pop(rid, None)
            if req.deadline <= now:
                self._expire_request(rid, "replica-death")
                continue
            origin["attempts"] += 1
            rec["redispatched_away"] += 1
            if origin["attempts"] > self.redispatch_max_attempts:
                self._fail_request(
                    rid,
                    f"redispatch-attempts-exhausted:"
                    f"{self.redispatch_max_attempts}",
                )
                continue
            # deterministic bounded backoff: the rid seeds the jitter so
            # a chaos-matrix replay re-derives the same delays, and the
            # attempt index walks the exponential schedule
            delays = backoff_delays(
                retries=self.redispatch_max_attempts,
                base_delay=self.redispatch_base_delay_s, seed=rid,
            )
            delay = delays[min(origin["attempts"] - 1, len(delays) - 1)]
            self._pending_redispatch.append(
                {"rid": rid, "not_before": now + delay, "src": i}
            )
            if self.reqtrace.enabled:
                self.reqtrace.event(
                    rid, "redispatch_queued", src=i,
                    attempt=origin["attempts"],
                    delay_s=round(delay, 6),
                )
        # affinity entries pinned to the dead replica are invalid — a
        # returning session re-pins wherever the gate sends it (its
        # prefix blocks died with the pool anyway)
        for sess in [s_ for s_, r in self._affinity.items() if r == i]:
            del self._affinity[sess]
        if self.watchdog is not None:
            self.watchdog.unwatch(f"replica{i}")
        rec["deaths"] += 1
        rec["consecutive"] = 0
        self._set_health(i, "dead", reason)

    def _fail_request(self, rid: int, reason: str) -> None:
        """Attempt cap exhausted: shed ``rid`` with outcome=failed —
        the post-admission twin of the gate's shed (the client may have
        seen partial tokens; the stream simply never completes)."""
        self.failed[rid] = reason
        self._failed_total += 1
        self._trim_rejects()
        self._origin.pop(rid, None)
        if not self.retain_results:
            self._retired_pending.append(rid)
        self.flightrec.record("request_failed", rid=rid, reason=reason)
        if self.reqtrace.enabled:
            root = self.reqtrace.open_root(rid)
            self.reqtrace.end(root, outcome="failed", reason=reason)
        if self.metrics_log is not None:
            self.metrics_log.log(
                kind="request", rid=rid, replica_id=-1, rejected=True,
                reject_reason=reason, outcome="failed",
                new_tokens=len(self.results.get(rid, ())),
            )
        if self.on_retire is not None:
            self.on_retire(rid, "failed")

    def _expire_request(self, rid: int, where: str) -> None:
        """Deadline lapsed while the request sat in the router's own
        machinery (harvested, or waiting out backoff) — the router is
        an enforcement point just like the scheduler tick."""
        self._deadline_expired_redispatch += 1
        self._origin.pop(rid, None)
        if not self.retain_results:
            self._retired_pending.append(rid)
        self.flightrec.record("deadline", rid=rid, where=where)
        if self.reqtrace.enabled:
            root = self.reqtrace.open_root(rid)
            self.reqtrace.end(
                root, outcome="deadline", reason=f"expired-{where}"
            )
        if self.metrics_log is not None:
            self.metrics_log.log(
                kind="request", rid=rid, replica_id=-1, rejected=True,
                reject_reason=f"deadline-expired-{where}",
                outcome="deadline",
                new_tokens=len(self.results.get(rid, ())),
            )
        if self.on_retire is not None:
            self.on_retire(rid, "deadline")

    def _pump_redispatch(self) -> None:
        """Re-submit harvested requests to surviving entry replicas.
        The replay prompt is the ORIGINAL prompt plus every token the
        router already DELIVERED for the rid (``self.results`` is the
        authoritative client-visible stream — produced-but-uncollected
        tokens died with the replica and are regenerated), so the
        surviving stream stays append-consistent and the prefix cache
        absorbs most of the replay's prefill. Re-admission bypasses the
        SLO gate: the request was already admitted once — replica loss
        must not demote it to a sheddable newcomer."""
        if not self._pending_redispatch:
            return
        now = time.perf_counter()
        alive = self._alive(self.entry_group)
        still_waiting: List[dict] = []
        for entry in self._pending_redispatch:
            rid = entry["rid"]
            origin = self._origin.get(rid)
            if origin is None:  # failed/expired since it was queued
                continue
            if origin["deadline"] <= now:
                self._expire_request(rid, "redispatch-wait")
                continue
            if not alive or now < entry["not_before"]:
                # backoff not elapsed, or no survivor to take it —
                # hold (a later revive() drains this queue)
                still_waiting.append(entry)
                continue
            delivered = self.results.get(rid, [])
            remaining = origin["max_new"] - len(delivered)
            if remaining <= 0:
                # every budgeted token was already delivered before the
                # replica died mid-retire — the stream is complete
                if self.reqtrace.enabled:
                    root = self.reqtrace.open_root(rid)
                    self.reqtrace.end(root, outcome="complete",
                                      reason="redispatch-noop")
                self._origin.pop(rid, None)
                if not self.retain_results:
                    self._retired_pending.append(rid)
                if self.on_retire is not None:
                    self.on_retire(rid, "complete")
                continue
            prompt = origin["prompt"]
            if delivered:
                prompt = np.concatenate(
                    [prompt, np.asarray(delivered, dtype=np.int32)]
                )
            target = min(
                alive,
                key=lambda j: (len(self.replicas[j].resident)
                               + len(self.replicas[j].queue)),
            )
            self.replicas[target].submit(
                prompt, int(remaining), session=origin["session"],
                rid=rid, deadline=origin["deadline"],
            )
            self.placement[rid] = target
            self._redispatched += 1
            if origin["session"] is not None:
                # re-pin the session where its replayed prefix now lives
                self._affinity[origin["session"]] = target
                self._affinity.move_to_end(origin["session"])
            self.flightrec.record(
                "redispatch", rid=rid, src=entry["src"], dst=target,
                attempt=origin["attempts"],
                replayed=len(delivered),
            )
            if self.reqtrace.enabled:
                self.reqtrace.event(
                    rid, "redispatch", src=entry["src"], dst=target,
                    attempt=origin["attempts"],
                    replayed=len(delivered),
                )
        self._pending_redispatch = still_waiting

    def revive(self, i: int, *, warmup: bool = True,
               background: bool = False) -> None:
        """Re-admit a fresh replica at dead slot ``i``: a new
        scheduler/engine/pool with the old slot's exact geometry,
        device, and seed, warmed through the compile cache BEFORE the
        rejoining→healthy flip so its first real tick pays no compile
        (and survivors, untouched, never recompile — the registry
        fingerprint proof in the chaos tests)."""
        rec = self.health[i]
        if rec["state"] != "dead":
            raise RuntimeError(
                f"revive: replica {i} is {rec['state']}, not dead"
            )
        self._set_health(i, "rejoining", "revive")
        self.replicas[i] = self._make_replica(i)
        if warmup:
            self.replicas[i].warmup(background=background)
        rec["consecutive"] = 0
        rec["last_error"] = None
        if self.watchdog is not None:
            self.watchdog.watch(f"replica{i}")
        self._set_health(i, "healthy", "revived")

    # ---- routing ----

    def _group_metrics(self, group: List[int]) -> Dict[int, dict]:
        # gate_metrics == metrics() on the synchronous loop; under the
        # async loop it is the worker-refreshed snapshot + live cheap
        # counters, so per-submit routing stops paying the O(n log n)
        # percentile math on the critical path
        return {i: self.replicas[i].gate_metrics() for i in group}

    def submit(self, prompt: np.ndarray, max_new_tokens: int, *,
               session: Optional[int] = None,
               deadline_s: Optional[float] = None) -> int:
        """Route one request; returns its fleet rid. A shed request gets
        a rid too — ``rejected[rid]`` holds the reason and no tokens
        will ever stream for it (the explicit fast-reject contract).
        ``deadline_s`` is a relative latency budget: the gate sheds an
        already-expired one at admission, and the absolute instant it
        fixes travels on the ``Request`` through every replica hop —
        re-dispatch does NOT grant a fresh budget."""
        rid = self._next_rid
        self._next_rid += 1
        # dead/draining/rejoining replicas take no traffic: the gate
        # only ever sees alive entry replicas, and a fully-dead entry
        # group sheds explicitly instead of routing into a corpse
        alive = self._alive(self.entry_group)
        preferred = None
        if session is not None:
            preferred = self._affinity.get(session)
            if preferred is not None:
                self._affinity.move_to_end(session)  # LRU touch
            if preferred is not None and preferred not in alive:
                preferred = None  # pinned replica died; re-pin below
        if not alive:
            decision = Decision(SHED, -1, "fleet-unavailable")
        else:
            with self.ledger.host("admission/gate"):
                decision = self.gate.route(
                    self._group_metrics(alive), preferred,
                    deadline_s=deadline_s,
                )
        if decision.action == SHED and decision.reason == "deadline-expired":
            self._deadline_sheds += 1
        if self.reqtrace.enabled:
            # the gate decision opens the request's root span — the
            # first causal fact of its lifecycle (a shed closes it
            # right here: complete trace, outcome=shed)
            trace_decision(
                self.reqtrace, rid, decision, session=session,
                preferred=preferred,
                prompt_len=int(np.asarray(prompt).size),
            )
        if decision.action == SHED:
            self.rejected[rid] = decision.reason
            self._shed_total += 1
            self._trim_rejects()
            self.flightrec.record("shed", rid=rid, reason=decision.reason)
            if self.metrics_log is not None:
                self.metrics_log.log(
                    kind="request", rid=rid,
                    replica_id=(preferred if preferred is not None else -1),
                    rejected=True, reject_reason=decision.reason,
                    session=session,
                    prompt_len=int(np.asarray(prompt).size),
                    new_tokens=0,
                )
            return rid
        target = decision.replica
        if session is not None and session not in self._affinity:
            self._affinity[session] = target
            while len(self._affinity) > self.affinity_cap:
                self._affinity.popitem(last=False)
                self._affinity_evictions += 1
        if decision.action == SPILL:
            self._spilled += 1
            self.flightrec.record(
                "spill", rid=rid, to=target, reason=decision.reason
            )
        elif decision.action == PREEMPT:
            # the pressure rung: park one LRU chain on the target, then
            # queue this request in the capacity it frees. A victim can
            # vanish between the gate's metrics read and now — the
            # request still queues there (backpressure, not failure).
            victim = self.replicas[target].preempt_lru(
                reason=decision.reason or "pressure"
            )
            self._preempt_routes += 1
            self.flightrec.record(
                "preempt_route", rid=rid, to=target, victim=victim,
                reason=decision.reason,
            )
        self.replicas[target].submit(
            prompt, max_new_tokens, session=session,
            spilled=(decision.action == SPILL), rid=rid,
            deadline_s=deadline_s,
        )
        self.placement[rid] = target
        return rid

    # ---- the host loop ----

    def _pump_handoffs(self) -> None:
        """Move every ready request's KV blocks prefill→decode. Targets
        are tried least-loaded-first; a full decode fleet leaves the
        request parked (blocks intact on the prefill replica) for the
        next tick — the same queue-don't-crash contract as admission."""
        budget = (
            self.handoffs_per_tick
            if self.handoffs_per_tick is not None else float("inf")
        )
        order = sorted(
            self._alive(self.decode_group),
            key=lambda i: (len(self.replicas[i].resident),
                           len(self.replicas[i].queue)),
        )
        preempted_this_pump = False
        for pi in self._alive(self.entry_group):
            ps = self.replicas[pi]
            for rid in ps.ready_rids():
                if budget <= 0:
                    return
                try:
                    # serve.handoff_export fires inside export_chain; a
                    # crash here kills the SOURCE replica — its parked
                    # ready set (this rid included) harvests into the
                    # re-dispatch queue, nothing adopted yet
                    req, export = ps.peek_ready(rid)
                except Exception as e:  # noqa: BLE001 — fault boundary
                    self._note_failure(pi, e, site="handoff_export")
                    break
                t0 = time.perf_counter()
                adopted_by = None
                for di in order:
                    if self.health[di]["state"] not in _ROUTABLE:
                        continue  # condemned earlier in this same pump
                    try:
                        # serve.handoff_import fires inside import_chain
                        # before any fresh block lands; a crash kills
                        # the TARGET replica while the source's export
                        # stays valid (the PR 16 failure-safe contract)
                        # — the next candidate simply retries the adopt
                        if self.replicas[di].adopt(req, export):
                            adopted_by = di
                            break
                    except Exception as e:  # noqa: BLE001
                        self._note_failure(di, e, site="handoff_import")
                        continue
                if adopted_by is None:
                    # no decode capacity this tick. Under the pressure
                    # tier, park ONE idle decode chain (LRU) so next
                    # tick's pump can adopt — the handoff twin of the
                    # SLO gate's preempt rung: a prefill-complete
                    # request stalling on a full decode pool is the same
                    # over-commit the admission path preempts for. One
                    # victim per pump (anti-thrash); the request stays
                    # parked here, blocks intact, and retries.
                    if not preempted_this_pump:
                        for di in order:
                            if not self.replicas[di].offload:
                                continue
                            victim = self.replicas[di].preempt_lru(
                                reason="handoff-pressure"
                            )
                            if victim is not None:
                                preempted_this_pump = True
                                self._preempt_routes += 1
                                self.flightrec.record(
                                    "preempt_route", rid=rid, to=di,
                                    victim=victim,
                                    reason="handoff-pressure",
                                )
                                break
                    break
                ps.complete_handoff(rid)
                wall = time.perf_counter() - t0
                self.handoff_lat.observe(wall)
                self.placement[rid] = adopted_by
                self._handoff_count += 1
                if self.reqtrace.enabled:
                    # the handoff as a span of its own (backdated to the
                    # export), plus a flow link to the decode window it
                    # enabled on the other replica — peek/adopt/complete
                    # become visible parent→child structure in the trace
                    h = self.reqtrace.begin(
                        rid, "handoff", replica=pi, t=t0, src=pi,
                        dst=adopted_by, blocks=export.n_blocks,
                        bytes=ps.engine.chain_bytes(export.n_blocks),
                    )
                    self.reqtrace.end(h, wall_s=round(wall, 6))
                    self.reqtrace.link(rid, h, req.span_decode,
                                       "handoff")
                self.flightrec.record(
                    "handoff", rid=rid, src=pi, dst=adopted_by
                )
                budget -= 1

    def _run_tick(self, i: int) -> List[Tuple[int, int]]:
        """Tick replica ``i`` under the failure plane: heartbeat the
        watchdog, catch any exception escaping the tick (→ suspect /
        condemned), and re-check the tick's wall clock against
        ``tick_deadline_s`` — a tick that overran the deadline condemns
        its replica for ``hang`` even though it eventually returned
        (the injected-hang simulation of a wedged device loop). Tokens
        a hung tick DID flush are still delivered: they left the
        replica before it was declared dead, and dropping them would
        strand requests that retired during the hung tick."""
        s = self.replicas[i]
        toks: List[Tuple[int, int]] = []
        self._ticking = i
        if self.watchdog is not None:
            self.watchdog.beat(f"replica{i}")
        t0 = time.perf_counter()
        try:
            if self.async_host:
                toks.extend(s.collect_tick())
                s.dispatch_tick()
            else:
                toks.extend(s.step())
        except Exception as e:  # noqa: BLE001 — the fault boundary
            self._note_failure(i, e, site="tick")
        else:
            wall = time.perf_counter() - t0
            if (self.tick_deadline_s is not None
                    and wall >= self.tick_deadline_s):
                # deterministic hang condemnation: measured on the loop
                # itself, not the poller thread, so the chaos matrix
                # never races the watchdog's poll cadence
                self._condemn(i, f"tick-hang:{wall:.3f}s")
            else:
                self._note_success(i)
                if self.watchdog is not None:
                    self.watchdog.beat(f"replica{i}")
        finally:
            self._ticking = None
        return toks

    def step(self) -> List[Tuple[int, int]]:
        """One fleet tick. Synchronous loop: tick each replica fully —
        decode replicas first (their token sync stays clear of this
        tick's fresh prefill dispatches), then prefill/mixed replicas,
        then the handoff pump. Async loop (``async_host=True``):
        **dispatch-then-collect** — first COLLECT every replica's
        previous tick (lagged: those ticks have been in flight across
        the pump and all inter-step host work), then DISPATCH every
        replica's next tick back-to-back so every compiled program is
        enqueued before any of this step's host work runs, then the
        pump. Per replica the order collect(N−1) → dispatch(N) is the
        synchronous schedule, so greedy token streams are bit-identical
        between modes; only cross-replica interleaving (and the wall
        clock) changes."""
        if self._start_time is None:
            self._start_time = time.perf_counter()
        out: List[Tuple[int, int]] = []
        # harvested requests replay FIRST, so a request re-dispatched at
        # tick N starts prefilling at tick N (once its backoff elapses)
        # — no extra tick of dead air between death and recovery
        self._pump_redispatch()
        # note: interleaved collect/dispatch in the async loop — while
        # replica i's freshly dispatched tick N is in flight, the loop
        # is already collecting replica i+1's tick N−1 and building its
        # tick N, so every replica's dispatch-side host work overlaps
        # some OTHER replica's device work
        for i in self._alive(self.decode_group + self.entry_group):
            out.extend(self._run_tick(i))
        if self.decode_group:
            with self.ledger.host("handoff-pump"):
                self._pump_handoffs()
        for rid, tok in out:
            self.results.setdefault(rid, []).append(tok)
        self._drop_retired()
        self._tick += 1
        if self._tick % 16 == 0:  # sampled: metrics() per tick is waste
            self._recommend_peak = max(self._recommend_peak,
                                       self.recommend_replicas())
        return out

    @property
    def idle(self) -> bool:
        # Scheduler.idle counts parked and mid-swap requests as
        # in-flight work, so a drain never strands a preempted stream;
        # has_uncollected keeps the async loop stepping until every
        # in-flight tick's tokens have been collected AND delivered;
        # pending re-dispatches are in-flight work too — a fleet with a
        # harvested request waiting out its backoff is NOT idle
        return (
            all(s.idle and not s.has_uncollected for s in self.replicas)
            and not self._pending_redispatch
        )

    def drain(self, max_steps: int = 100_000) -> Dict[int, List[int]]:
        """Step until every replica is empty; returns ``{rid: [tokens]}``
        for every request that produced output (shed rids absent)."""
        for _ in range(max_steps):
            if self.idle:
                if self.host_pool is not None:
                    # barrier: offloaded JSONL/metric work settles with
                    # the drain, same as the synchronous loop's contract
                    for s in self.replicas:
                        s.flush_host_work()
                    self.host_pool.flush()
                if self.blocksan is not None:
                    # fleet quiesce: every replica's ledger must equal
                    # its allocator with no chains, swap windows, or
                    # handoff pins outstanding (the drain retired or
                    # adopted everything; index-retained blocks are
                    # legitimately live)
                    for s in self.replicas:
                        if s._san is not None:
                            s._san.verify_quiesce()
                return dict(self.results)
            self.step()
        # drain diagnostics (satellite, round 19): name the stuck rids
        # by replica and state instead of a bare step count — the first
        # question a wedged-fleet post-mortem asks
        stuck = {
            f"r{i}": s.stuck_rids()
            for i, s in enumerate(self.replicas) if not s.idle
        }
        pending = sorted(e["rid"] for e in self._pending_redispatch)
        raise RuntimeError(
            f"fleet drain did not converge within {max_steps} steps; "
            f"stuck rids by replica/state: {stuck}; "
            f"awaiting redispatch: {pending}"
        )

    def cancel(self, rid: int, reason: str = "client-cancel") -> bool:
        """Fleet cancellation: abort ``rid`` on whichever replica holds
        it (queued, resident, parked, mid-swap, or handoff-ready).
        Returns False when no replica knows the rid — already retired,
        shed, or never submitted; cancellation is idempotent."""
        return any(s.cancel(rid, reason=reason) for s in self.replicas)

    # ---- compile-cache integration ----

    def registries(self):
        """One ``compilecache.serving_registry`` per replica — the
        programs each replica can ever compile, enumerated on ITS
        mesh/device placement."""
        from pytorch_distributed_tpu.compilecache import serving_registry

        return [
            serving_registry(s.engine, extra=(f"replica={s.replica_id}",
                                              f"role={role}"))
            for s, role in zip(self.replicas, self.roles)
        ]

    def assert_registry_covers(self) -> None:
        """Fleet-wide coverage guard: every compiled program on every
        replica must have been predicted by that replica's registry."""
        for reg, s in zip(self.registries(), self.replicas):
            reg.assert_covers(s.engine.compiled_program_names())

    def warmup(self, background: bool = False) -> None:
        """Compile every replica's programs before traffic (decode
        replicas only ever need the decode tick, but uniform warmup
        keeps role changes free)."""
        for s in self.replicas:
            s.warmup(background=background)

    # ---- metrics ----

    def recommend_replicas(self) -> int:
        """The autoscaler hook (``fleet.admission.recommend_replicas``)
        over the ENTRY group's live metrics — decode replicas scale with
        prefill replicas, not independently, in this round."""
        return recommend_replicas(
            len(self.entry_group),
            list(self._group_metrics(self.entry_group).values()),
            self.gate,
        )

    def metrics(self) -> dict:
        """Fleet rollup: totals, shed/spill rates, fleet-wide latency
        percentiles (replica series concatenated — every request appears
        in exactly one replica's series), handoff stats, the autoscaler
        recommendation, and flat per-replica key summaries."""
        per = [s.metrics() for s in self.replicas]
        submitted = self._next_rid
        shed = self._shed_total
        placed = submitted - shed
        elapsed = (
            time.perf_counter() - self._start_time
            if self._start_time is not None else 0.0
        )
        out: dict = {
            "replicas": len(self.replicas),
            "disaggregated": self.disaggregated,
            "submitted": submitted,
            "shed": shed,
            "spilled": self._spilled,
            "shed_rate": shed / submitted if submitted else 0.0,
            "spill_rate": self._spilled / placed if placed else 0.0,
            "completed": sum(m["completed"] for m in per),
            "tokens_out": sum(m["tokens_out"] for m in per),
            "tokens_per_s": (
                sum(m["tokens_out"] for m in per) / elapsed
                if elapsed else 0.0
            ),
            "handoffs": self._handoff_count,
            # pressure tier rollup (round 13): fleet-wide preemptions,
            # restores, parked chains, and swap traffic — shed stays the
            # headline failure count these exist to zero out
            "preempt_routes": self._preempt_routes,
            "preempts": sum(m["preempts"] for m in per),
            "restores": sum(m["restores"] for m in per),
            "parked": sum(m["parked"] for m in per),
            "swap_bytes": sum(m["swap_bytes"] for m in per),
            "swap_aborts": sum(m["swap_aborts"] for m in per),
            "preempt_rate": (
                sum(m["preempts"] for m in per) / placed if placed else 0.0
            ),
            # prefix-cache rollup (round 17): fleet-wide hit rate over
            # per-replica lookups (each admission looks up exactly once
            # on its replica, so concatenating series is exact), the
            # sharing/COW/eviction totals, and the affinity table's LRU
            # accounting (the round-17 unbounded-growth fix)
            "prefix_lookups": sum(m["prefix_lookups"] for m in per),
            "prefix_hits": sum(m["prefix_hits"] for m in per),
            "prefix_hit_rate": (
                sum(m["prefix_hits"] for m in per)
                / max(sum(m["prefix_lookups"] for m in per), 1)
            ),
            "prefix_covered_tokens": sum(
                m["prefix_covered_tokens"] for m in per
            ),
            "admitted_prefill_tokens": sum(
                m["admitted_prefill_tokens"] for m in per
            ),
            "prefix_cow_copies": sum(m["prefix_cow_copies"] for m in per),
            "prefix_evictions": sum(m["prefix_evictions"] for m in per),
            "prefix_shared_blocks": sum(
                m["prefix_shared_blocks"] for m in per
            ),
            "affinity_sessions": len(self._affinity),
            "affinity_evictions": self._affinity_evictions,
            # round 21 retention plane: how many retired rids had their
            # results/placement entries dropped (0 in the default
            # keep-everything mode) and the live-request axis the
            # census audits against
            "results_dropped": self._results_dropped,
            "live_requests": self.live_requests(),
            "cancelled": sum(m["cancelled"] for m in per),
            # failure-plane rollup (round 19): health census, replica
            # deaths, re-dispatch traffic, and the deadline ledger —
            # "deadline_misses" are scheduler-tick expiries (the request
            # was running), "deadline_sheds" died at the gate, and
            # "deadline_expired_redispatch" lapsed inside the router's
            # own recovery machinery
            "replicas_healthy": sum(
                1 for h in self.health if h["state"] in _ROUTABLE
            ),
            "replica_deaths": sum(h["deaths"] for h in self.health),
            "redispatched": self._redispatched,
            "redispatch_pending": len(self._pending_redispatch),
            "failed": self._failed_total,
            "deadline_misses": sum(m["deadline_misses"] for m in per),
            "deadline_sheds": self._deadline_sheds,
            "deadline_expired_redispatch":
                self._deadline_expired_redispatch,
            **(self.blocksan.summary()
               if self.blocksan is not None else {}),
            "recommended_replicas": self.recommend_replicas(),
            "recommended_replicas_peak": self._recommend_peak,
            "async_host": self.async_host,
        }
        # host–device overlap rollup (round 16): per-replica device-busy
        # fractions PLUS the interval-union fraction. On a shared device
        # (the CPU simulation) a replica's dispatch→completion window
        # includes time queued behind the other replicas, so per-replica
        # fractions overlap and must not be summed — the union is true
        # device utilization, backend-marked (gather_ab_backend pattern)
        if self.ledger.enabled:
            from pytorch_distributed_tpu.telemetry.overlap import (
                fleet_busy_summary,
            )

            fb = fleet_busy_summary(self.ledger.snapshot())
            if fb["replicas"]:
                import jax

                out["device_busy_frac_union"] = fb["union_busy_frac"]
                out["device_busy_backend"] = jax.default_backend()
                for rep, frac in sorted(fb["replicas"].items()):
                    out[f"r{rep}_device_busy_frac"] = frac
        out.update(self.handoff_lat.summary("handoff"))
        for name in ("ttft", "token_lat", "queue_wait"):
            vals: List[float] = []
            for s in self.replicas:
                vals.extend(getattr(s, name).values)
            for q, v in percentiles(vals).items():
                out[f"{name}_{q}_s"] = v
        for i, m in enumerate(per):
            for k in ("tokens_out", "completed", "queue_depth",
                      "occupancy_mean", "goodput_frac", "preempts",
                      "restores"):
                out[f"r{i}_{k}"] = m[k]
            for k in ("ttft_p95_s", "queue_wait_p95_s"):
                if k in m:
                    out[f"r{i}_{k}"] = m[k]
            out[f"r{i}_role"] = self.roles[i]
            out[f"r{i}_health"] = self.health[i]["state"]
        return out

    def log_summary(self) -> None:
        """One ``kind="fleet_summary"`` JSONL record — the fleet half of
        what ``scripts/telemetry_report.py`` renders. Flushes the async
        host workers first so every offloaded per-request record lands
        before the summary that aggregates them."""
        if self.host_pool is not None:
            for s in self.replicas:
                s.flush_host_work()
            self.host_pool.flush()
        if self.metrics_log is not None:
            with self.ledger.host("jsonl-emit"):
                self.metrics_log.log(kind="fleet_summary", **self.metrics())
