"""Seeded traffic traces: bursty arrivals, heavy-tail lengths, replay.

Every serving measurement before round 10 drove equilibrium traffic —
all requests submitted up front, or Poisson at the closed-form
equilibrium rate (scripts/bench_serving.py). Real deployments are the
opposite regime: arrivals come in bursts (diurnal spikes, retry storms,
one tenant's batch job) and prompt/output lengths are heavy-tailed (a
p99 prompt many times the median — the shape vLLM/Orca traces show).
The fleet layer's whole value — spill, shed, disaggregation — only
shows under that traffic, so this module makes it a first-class,
reusable artifact:

- ``generate_trace``: a seeded arrival process — Poisson at
  ``base_rate`` with periodic burst episodes at ``base_rate *
  burst_rate_mult`` — with lognormal (heavy-tail) prompt and output
  lengths, assigned round-robin-free random session ids for affinity
  routing. Deterministic per seed.
- ``save_trace``/``load_trace``: one-line-per-request JSONL (plus a
  ``kind="trace_header"`` provenance line recording the generator
  parameters), so the same trace file feeds the fleet bench, the
  single-replica bench, ``recipes/serve_lm.py --trace``, and the CI
  fleet smoke.
- ``replay_trace``: the step-indexed driver. Arrival times are mapped
  to scheduler ticks via a NOMINAL tick length (``tick_s``) — offered
  load is then defined in the step domain (requests per tick), which is
  machine-independent: whether one contended CPU core or a TPU pod
  turns the crank, replica capacity per tick and the backlog a trace
  builds are identical. Wall-clock latencies (TTFT, token gaps) are
  still measured for real by the schedulers underneath.

Prompt TOKENS are not stored in the trace (only lengths): they are
regenerated deterministically per rid by ``prompt_for`` at replay time,
so a trace file is model-vocab-agnostic and stays small.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Callable, Iterable, Iterator, List, Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class TraceRequest:
    """One arrival: ``t`` seconds since trace start (nominal time),
    ``session`` the affinity key, lengths in tokens."""

    rid: int
    t: float
    session: int
    prompt_len: int
    max_new: int


def _heavy_tail(rng, median: float, sigma: float, lo: int,
                hi: Optional[int]) -> int:
    """Lognormal sample clipped to [lo, hi] — median ``median``, tail
    weight ``sigma`` (sigma 0.8 puts p99 at ~6x the median)."""
    v = rng.lognormal(mean=float(np.log(max(median, 1.0))), sigma=sigma)
    if hi is not None:
        v = min(v, hi)
    return int(max(lo, round(v)))


def generate_trace(
    *,
    seed: int = 0,
    duration_s: float = 60.0,
    base_rate: float = 2.0,
    burst_rate_mult: float = 4.0,
    burst_every_s: float = 10.0,
    burst_len_s: float = 2.0,
    sessions: int = 16,
    prompt_median: int = 32,
    prompt_sigma: float = 0.8,
    prompt_min: int = 4,
    prompt_max: Optional[int] = None,
    max_new_median: int = 12,
    max_new_sigma: float = 0.6,
    max_new_min: int = 2,
    max_new_max: Optional[int] = None,
) -> List[TraceRequest]:
    """Seeded bursty heavy-tail trace.

    Arrivals are a piecewise Poisson process: rate ``base_rate`` req/s,
    lifted to ``base_rate * burst_rate_mult`` inside burst episodes (the
    first ``burst_len_s`` of every ``burst_every_s`` window). Prompt and
    output lengths are lognormal with medians/sigmas as given. The same
    seed always yields the same trace.
    """
    if duration_s <= 0 or base_rate <= 0:
        raise ValueError("duration_s and base_rate must be positive")
    if burst_rate_mult < 1.0:
        raise ValueError("burst_rate_mult must be >= 1 (1 = no bursts)")
    rng = np.random.default_rng(seed)
    out: List[TraceRequest] = []
    t = 0.0
    while True:
        in_burst = (
            burst_len_s > 0 and (t % burst_every_s) < burst_len_s
        )
        rate = base_rate * (burst_rate_mult if in_burst else 1.0)
        t += float(rng.exponential(1.0 / rate))
        if t >= duration_s:
            return out
        out.append(TraceRequest(
            rid=len(out),
            t=t,
            session=int(rng.integers(sessions)),
            prompt_len=_heavy_tail(rng, prompt_median, prompt_sigma,
                                   prompt_min, prompt_max),
            max_new=_heavy_tail(rng, max_new_median, max_new_sigma,
                                max_new_min, max_new_max),
        ))


def iter_trace(
    *,
    seed: int = 0,
    duration_s: float = 60.0,
    base_rate: float = 2.0,
    burst_rate_mult: float = 4.0,
    burst_every_s: float = 10.0,
    burst_len_s: float = 2.0,
    sessions: int = 16,
    prompt_median: int = 32,
    prompt_sigma: float = 0.8,
    prompt_min: int = 4,
    prompt_max: Optional[int] = None,
    max_new_median: int = 12,
    max_new_sigma: float = 0.6,
    max_new_min: int = 2,
    max_new_max: Optional[int] = None,
    unique_sessions: bool = False,
) -> Iterator[TraceRequest]:
    """Streaming ``generate_trace`` — O(1) memory for 100k+-request soaks.

    Yields the SAME requests as ``generate_trace`` for the same
    parameters and seed (identical RNG draw order), without ever
    holding the trace in a list — the round-21 soak streams a
    million-user-shaped trace through this. ``unique_sessions=True``
    gives every request its own session id (``session == rid``): the
    one-query-per-user shape that stresses the affinity LRU hardest.
    The session draw is still consumed in that mode so lengths and
    arrival times stay seed-identical across both shapes.
    """
    if duration_s <= 0 or base_rate <= 0:
        raise ValueError("duration_s and base_rate must be positive")
    if burst_rate_mult < 1.0:
        raise ValueError("burst_rate_mult must be >= 1 (1 = no bursts)")
    rng = np.random.default_rng(seed)
    rid = 0
    t = 0.0
    while True:
        in_burst = (
            burst_len_s > 0 and (t % burst_every_s) < burst_len_s
        )
        rate = base_rate * (burst_rate_mult if in_burst else 1.0)
        t += float(rng.exponential(1.0 / rate))
        if t >= duration_s:
            return
        session = int(rng.integers(sessions))
        yield TraceRequest(
            rid=rid,
            t=t,
            session=rid if unique_sessions else session,
            prompt_len=_heavy_tail(rng, prompt_median, prompt_sigma,
                                   prompt_min, prompt_max),
            max_new=_heavy_tail(rng, max_new_median, max_new_sigma,
                                max_new_min, max_new_max),
        )
        rid += 1


def prompt_for(req: TraceRequest, vocab_size: int,
               seed: int = 0) -> np.ndarray:
    """The request's deterministic prompt tokens — a per-rid seeded
    stream, so replays of one trace agree token-for-token across
    processes and configurations sharing a vocab."""
    rng = np.random.default_rng((seed, req.rid))
    return rng.integers(1, vocab_size, size=req.prompt_len,
                        dtype=np.int64).astype(np.int32)


def shared_prefix_prompt_for(req: TraceRequest, vocab_size: int,
                             prefix_len: int, seed: int = 0,
                             n_prefixes: int = 1) -> np.ndarray:
    """System-prompt-heavy prompts (the round-17 prefix-cache trace):
    a ``prefix_len``-token SYSTEM PREFIX shared across requests —
    seeded independently of rids, chosen per ``session % n_prefixes``
    so multi-tenant shapes (one system prompt per tenant) are one knob
    away — followed by the request's own ``prompt_for`` tail. Total
    length is ``prefix_len + req.prompt_len``; callers clamp the trace
    accordingly."""
    if prefix_len < 1:
        raise ValueError(f"prefix_len must be >= 1, got {prefix_len}")
    pid = req.session % max(n_prefixes, 1)
    # the 3-int tuple seed cannot collide with prompt_for's (seed, rid)
    rng = np.random.default_rng((seed, 1_000_003, pid))
    prefix = rng.integers(1, vocab_size, size=prefix_len,
                          dtype=np.int64).astype(np.int32)
    return np.concatenate([prefix, prompt_for(req, vocab_size, seed=seed)])


# ---------------------------------------------------------------------------
# JSONL persistence
# ---------------------------------------------------------------------------


def save_trace(path: str, trace: List[TraceRequest], **header) -> None:
    """Write the reusable JSONL trace: a ``trace_header`` provenance
    line (generator params, free-form) then one ``kind="trace"`` line
    per request."""
    with open(path, "w") as f:
        f.write(json.dumps(
            {"kind": "trace_header", "requests": len(trace), **header}
        ) + "\n")
        for r in trace:
            f.write(json.dumps({
                "kind": "trace", "rid": r.rid, "t": round(r.t, 6),
                "session": r.session, "prompt_len": r.prompt_len,
                "max_new": r.max_new,
            }) + "\n")


def load_trace(path: str) -> List[TraceRequest]:
    """Read a trace JSONL (unknown kinds skipped, so traces can live in
    mixed telemetry streams); rids are re-checked to be unique."""
    out: List[TraceRequest] = []
    with open(path) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{i + 1}: not JSONL ({e})") from e
            if rec.get("kind") != "trace":
                continue
            out.append(TraceRequest(
                rid=int(rec["rid"]), t=float(rec["t"]),
                session=int(rec["session"]),
                prompt_len=int(rec["prompt_len"]),
                max_new=int(rec["max_new"]),
            ))
    if len({r.rid for r in out}) != len(out):
        raise ValueError(f"{path}: duplicate rids in trace")
    return out


def clamp_trace(trace: List[TraceRequest], max_seq_len: int,
                chunk: int) -> List[TraceRequest]:
    """Fit a trace to a serving config: clip each request so its
    chunk-padded prompt AND prompt+output fit ``max_seq_len`` (the
    scheduler's admission contract). Keeps arrival times and sessions —
    the traffic shape — while making any trace servable by any config."""
    out = []
    for r in trace:
        # leave at least one decode token's room below max_seq_len
        plen = max(1, min(r.prompt_len, (max_seq_len // chunk) * chunk,
                          max_seq_len - 1))
        mnew = max(1, min(r.max_new, max_seq_len - plen))
        out.append(dataclasses.replace(r, prompt_len=plen, max_new=mnew))
    return out


# ---------------------------------------------------------------------------
# replay
# ---------------------------------------------------------------------------


def replay_trace(
    trace: List[TraceRequest],
    submit: Callable[[TraceRequest], None],
    tick: Callable[[], None],
    is_idle: Callable[[], bool],
    *,
    tick_s: float = 1.0,
    max_steps: int = 1_000_000,
) -> int:
    """Drive any serving front-end through a trace in the step domain.

    Tick ``k`` first submits every request with ``t <= k * tick_s``,
    then calls ``tick()`` once; after the last arrival it keeps ticking
    until ``is_idle()``. ``tick_s`` is the NOMINAL tick — it converts
    trace time to tick indices and nothing else, so a trace offers the
    same per-tick load on any machine. Returns the number of ticks run.
    """
    if tick_s <= 0:
        raise ValueError("tick_s must be positive")
    trace = sorted(trace, key=lambda r: (r.t, r.rid))
    i = 0
    for step in range(max_steps):
        while i < len(trace) and trace[i].t <= step * tick_s:
            submit(trace[i])
            i += 1
        if i >= len(trace) and is_idle():
            return step
        tick()
    raise RuntimeError(
        f"replay did not converge within {max_steps} ticks "
        f"({len(trace) - i} arrivals pending)"
    )


def replay_stream(
    arrivals: Iterable[TraceRequest],
    submit: Callable[[TraceRequest], None],
    tick: Callable[[], None],
    is_idle: Callable[[], bool],
    *,
    tick_s: float = 1.0,
    max_steps: int = 10_000_000,
) -> int:
    """``replay_trace`` over an arrival ITERATOR — one-request
    lookahead, O(1) memory, for soaks whose trace never fits a list.

    Requires arrivals in non-decreasing ``t`` order (``iter_trace``
    yields strictly increasing times by construction). Same step-domain
    semantics as ``replay_trace``: tick ``k`` submits everything with
    ``t <= k * tick_s``, then ticks once; after the stream is drained
    it ticks until ``is_idle()``. Returns the number of ticks run.
    """
    if tick_s <= 0:
        raise ValueError("tick_s must be positive")
    it = iter(arrivals)
    pending = next(it, None)
    last_t = float("-inf")
    for step in range(max_steps):
        while pending is not None and pending.t <= step * tick_s:
            if pending.t < last_t:
                raise ValueError(
                    f"replay_stream needs time-ordered arrivals "
                    f"(t={pending.t} after t={last_t})")
            last_t = pending.t
            submit(pending)
            pending = next(it, None)
        if pending is None and is_idle():
            return step
        tick()
    raise RuntimeError(
        f"stream replay did not converge within {max_steps} ticks")
