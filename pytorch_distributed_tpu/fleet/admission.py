"""SLO-aware admission: route, spill, queue, or shed — plus the
autoscaler recommendation.

One replica's scheduler never rejects: it queues on OOM and lets queue
wait grow without bound (serving/scheduler.py — the right contract for a
single engine that cannot know whether more capacity exists). The fleet
layer CAN know: it holds every replica's live metrics — the exact
host-side TTFT/queue-wait percentiles and queue depths PR 4 taught
``Scheduler.metrics()`` to compute — so admission becomes a real
decision:

- **admit** to the session's affinity replica while it has SLO headroom;
- **spill** to the least-loaded cool replica when the affinity replica
  is hot (queue past ``spill_queue_depth``, or its live TTFT /
  queue-wait p95 past the configured SLO target) — the request trades
  prefix locality for latency. With the round-17 prefix cache on, that
  trade has a price tag — a spilled session re-prefills its shared
  prefix from token zero on a cold replica — so ``prefix_sticky_depth``
  lets a merely-queue-deep affinity replica keep its sessions a few
  requests longer before the spill;
- **queue** on the least-loaded replica when every replica is hot but
  none is past the shed bound — backpressure, not failure;
- **preempt** (round 13, the KV pressure tier) when every replica is
  past the shed bound but some replica still holds preemptible resident
  chains (``Scheduler.metrics()["preemptible"]`` — offload-enabled
  replicas report their eligible LRU victims): the router parks one
  idle chain there (swap-to-host or recompute, the measured
  cost-card choice) and queues the new request in its place — a cheap
  preemption instead of a user-visible reject;
- **shed** (explicit reject, reason in the per-request JSONL) only as
  the LAST resort: every replica past ``shed_queue_depth`` AND no
  preemptible capacity anywhere (and, for offload fleets, the pressure
  queue bound ``pressure_queue_depth`` exhausted) — admitting one more
  request could not possibly meet the SLO, and an honest fast reject
  beats a token stream that arrives after the client gave up.

Thresholds live in ``SLOConfig``; the defaults never shed (infinite
SLO, generous depths) so a bare two-replica router behaves like a pure
load balancer until the operator states a target.

``recommend_replicas`` is the autoscaler hook: scale up when every
replica is hot (the gate is about to queue/shed — more capacity is the
only fix), scale down when the fleet is demonstrably idle (mean slot
occupancy below ``low_utilization``, queues empty, and the goodput
ledger shows the wall is not being eaten by compile stalls that extra
replicas would re-pay). It RECOMMENDS — the driving loop owns replica
lifecycles (``Scheduler.drain_graceful`` is the safe scale-down path).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, NamedTuple, Optional, Sequence

#: Decision.action values
ADMIT, SPILL, SHED = "admit", "spill", "shed"
PREEMPT = "preempt"


@dataclasses.dataclass(frozen=True)
class SLOConfig:
    """Admission targets. Latency SLOs are wall-clock milliseconds
    checked against the replicas' LIVE p95 series; depth bounds are
    step-domain (deterministic under trace replay)."""

    ttft_p95_ms: float = float("inf")
    queue_wait_p95_ms: float = float("inf")
    #: prefer another replica once the affinity replica queues this deep
    spill_queue_depth: int = 4
    #: reject (with reason) once EVERY replica queues this deep
    shed_queue_depth: int = 64
    #: pressure backstop (round 13): when every replica is past the shed
    #: bound and no chain is preemptible RIGHT NOW, an offload-capable
    #: replica may still queue the request up to this depth (None = no
    #: bound — the zero-shed mode: pressure degrades to backpressure,
    #: never to rejects, as long as the pressure tier is on)
    pressure_queue_depth: Optional[int] = None
    #: prefix locality rung (round 17): when the session's affinity
    #: replica runs a prefix cache and is hot ONLY by queue depth (not
    #: draining, not an SLO/anomaly breach), stay sticky up to this
    #: deeper bound instead of spilling — the request's shared prefix is
    #: resident THERE, and a spill re-prefills it from token zero on a
    #: cold replica. None = off (spill at spill_queue_depth as before).
    prefix_sticky_depth: Optional[int] = None

    def __post_init__(self):
        if self.spill_queue_depth < 1:
            raise ValueError("spill_queue_depth must be >= 1")
        if self.shed_queue_depth < self.spill_queue_depth:
            raise ValueError(
                "shed_queue_depth must be >= spill_queue_depth "
                f"({self.shed_queue_depth} < {self.spill_queue_depth})"
            )
        if (self.pressure_queue_depth is not None
                and self.pressure_queue_depth < self.shed_queue_depth):
            raise ValueError(
                "pressure_queue_depth must be >= shed_queue_depth "
                f"({self.pressure_queue_depth} < {self.shed_queue_depth})"
            )
        if self.prefix_sticky_depth is not None and not (
            self.spill_queue_depth <= self.prefix_sticky_depth
            <= self.shed_queue_depth
        ):
            raise ValueError(
                "prefix_sticky_depth must lie in [spill_queue_depth, "
                f"shed_queue_depth], got {self.prefix_sticky_depth}"
            )


class Decision(NamedTuple):
    """One routing decision: ``action`` ∈ {admit, spill, preempt, shed},
    ``replica`` the target id (-1 on shed), ``reason`` why the affinity
    replica was left / the request was shed ('' on plain admits). A
    ``preempt`` decision means: park one LRU chain on ``replica`` (the
    router calls ``Scheduler.preempt_lru``) and queue the request
    there."""

    action: str
    replica: int
    reason: str


class SLOGate:
    """Stateless routing policy over live per-replica metrics dicts
    (``Scheduler.metrics()`` shape: ``queue_depth``, ``occupancy``,
    ``ttft_p95_s``, ``queue_wait_p95_s``, ``draining``)."""

    def __init__(self, slo: Optional[SLOConfig] = None):
        self.slo = slo if slo is not None else SLOConfig()

    # ---- per-replica predicates ----

    def hot(self, m: dict) -> Optional[str]:
        """The first SLO signal this replica violates, or None while it
        has headroom. Draining replicas are permanently hot — the gate
        routes around them during scale-down. A replica whose anomaly
        sentinel fired recently (``anomaly_recent``, ISSUE 8 — a
        tick-time/TTFT/queue-depth z-score excursion) is hot too: the
        gate spills around a replica that is *degrading* before its p95
        series has drifted far enough to breach the SLO itself."""
        if m.get("draining"):
            return "draining"
        if m["queue_depth"] >= self.slo.spill_queue_depth:
            return "queue_depth"
        if m.get("ttft_p95_s", 0.0) * 1e3 > self.slo.ttft_p95_ms:
            return "slo_ttft_p95"
        if m.get("queue_wait_p95_s", 0.0) * 1e3 > self.slo.queue_wait_p95_ms:
            return "slo_queue_wait_p95"
        if m.get("anomaly_recent"):
            return "anomaly"
        return None

    def overloaded(self, m: dict) -> bool:
        """Past the point where queueing is honest: one more request
        cannot meet the SLO no matter how the fleet routes it."""
        return (
            bool(m.get("draining"))
            or m["queue_depth"] >= self.slo.shed_queue_depth
        )

    @staticmethod
    def _load_key(m: dict):
        return (m["queue_depth"], m.get("occupancy", 0.0))

    # ---- the routing decision ----

    def route(self, metrics: Dict[int, dict],
              preferred: Optional[int] = None,
              deadline_s: Optional[float] = None) -> Decision:
        """Pick a replica for one request given each candidate replica's
        live metrics (``{replica_id: metrics_dict}``) and the session's
        affinity replica (None for session-less requests).

        ``deadline_s`` is the request's remaining deadline budget
        (seconds; None = no deadline). A request that arrives already
        expired — or will expire before any replica could plausibly
        admit it — is shed HERE with reason ``"deadline-expired"``:
        admission is the first enforcement point of the per-request
        deadline (round 19), and an honest immediate expiry beats
        queueing work the client has already abandoned."""
        if not metrics:
            raise ValueError("route() needs at least one candidate replica")
        if deadline_s is not None and deadline_s <= 0:
            return Decision(SHED, -1, "deadline-expired")
        hot = {i: self.hot(m) for i, m in metrics.items()}
        if preferred is not None and hot.get(preferred) is None:
            return Decision(ADMIT, preferred, "")
        # prefix locality rung (round 17): the affinity replica's index
        # holds this session's prefix — if it is hot ONLY by queue
        # depth, queue a bit deeper there rather than paying a cold
        # O(prompt) prefill elsewhere. Never overrides draining or a
        # live SLO/anomaly breach, and never exceeds the shed bound.
        if (
            self.slo.prefix_sticky_depth is not None
            and preferred is not None
            and hot.get(preferred) == "queue_depth"
            and metrics[preferred].get("prefix_cache")
            and metrics[preferred]["queue_depth"]
            < self.slo.prefix_sticky_depth
        ):
            return Decision(ADMIT, preferred, "prefix-sticky")
        by_load = sorted(metrics, key=lambda i: self._load_key(metrics[i]))
        cool = [i for i in by_load if hot[i] is None]
        if cool:
            action = SPILL if preferred is not None else ADMIT
            return Decision(action, cool[0], hot.get(preferred) or "")
        if all(self.overloaded(m) for m in metrics.values()):
            # the preempt rung (round 13): before shedding, park an
            # idle resident chain on the least-loaded replica that has
            # one — pressure degrades to a cheap preemption, shed stays
            # the last resort
            preemptable = [
                i for i in by_load
                if metrics[i].get("preemptible", 0) > 0
                and not metrics[i].get("draining")
            ]
            if preemptable:
                i = preemptable[0]
                return Decision(PREEMPT, i, hot[i] or "pressure")
            # nothing preemptible RIGHT NOW (protection windows, chains
            # mid-swap): an offload fleet still queues up to the
            # pressure bound — its parked work WILL free capacity
            pressured = [
                i for i in by_load
                if metrics[i].get("offload")
                and not metrics[i].get("draining")
                and (self.slo.pressure_queue_depth is None
                     or metrics[i]["queue_depth"]
                     < self.slo.pressure_queue_depth)
            ]
            if pressured:
                i = pressured[0]
                action = (
                    SPILL if preferred is not None and i != preferred
                    else ADMIT
                )
                return Decision(action, i, "pressure-queue")
            victim = preferred if preferred is not None else by_load[0]
            return Decision(SHED, -1, hot[victim] or "queue_depth")
        # every replica hot, none past the shed bound: queue on the
        # least-loaded that can still take work — backpressure
        for i in by_load:
            if not self.overloaded(metrics[i]):
                action = (
                    SPILL if preferred is not None and i != preferred
                    else ADMIT
                )
                return Decision(action, i, hot[i] or "")
        return Decision(SHED, -1, "queue_depth")  # unreachable guard


def trace_decision(reqtrace, rid: int, decision: Decision, *,
                   session: Optional[int] = None,
                   preferred: Optional[int] = None,
                   prompt_len: Optional[int] = None) -> int:
    """Open ``rid``'s lifecycle trace at the gate decision (round 14;
    ``telemetry.reqtrace``).

    The admission decision is the request's first causal fact — every
    later span (queue wait, prefill, handoff, decode, preemption) hangs
    under the root this opens. Each decision lands as one tagged
    ``gate`` event: ``action`` ∈ {admit, spill, preempt, shed} plus the
    reason the affinity replica was left (a queue-on-hot-fleet admit is
    an admit whose reason names the SLO signal — the "queue"
    backpressure rung). A shed CLOSES the root immediately: the trace
    is complete, outcome ``shed`` (``deadline`` when the shed rung was
    the gate's deadline check — the request expired at admission, not
    for capacity), and ``--assert-complete`` holds for rejected
    requests too. Returns the root span id."""
    root = reqtrace.open_root(
        rid, session=session, prompt_len=prompt_len
    )
    reqtrace.event(
        rid, "gate", parent=root,
        action=decision.action,
        target=decision.replica,
        reason=decision.reason or None,
        preferred=preferred,
    )
    if decision.action == SHED:
        outcome = (
            "deadline" if decision.reason == "deadline-expired"
            else "shed"
        )
        reqtrace.end(root, outcome=outcome, reason=decision.reason)
    return root


def recommend_replicas(
    n_now: int,
    metrics: Sequence[dict],
    gate: SLOGate,
    *,
    low_utilization: float = 0.25,
) -> int:
    """Replica-count recommendation from live fleet metrics.

    Scale **up** when every replica is hot (the gate has nowhere cool
    left to route — more capacity is the only lever). Scale **down**
    when the fleet is provably idle: mean slot occupancy under
    ``low_utilization``, all queues empty, and mean ledger goodput
    (non-compile wall fraction) above 0.5 — a compile-bound fleet is
    warming up, not idle, and shrinking it would re-pay the warmup.
    Otherwise hold. Never recommends below 1.
    """
    if not metrics:
        return n_now
    if all(gate.hot(m) is not None for m in metrics):
        return n_now + 1
    occ = sum(m.get("occupancy_mean", 0.0) for m in metrics) / len(metrics)
    goodput = sum(m.get("goodput_frac", 1.0) for m in metrics) / len(metrics)
    if (
        n_now > 1
        and occ < low_utilization
        and goodput > 0.5
        and all(m["queue_depth"] == 0 for m in metrics)
    ):
        return n_now - 1
    return n_now
