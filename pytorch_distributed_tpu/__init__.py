"""pytorch_distributed_tpu — a TPU-native distributed training framework.

A ground-up JAX/XLA re-design of the capability surface of
HFAiLab/pytorch_distributed (ResNet-50/ImageNet data-parallel training,
single chip → multi-host pod):

- ``models``   — ResNet family in flax (ref: torchvision.models.resnet50,
  ``resnet_single_gpu.py:83``).
- ``ops``      — loss / metrics / optimizer / LR schedule / precision policy
  (ref: ``torch.optim.SGD`` + ``StepLR`` + ``nn.CrossEntropyLoss``,
  ``resnet_single_gpu.py:107-109``; AMP ``resnet_ddp_apex.py:27-33``).
- ``parallel`` — device mesh, ``jax.distributed`` rendezvous, SPMD data
  parallelism over ICI/DCN (ref: NCCL process group + DDP,
  ``restnet_ddp.py:94-99``).
- ``data``     — packed-record dataset (ffrecord-style, C++ reader core),
  DistributedSampler semantics, host→device pipeline (ref: ``hfai.datasets``,
  ``restnet_ddp.py:107-119``).
- ``train``    — one SPMD trainer serving all four reference recipes, with
  suspend/checkpoint/resume (ref: ``restnet_ddp.py:36-47,127-132``).
- ``utils``    — env manifest pinning (ref: ``hf_env.set_env``), logging,
  profiling.
- ``telemetry`` — the observability runtime: sync-free device metrics
  ring, host span tracing, goodput ledger, latency percentiles (the
  reference has only ``time.time()`` prints; ANALYSIS.md
  "Observability & goodput").

The reference's four scripts differ only in how replicas communicate; here
that difference collapses into sharding specs on one trainer (SURVEY.md §7).
"""

__version__ = "0.1.0"

from pytorch_distributed_tpu.utils.env import set_env

__all__ = ["set_env", "__version__"]
