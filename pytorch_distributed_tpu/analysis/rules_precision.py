"""Rule: precision-cast — dtype policy lives in ops/precision.py, not inline.

The mixed-precision contract (params f32, compute bf16, outputs f32) is
owned by ``ops.precision.Policy``; an inline ``.astype(jnp.bfloat16)``
inside an op silently overrides the policy for every caller — including
the fp32 baseline recipes that exist to measure bf16 against. This rule
flags literal float-dtype casts in modules under ``ops/`` (the policy's
jurisdiction), except in ``ops/precision.py`` itself.

Intentional sites — fp32 accumulators inside flash/ring kernels, loss
upcasts required for numerics — stay, with either an inline
``# jaxlint: disable=precision-cast -- <why>`` or an entry in the lint
baseline (``scripts/jaxlint_baseline.json``); either way the reason is
recorded next to the cast instead of living in someone's head.

Flagged forms: ``x.astype(jnp.float32)``, ``x.astype(np.bfloat16)``,
``x.astype("float32")`` and ``jnp.asarray(x, jnp.bfloat16)`` /
``jnp.array(x, dtype="float32")``. Policy-driven casts
(``x.astype(self.compute_dtype)``, ``x.astype(q.dtype)``) are the point
of the rule and never flagged.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from pytorch_distributed_tpu.analysis._astutil import dotted, get_kwarg
from pytorch_distributed_tpu.analysis.core import (
    Finding,
    LintContext,
    ParsedModule,
    RuleInfo,
)

RULES = [
    RuleInfo(
        "precision-cast", "warning",
        "literal f32/bf16 cast in ops/ outside ops/precision.py policy "
        "helpers",
        "The mixed-precision contract (params f32, compute bf16, outputs "
        "f32) is owned by ops.precision.Policy; an inline "
        ".astype(jnp.bfloat16) inside an op silently overrides the "
        "policy for every caller — including the fp32 baseline recipes "
        "that exist to measure bf16 against. Intentional sites (fp32 "
        "kernel accumulators, loss upcasts required for numerics) stay, "
        "with an inline suppression or a baseline entry — either way "
        "the reason is recorded next to the cast. Policy-driven casts "
        "(x.astype(self.compute_dtype), x.astype(q.dtype)) are the "
        "point of the rule and never flagged.",
    ),
]

_POLICY_DTYPES = {"float32", "bfloat16", "float16"}
_SCOPE_DIR = "ops/"
_EXEMPT_BASENAME = "precision.py"


def _literal_dtype(node: ast.expr) -> Optional[str]:
    """'float32' for jnp.float32 / np.bfloat16 / "float32" literals."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value if node.value in _POLICY_DTYPES else None
    d = dotted(node)
    if d is None:
        return None
    tail = d.rsplit(".", 1)[-1]
    return tail if tail in _POLICY_DTYPES else None


def check_precision_casts(mod: ParsedModule, ctx: LintContext) -> List[Finding]:
    path = mod.path
    if _SCOPE_DIR not in path or path.endswith("/" + _EXEMPT_BASENAME):
        return []
    findings: List[Finding] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        dt = None
        form = None
        if isinstance(f, ast.Attribute) and f.attr == "astype" and node.args:
            dt = _literal_dtype(node.args[0])
            form = "astype"
        elif isinstance(f, ast.Attribute) and f.attr in ("asarray", "array"):
            arg = get_kwarg(node, "dtype")
            if arg is None and len(node.args) > 1:
                arg = node.args[1]
            if arg is not None:
                dt = _literal_dtype(arg)
                form = f.attr
        if dt is None:
            continue
        direction = "upcast to" if dt == "float32" else "downcast to"
        findings.append(Finding(
            "precision-cast", "warning", path, node.lineno,
            f"literal {direction} {dt} via .{form}() outside "
            f"ops/precision.py's Policy helpers — route dtype decisions "
            f"through the policy (or record why not: "
            f"'# jaxlint: disable=precision-cast -- <reason>')",
        ))
    return findings


CHECK = check_precision_casts
CROSS_MODULE = False
