"""Rule: partition-coverage — every shardable param is claimed by a rule.

The TP/EP/vocab partition tables in ``train/lm.py`` are path-regex lists;
a renamed flax module or a typo'd pattern makes a parameter silently fall
through ``match_partition_rules`` to replicated — correct math, quietly
losing the memory/bandwidth the rule existed to save. This check builds
REAL parameter trees (``jax.eval_shape`` over probe configs — no device
memory, no mesh needed) and cross-checks them against the rule tables:

- a leaf with >= ``min_elems`` elements and >= 2 dims that no rule claims
  and no allowlist entry covers -> finding (fell through to replicated);
- a rule pattern that matches no parameter in ANY probe config -> finding
  (dead rule: it guards nothing, usually a drifted path).

Probe configs cover both attention parameterizations (fused MHA qkv vs
GQA q/kv), MoE expert placement and the vocab-parallel head, so every
rule in the table is exercised by at least one tree.

Unlike the AST rules this needs a live jax/flax; the CLI degrades to a
skip (with a notice) when the import fails.
"""

from __future__ import annotations

import re
from typing import List, Optional, Sequence, Tuple

from pytorch_distributed_tpu.analysis.core import Finding

# Parameters that are REPLICATED BY DESIGN: norm scales/offsets and the
# learned position table are small and read by every shard every step —
# sharding them trades a broadcast for an all_gather and wins nothing.
REPLICATED_BY_DESIGN = (
    r"(^|/)ln[^/]*/",      # layernorms (ln_1, ln_2, ln_f)
    r"(^|/)norm[^/]*/",
    r"(^|/)wpe/",          # learned positions
    r"/bias$",
    r"/scale$",
)


def _probe_trees():
    """[(label, config, params shape tree)] for the coverage probes."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.models.transformer import (
        TransformerLM,
        tiny_config,
    )

    # Shapes are GLOBAL and identical across parallel layouts, so the probe
    # initializes through the same dense twin create_lm_state uses.
    probes = [
        (
            "mha+moe+vocab_parallel",
            tiny_config(
                model_axis="model", tp_size=2, vocab_parallel=True,
                n_experts=2, expert_axis="data", ep_size=2,
            ),
        ),
        (
            "gqa",
            tiny_config(model_axis="model", tp_size=2, num_kv_heads=2),
        ),
    ]
    out = []
    for label, cfg in probes:
        import dataclasses

        init_cfg = dataclasses.replace(
            cfg, attention="dense", model_axis=None, tp_size=1,
            expert_axis=None, ep_size=1,
        )
        model = TransformerLM(init_cfg)
        shapes = jax.eval_shape(
            lambda m=model: m.init(
                jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
            )
        )["params"]
        out.append((label, cfg, shapes))
    return out


def check_partition_coverage(
    rules: Optional[Sequence[Tuple[str, object]]] = None,
    min_elems: int = 256,
    allow_replicated: Sequence[str] = REPLICATED_BY_DESIGN,
) -> List[Finding]:
    """Cross-check the LM partition tables against real param trees.

    ``rules``: override the full rule list (tests); default derives the
    per-probe list exactly the way ``lm_state_specs`` does
    (TRANSFORMER_TP_RULES + MoE + vocab rules per config).
    """
    import jax

    from pytorch_distributed_tpu.parallel.tensor import path_str
    from pytorch_distributed_tpu.train import lm as lm_mod

    rule_file = "pytorch_distributed_tpu/train/lm.py"
    findings: List[Finding] = []
    matched_patterns = set()
    all_patterns = []

    for label, cfg, shapes in _probe_trees():
        if rules is None:
            probe_rules = (
                lm_mod.TRANSFORMER_TP_RULES
                + lm_mod._moe_rules(cfg)
                + lm_mod._vocab_rules(cfg)
            )
        else:
            probe_rules = tuple(rules)
        for pattern, _spec in probe_rules:
            if pattern not in all_patterns:
                all_patterns.append(pattern)
        leaves = jax.tree_util.tree_flatten_with_path(shapes)[0]
        for path, leaf in leaves:
            name = path_str(path)
            shape = tuple(getattr(leaf, "shape", ()))
            hit = next(
                (p for p, _s in probe_rules if re.search(p, name)), None
            )
            if hit is not None:
                matched_patterns.add(hit)
                continue
            size = 1
            for d in shape:
                size *= d
            if len(shape) < 2 or size < min_elems:
                continue
            if any(re.search(a, name) for a in allow_replicated):
                continue
            findings.append(Finding(
                "partition-coverage", "error", rule_file, 0,
                f"[{label}] parameter {name!r} {shape} matches no partition "
                f"rule and falls through to replicated — add a rule or an "
                f"explicit REPLICATED_BY_DESIGN entry",
            ))

    for pattern in all_patterns:
        if pattern not in matched_patterns:
            findings.append(Finding(
                "partition-coverage", "error", rule_file, 0,
                f"partition rule {pattern!r} matches no parameter in any "
                f"probe config — dead rule (drifted module path?)",
            ))
    return findings
