"""SARIF 2.1.0 emission for jaxlint findings.

SARIF (Static Analysis Results Interchange Format) is the lingua franca
of CI annotation surfaces — GitHub code scanning, GitLab SAST, VS Code's
SARIF viewer all ingest it directly, so one artifact turns jaxlint
findings into inline PR annotations with zero glue code. The emitter
maps:

- each catalogue rule -> ``tool.driver.rules[]`` (id, short/full
  description, default severity level);
- each finding -> ``results[]`` with the repo-relative artifact
  location, 1-based region, and the finding's stable content-derived
  fingerprint under ``partialFingerprints`` — the key CI services use
  to track a finding across commits even as line numbers shift (the
  same content-not-line-number contract as the text baseline).

Pure stdlib, no jax; validated structurally by tests/test_jaxlint_v2.py.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence

from pytorch_distributed_tpu.analysis.core import (
    Finding,
    RuleInfo,
    rule_catalog,
)

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)
_TOOL_VERSION = "2.0.0"

_LEVELS = {"error": "error", "warning": "warning"}


def _rule_descriptor(info: RuleInfo) -> dict:
    return {
        "id": info.rule,
        "shortDescription": {"text": info.short},
        "fullDescription": {"text": info.explain},
        "defaultConfiguration": {
            "level": _LEVELS.get(info.severity, "warning")
        },
        "helpUri": (
            "https://example.invalid/jaxlint#" + info.rule
        ),
    }


def to_sarif(
    findings: Sequence[Finding],
    baselined: Sequence[Finding] = (),
    catalog: Optional[Sequence[RuleInfo]] = None,
) -> dict:
    """One SARIF run. ``findings`` become normal results; ``baselined``
    ones are included with ``baselineState: "unchanged"`` so a CI viewer
    shows the full picture while its gate keys only on new results."""
    catalog = list(catalog) if catalog is not None else rule_catalog()
    rules = [_rule_descriptor(r) for r in catalog]
    index: Dict[str, int] = {r["id"]: i for i, r in enumerate(rules)}

    def result(f: Finding, baseline_state: Optional[str]) -> dict:
        out = {
            "ruleId": f.rule,
            "level": _LEVELS.get(f.severity, "warning"),
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f.path,
                        "uriBaseId": "SRCROOT",
                    },
                    "region": {"startLine": max(f.line, 1)},
                },
            }],
        }
        if f.rule in index:
            out["ruleIndex"] = index[f.rule]
        if f.fingerprint:
            out["partialFingerprints"] = {
                "jaxlintFingerprint/v1": f.fingerprint
            }
        if baseline_state is not None:
            out["baselineState"] = baseline_state
        return out

    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {
                "driver": {
                    "name": "jaxlint",
                    "version": _TOOL_VERSION,
                    "informationUri": "ANALYSIS.md",
                    "rules": rules,
                },
            },
            "originalUriBaseIds": {
                "SRCROOT": {"description": {"text": "repository root"}},
            },
            "results": (
                [result(f, None) for f in findings]
                + [result(f, "unchanged") for f in baselined]
            ),
        }],
    }


def write_sarif(
    path: str,
    findings: Sequence[Finding],
    baselined: Sequence[Finding] = (),
) -> None:
    doc = to_sarif(findings, baselined)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=False)
        f.write("\n")
