"""Rule family: lifecycle — KV block ownership and span discipline.

The runtime half of blocksan (``analysis/blocksan.py``) proves a TRACE
leak-free; this family proves properties of the CODE: every path out of
a function that acquired pool blocks either commits them to a table or
frees them, nobody touches the allocator's private books, and swap-span
open/close calls balance across outcome paths. The three rules target
the exact bug shapes the sanitizer catches at runtime — so a violation
the kill matrix would need a fault injection to surface is flagged at
lint time instead.

- ``lifecycle-alloc-leak`` (error): a function assigns the result of an
  allocator acquire (``.alloc(``, ``.alloc_mixed(``, ``._alloc_evict(``)
  and a ``raise`` or early ``return`` is lexically reachable after it,
  before the chain is committed to a block table row or freed. The OOM
  idiom — ``if chain is None: return ...`` — is recognized as clean
  (nothing was allocated on that path), as is a raise preceded by a
  ``.free(`` call (the try/except release shape ``import_chain`` uses),
  and returning the chain itself (the hand-off idiom ``_alloc_evict``
  uses).
- ``lifecycle-refcount-outside-allocator`` (error): writes to the
  allocator's private books (``._refs``/``._free``/``._chains``/
  ``._states``) or ``.incref(``/``.decref(`` calls outside
  ``serving/kv_pool.py``. The allocator's invariants — all-or-nothing
  alloc, loud double-free, swap-window pinning, the sanitizer hooks —
  hold only when every mutation flows through its API; a stray
  ``allocator._refs[b] += 1`` is invisible to all of them.
- ``lifecycle-span-imbalance`` (warning): swap-span open calls
  (``.set_state(``, ``.swap_out_begin(``) without a matching close
  (``.clear_state(``, ``.swap_out_finish(``) in the same function —
  either no close on ANY path, or a ``raise`` after the open with no
  close lexically between. Cross-function window protocols (the
  scheduler opens in ``preempt`` and closes in ``_finalize_swaps`` next
  tick) are real and deliberate — suppress inline with the reason, so
  the protocol is recorded next to the open it justifies.

- ``lifecycle-fault-site-untested`` (error): a ``fault_point("serve.*")``
  literal in scanned code whose site string never appears in the chaos
  matrix (``tests/test_chaos_matrix.py``). A serve-side fault site that
  no chaos scenario exercises is dead armor: the failure plane's
  recovery guarantees (harvest, re-dispatch, deadline expiry) are only
  as real as the grid that proves them, so every new site must land
  with a matrix entry. Missing chaos file → every serve site flags.

Boundaries (documented in ANALYSIS.md): the analysis is lexical within
one function — acquire/release pairs split across functions need a
suppression stating the protocol; "commit" means a store into a
``.tables``-named subscript, so an engine committing through a helper
would need its commit recognized the same way; aliasing (``a = self
.allocator; a._refs[...]``) is visible, but re-exporting the books
through another name is not. The fault-site rule reads the chaos file
as TEXT (a substring probe for the site literal), not as a parsed
module — ``run_lint`` scans only the paths it is given, and tests are
deliberately outside that set; the rule stays ``CROSS_MODULE=False``
because the probe needs no other scanned module, only the repo layout.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Tuple

from pytorch_distributed_tpu.analysis.core import (
    Finding,
    LintContext,
    ParsedModule,
    RuleInfo,
)

RULES = [
    RuleInfo(
        "lifecycle-alloc-leak", "error",
        "allocated block chain can escape through a raise/early return "
        "before table commit or free",
        "A function assigns the result of a pool acquire — .alloc(), "
        ".alloc_mixed(), ._alloc_evict() — and then a raise statement or "
        "an early return is lexically reachable before the chain is "
        "committed to a block-table row or freed. On that edge the "
        "blocks are live in the allocator but referenced by nothing the "
        "scheduler tracks: a permanent capacity leak that surfaces only "
        "as mystery pool pressure (blocksan reports it as "
        "leak-at-retire, but only on a run that actually takes the "
        "edge). Guard the window with try/except that frees the chain "
        "and re-raises (the import_chain shape), or commit before "
        "raising. The OOM idiom `if chain is None: return ...` is "
        "recognized as clean — nothing was allocated on that path — and "
        "so is returning the chain itself to a caller that owns it.",
    ),
    RuleInfo(
        "lifecycle-refcount-outside-allocator", "error",
        "allocator private books mutated (or incref/decref called) "
        "outside serving/kv_pool.py",
        "The BlockAllocator's invariants — all-or-nothing alloc_mixed, "
        "loud double-free, swap-window pinning, the blocksan shadow "
        "hooks — hold only when every refcount and free-list mutation "
        "flows through its API from within serving/kv_pool.py (the "
        "PrefixIndex, its one sanctioned sharer, lives there). A write "
        "to ._refs/._free/._chains/._states from anywhere else, or an "
        ".incref()/.decref() call outside that module, bypasses the "
        "sanitizer hooks and the allocator's own checks: the shadow "
        "ledger and the books silently diverge, and the next "
        "verify_quiesce blames code that was innocent. Route the "
        "mutation through alloc_mixed/free/set_state, or add the "
        "operation to the allocator's API surface.",
    ),
    RuleInfo(
        "lifecycle-span-imbalance", "warning",
        "swap span opened (.set_state/.swap_out_begin) without a close "
        "on every path in the function",
        "A function opens a swap window — .set_state() or "
        ".swap_out_begin() — and either never closes it (.clear_state/"
        ".swap_out_finish) anywhere in its body, or a raise after the "
        "open can escape with no close lexically between. An open "
        "window pins the chain: the allocator refuses to free it, so an "
        "escaped window turns every later retire/drain of that owner "
        "into a loud failure (or, caught carelessly, a leak). Close in "
        "a try/finally or on the except edge (the swap_out_begin "
        "shape). Deliberate cross-function protocols — open here, close "
        "in the finalize step next tick — are the one sanctioned "
        "imbalance: suppress inline with the reason, so the protocol "
        "is recorded at the open site.",
    ),
    RuleInfo(
        "lifecycle-fault-site-untested", "error",
        "serve-side fault_point site has no chaos-matrix entry in "
        "tests/test_chaos_matrix.py",
        "A fault_point(\"serve.*\") call whose site string appears "
        "nowhere in tests/test_chaos_matrix.py. The serve fault sites "
        "exist so the chaos matrix can kill a replica at every "
        "dispatch/collect/handoff boundary and prove the failure "
        "plane's guarantees — every request finishes, sheds, or "
        "expires; blocks never leak; span trees close. A site without "
        "a matrix entry is untested armor: the injection point ships, "
        "but nothing ever proves recovery from a fault there. Add a "
        "scenario (or extend the parametrized grid) that injects at "
        "the new site; if the chaos file itself is missing, every "
        "serve site flags until it exists. The probe is textual by "
        "design — naming the site string in the test file is the "
        "contract.",
    ),
]

_ACQUIRE_ATTRS = {"alloc", "alloc_mixed", "_alloc_evict"}
_PRIVATE_BOOKS = {"_refs", "_free", "_chains", "_states"}
_REF_CALLS = {"incref", "decref"}
_SPAN_OPENS = {"set_state", "swap_out_begin"}
_SPAN_CLOSES = {"clear_state", "swap_out_finish"}

#: the one module sanctioned to touch the private books and refcounts
_ALLOCATOR_MODULE = "serving/kv_pool.py"


def _call_attr(node: ast.AST) -> Optional[str]:
    """Attribute name of a method-style call (``x.y(...)`` -> ``y``)."""
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
        return node.func.attr
    return None


def _walk_no_nested(fn: ast.AST):
    """Walk a function body without descending into nested function/
    class definitions (their paths are not this function's paths)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


# ---- lifecycle-alloc-leak --------------------------------------------------


def _is_oom_guard_return(ret: ast.Return, fn: ast.FunctionDef,
                         chain_var: Optional[str]) -> bool:
    """True for the deterministic-OOM idiom: a return inside an
    ``if <chain> is None:`` (or ``if not <chain>:``) block — nothing was
    allocated on that path, so leaving is clean."""
    if chain_var is None:
        return False
    for node in _walk_no_nested(fn):
        if not isinstance(node, ast.If):
            continue
        test = node.test
        guarded = False
        if (
            isinstance(test, ast.Compare)
            and isinstance(test.left, ast.Name)
            and test.left.id == chain_var
            and len(test.ops) == 1
            and isinstance(test.ops[0], (ast.Is, ast.Eq))
            and isinstance(test.comparators[0], ast.Constant)
            and test.comparators[0].value is None
        ):
            guarded = True
        elif (
            isinstance(test, ast.UnaryOp)
            and isinstance(test.op, ast.Not)
            and isinstance(test.operand, ast.Name)
            and test.operand.id == chain_var
        ):
            guarded = True
        if guarded and any(n is ret for n in ast.walk(node)):
            return True
    return False


def _check_alloc_leak(fn: ast.FunctionDef, mod: ParsedModule,
                      findings: List[Finding]) -> None:
    acquires: List[Tuple[int, Optional[str]]] = []  # (line, chain var)
    for node in _walk_no_nested(fn):
        if isinstance(node, ast.Assign) and _call_attr(
                node.value) in _ACQUIRE_ATTRS:
            var = (
                node.targets[0].id
                if len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name) else None
            )
            acquires.append((node.lineno, var))
    if not acquires:
        return
    commit_lines = []    # table-row stores: self.tables[...] = ...
    free_lines = []      # .free(...) / .release(...) calls
    for node in _walk_no_nested(fn):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            for t in targets:
                if (
                    isinstance(t, ast.Subscript)
                    and isinstance(t.value, ast.Attribute)
                    and t.value.attr == "tables"
                ):
                    commit_lines.append(node.lineno)
        attr = _call_attr(node)
        if attr in ("free", "release"):
            free_lines.append(node.lineno)
    first_commit = min(commit_lines) if commit_lines else None

    for alloc_line, chain_var in acquires:
        for node in _walk_no_nested(fn):
            line = getattr(node, "lineno", 0)
            if line <= alloc_line:
                continue
            if first_commit is not None and line > first_commit:
                continue
            if isinstance(node, ast.Raise):
                # a free before this raise (the except-release shape)
                # hands the blocks back before the edge escapes
                if any(alloc_line < f < line for f in free_lines):
                    continue
                findings.append(Finding(
                    "lifecycle-alloc-leak", "error", mod.path, line,
                    f"{fn.name} allocates a block chain at line "
                    f"{alloc_line} but this raise can escape before the "
                    f"chain is committed to a table row or freed — the "
                    f"blocks leak; free in a try/except and re-raise "
                    f"(the import_chain shape), or record why the edge "
                    f"is unreachable",
                ))
            elif isinstance(node, ast.Return):
                if _is_oom_guard_return(node, fn, chain_var):
                    continue  # the OOM idiom: nothing was allocated
                if (
                    chain_var is not None
                    and isinstance(node.value, ast.Name)
                    and node.value.id == chain_var
                ):
                    continue  # chain handed to the caller, who owns it
                if any(alloc_line < f < line for f in free_lines):
                    continue
                findings.append(Finding(
                    "lifecycle-alloc-leak", "error", mod.path, line,
                    f"{fn.name} allocates a block chain at line "
                    f"{alloc_line} but returns here before the chain is "
                    f"committed to a table row or freed — the blocks "
                    f"leak on this path",
                ))


# ---- lifecycle-refcount-outside-allocator ----------------------------------


def _check_refcount_outside(mod: ParsedModule,
                            findings: List[Finding]) -> None:
    if mod.path.endswith(_ALLOCATOR_MODULE):
        return
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            for t in targets:
                base = t.value if isinstance(t, ast.Subscript) else t
                if (
                    isinstance(base, ast.Attribute)
                    and base.attr in _PRIVATE_BOOKS
                ):
                    findings.append(Finding(
                        "lifecycle-refcount-outside-allocator", "error",
                        mod.path, node.lineno,
                        f"write to allocator private book .{base.attr} "
                        f"outside {_ALLOCATOR_MODULE} — this bypasses "
                        f"the allocator's invariant checks and the "
                        f"blocksan shadow hooks; route it through the "
                        f"allocator API",
                    ))
        attr = _call_attr(node)
        if attr in _REF_CALLS:
            findings.append(Finding(
                "lifecycle-refcount-outside-allocator", "error",
                mod.path, node.lineno,
                f".{attr}() called outside {_ALLOCATOR_MODULE} — "
                f"refcount mutations belong to the allocator and its "
                f"in-module PrefixIndex; from anywhere else they skip "
                f"the chain/ownership bookkeeping the sanitizer and "
                f"the free path rely on",
            ))
        # container mutations on the books: x._free.append(b) etc.
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Attribute)
            and node.func.value.attr in _PRIVATE_BOOKS
            and node.func.attr in ("append", "extend", "pop", "remove",
                                   "clear", "update", "setdefault",
                                   "insert", "popitem")
        ):
            findings.append(Finding(
                "lifecycle-refcount-outside-allocator", "error",
                mod.path, node.lineno,
                f".{node.func.value.attr}.{node.func.attr}() mutates an "
                f"allocator private book outside {_ALLOCATOR_MODULE} — "
                f"route it through the allocator API",
            ))


# ---- lifecycle-span-imbalance ----------------------------------------------


def _check_span_imbalance(fn: ast.FunctionDef, mod: ParsedModule,
                          findings: List[Finding]) -> None:
    opens: List[int] = []
    closes: List[int] = []
    raises: List[int] = []
    for node in _walk_no_nested(fn):
        attr = _call_attr(node)
        if attr in _SPAN_OPENS:
            opens.append(node.lineno)
        elif attr in _SPAN_CLOSES:
            closes.append(node.lineno)
        elif isinstance(node, ast.Raise):
            raises.append(node.lineno)
    if not opens:
        return
    first_open = min(opens)
    if not closes:
        findings.append(Finding(
            "lifecycle-span-imbalance", "warning", mod.path, first_open,
            f"{fn.name} opens a swap span here and never closes it on "
            f"any path in this function — if the close lives in another "
            f"function (a cross-tick window protocol), suppress with "
            f"the protocol as the reason; otherwise close in "
            f"try/finally",
        ))
        return
    for r in sorted(raises):
        if r <= first_open:
            continue  # pre-open guard raises hold no window yet
        if any(first_open < c < r for c in closes):
            continue
        findings.append(Finding(
            "lifecycle-span-imbalance", "warning", mod.path, r,
            f"{fn.name} opened a swap span at line {first_open} and "
            f"this raise can escape with the window still open — the "
            f"chain stays pinned and every later free of its owner "
            f"fails loudly; close on the except edge before re-raising "
            f"(the swap_out_begin shape)",
        ))


# ---- lifecycle-fault-site-untested -----------------------------------------

#: where the chaos matrix lives, relative to the repo root that owns
#: the scanned module (derived per-module from abspath minus path)
_CHAOS_TEST_RELPATH = "tests/test_chaos_matrix.py"

#: chaos-file text cache keyed by (path, mtime_ns, size) — one read per
#: repo per process, yet an edited (or newly created) chaos file is
#: picked up on the next run instead of serving stale text
_CHAOS_CACHE: Dict[Tuple[str, Optional[int], Optional[int]],
                   Optional[str]] = {}


def _chaos_text(mod: ParsedModule) -> Optional[str]:
    """The chaos matrix's source text for the repo owning ``mod``, or
    None when the file does not exist (or the root is underivable)."""
    ab = mod.abspath.replace(os.sep, "/")
    rel = mod.path
    if not ab.endswith(rel):
        return None
    chaos = ab[: len(ab) - len(rel)] + _CHAOS_TEST_RELPATH
    try:
        st = os.stat(chaos)
        key = (chaos, st.st_mtime_ns, st.st_size)
    except OSError:
        return None
    if key not in _CHAOS_CACHE:
        try:
            with open(chaos, "r", encoding="utf-8") as f:
                _CHAOS_CACHE[key] = f.read()
        except OSError:
            _CHAOS_CACHE[key] = None
    return _CHAOS_CACHE[key]


def _check_fault_site_untested(mod: ParsedModule,
                               findings: List[Finding]) -> None:
    sites: List[Tuple[int, str]] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        name = (
            node.func.id if isinstance(node.func, ast.Name)
            else node.func.attr if isinstance(node.func, ast.Attribute)
            else None
        )
        if name != "fault_point" or not node.args:
            continue
        arg = node.args[0]
        if (
            isinstance(arg, ast.Constant)
            and isinstance(arg.value, str)
            and arg.value.startswith("serve.")
        ):
            sites.append((node.lineno, arg.value))
    if not sites:
        return
    text = _chaos_text(mod)
    for line, site in sites:
        if text is not None and site in text:
            continue
        detail = (
            f"the chaos matrix ({_CHAOS_TEST_RELPATH}) does not exist"
            if text is None else
            f"the site string never appears in {_CHAOS_TEST_RELPATH}"
        )
        findings.append(Finding(
            "lifecycle-fault-site-untested", "error", mod.path, line,
            f"serve fault site {site!r} has no chaos-matrix entry — "
            f"{detail}; add a scenario that injects at this site so "
            f"the failure plane's recovery from it is proven, not "
            f"assumed",
        ))


def check_lifecycle(mod: ParsedModule, ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _check_alloc_leak(node, mod, findings)
            _check_span_imbalance(node, mod, findings)
    _check_refcount_outside(mod, findings)
    _check_fault_site_untested(mod, findings)
    return findings


CHECK = check_lifecycle
CROSS_MODULE = False
