"""Rule family: threads — host-side concurrency hazards.

The device side of this repo is SPMD and deterministic; the HOST side
has quietly grown a small fleet of concurrent actors: the watchdog
deadline thread, the background warmup compiler, the Prometheus
exporter's HTTP threads, the async checkpoint writers, the data-loader
producer, plus signal handlers (suspend protocol) and the chained
``sys.excepthook`` (flight recorder). Python's GIL makes many races
*benign-looking* — right up until a compound check-then-act interleaves.
TSan doesn't exist for Python; this family is the static stand-in.

The pass first builds a **thread-entry-point inventory** per module
(``thread_inventory``): every ``threading.Thread(target=...)``, every
``signal.signal(sig, handler)`` registration, every ``sys.excepthook``
assignment. Tests and ``--explain`` consume it; two rules check against
it:

- ``thread-unsynced-mutation`` (warning): inside a class, an attribute
  mutated from a thread-entry method (or a method transitively reachable
  from one through ``self.*()`` calls) without any ``with self.<lock>:``
  held, when the same attribute is also touched by the class's
  non-thread methods. The classic shapes: a results list appended from
  the worker and read from ``summary()``, a state flag flipped on both
  sides of a check-then-act. Both lock idioms are credited: ``with
  self.<lock>:`` and a bare ``self.<lock>.acquire()`` …
  ``release()`` pair tracked lexically through the statement list (the
  try/finally shape). Deliberate lock-free protocols (monotonic flags,
  GIL-atomic single stores) stay — with an inline suppression
  recording WHY they are safe.
- ``thread-blocking-signal`` (error): a blocking call —
  ``.block_until_ready()``, ``open()``/file I/O, ``time.sleep``,
  ``.join()``, ``.acquire()``, ``jax.device_get``, ``subprocess.*`` —
  inside a registered signal handler. Signal handlers run *between
  bytecodes on the main thread*, possibly while the interpreter holds
  the very lock the handler would need: a blocking handler deadlocks
  the run it was installed to save. Handlers must only latch
  (``Event.set``, set a flag, chain the previous handler) and return;
  the suspend protocol's ``_on_signal`` is the reference shape.

Boundaries (documented in ANALYSIS.md): thread targets that are local
closures or attributes of OTHER objects (``self._server.serve_forever``)
are inventoried but not analyzed; lock discipline is "some lock held",
not "the right lock"; cross-module handler registration is invisible.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from pytorch_distributed_tpu.analysis._astutil import (
    dotted,
    get_kwarg,
    terminal_name,
)
from pytorch_distributed_tpu.analysis.core import (
    Finding,
    LintContext,
    ParsedModule,
    RuleInfo,
)

RULES = [
    RuleInfo(
        "thread-unsynced-mutation", "warning",
        "shared attribute mutated from a thread without a lock held",
        "An attribute written from a threading.Thread target method (or "
        "a method it reaches through self.*() calls) while the class's "
        "other methods also read or write it, with no lock covering the "
        "write — either `with self.<lock>:` or a bare "
        "`self.<lock>.acquire()` ... `release()` pair around it (the "
        "try/finally shape). The GIL serializes single bytecodes, not "
        "compound operations: check-then-append, read-modify-write "
        "(`self.n += 1`) and multi-field updates can interleave with the "
        "main thread and corrupt or drop state. Hold the class's lock "
        "around the mutation (the WarmupRunner._records_lock pattern), "
        "or — for deliberate lock-free protocols like the watchdog's "
        "monotonic heartbeat flags — suppress inline with the reason "
        "the race is benign, so the safety argument is recorded next to "
        "the code it protects.",
    ),
    RuleInfo(
        "thread-blocking-signal", "error",
        "blocking call inside a registered signal handler",
        "Signal handlers run between bytecodes on the main thread, "
        "possibly while the interpreter is inside the allocator, a "
        "logging lock, or a jax dispatch — any blocking call there "
        "(.block_until_ready(), open()/file I/O, time.sleep, .join(), "
        ".acquire(), jax.device_get, subprocess) can deadlock the "
        "process the handler was installed to save, or block past the "
        "scheduler's grace window. A handler must only latch state "
        "(threading.Event.set, a bool flag), optionally chain the "
        "previous handler, and return; the run's main loop polls the "
        "latch at a safe point (SuspendWatcher._on_signal is the "
        "reference shape). Checkpointing belongs on the poll side, "
        "never in the handler.",
    ),
]

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
_CONTAINER_MUTATORS = {
    "append", "extend", "insert", "pop", "update", "clear", "setdefault",
    "add", "remove", "discard", "popitem",
}
_BLOCKING_ATTRS = {"block_until_ready", "join", "acquire", "device_get"}
_BLOCKING_DOTTED_PREFIXES = ("time.sleep", "subprocess.", "os.system")


def _self_attr(node: ast.expr) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


# ---- the inventory ---------------------------------------------------------


def thread_inventory(mod: ParsedModule) -> Dict[str, List[dict]]:
    """Every concurrency entry point declared in this module.

    ``threads``          [{line, target, kind}] — kind is "self-method",
                         "function", or "opaque" (attr of another object)
    ``signal_handlers``  [{line, handler, kind}]
    ``excepthooks``      [{line, value}] — ``sys.excepthook = ...`` sites
    """
    threads: List[dict] = []
    handlers: List[dict] = []
    hooks: List[dict] = []
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            name = terminal_name(node)
            if name == "Thread":
                target = get_kwarg(node, "target")
                threads.append({
                    "line": node.lineno,
                    "target": _entry_name(target),
                    "kind": _entry_kind(target),
                })
            elif name == "signal" and isinstance(node.func, ast.Attribute):
                # signal.signal(sig, handler) — not the bare `signal` module
                if dotted(node.func) == "signal.signal" and len(node.args) >= 2:
                    h = node.args[1]
                    handlers.append({
                        "line": node.lineno,
                        "handler": _entry_name(h),
                        "kind": _entry_kind(h),
                    })
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if dotted(t) == "sys.excepthook":
                    hooks.append({
                        "line": node.lineno,
                        "value": _entry_name(node.value) or "<expr>",
                    })
    return {
        "threads": threads,
        "signal_handlers": handlers,
        "excepthooks": hooks,
    }


def _entry_name(node: Optional[ast.expr]) -> Optional[str]:
    if node is None:
        return None
    d = dotted(node)
    if d is not None:
        return d
    return None


def _entry_kind(node: Optional[ast.expr]) -> str:
    if node is None:
        return "opaque"
    if _self_attr(node) is not None:
        return "self-method"
    if isinstance(node, ast.Name):
        return "function"
    return "opaque"


# ---- per-class unsynced-mutation analysis ----------------------------------


class _ClassView:
    def __init__(self, cls: ast.ClassDef):
        self.cls = cls
        self.methods: Dict[str, ast.FunctionDef] = {
            n.name: n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        self.lock_attrs: Set[str] = set()
        self.container_attrs: Set[str] = set()
        for m in self.methods.values():
            for node in ast.walk(m):
                if not isinstance(node, ast.Assign):
                    continue
                attr = None
                for t in node.targets:
                    attr = _self_attr(t) or attr
                if attr is None:
                    continue
                v = node.value
                if isinstance(v, ast.Call) and terminal_name(v) in _LOCK_CTORS:
                    self.lock_attrs.add(attr)
                elif isinstance(v, (ast.List, ast.Dict, ast.Set)) or (
                    isinstance(v, ast.Call)
                    and terminal_name(v) in ("list", "dict", "set", "deque")
                ):
                    self.container_attrs.add(attr)

    def thread_entry_methods(self) -> Set[str]:
        """Methods handed to threading.Thread(target=self.X) anywhere in
        this class, plus everything they reach via self.Y() calls."""
        roots: Set[str] = set()
        for m in self.methods.values():
            for node in ast.walk(m):
                if isinstance(node, ast.Call) and terminal_name(node) == "Thread":
                    attr = _self_attr(get_kwarg(node, "target"))
                    if attr is not None and attr in self.methods:
                        roots.add(attr)
        # transitive closure over self-method calls AND self-method
        # references (callbacks handed to retry/executor helpers run in
        # the same thread context as the method that passes them)
        frontier = list(roots)
        while frontier:
            name = frontier.pop()
            for node in ast.walk(self.methods[name]):
                callee = None
                if isinstance(node, ast.Call):
                    callee = _self_attr(node.func)
                elif isinstance(node, ast.Attribute):
                    callee = _self_attr(node)
                if callee in self.methods and callee not in roots:
                    roots.add(callee)
                    frontier.append(callee)
        return roots

    def attr_access_map(self) -> Dict[str, Set[str]]:
        """self-attr name -> method names touching it (read or write)."""
        out: Dict[str, Set[str]] = {}
        for name, m in self.methods.items():
            for node in ast.walk(m):
                attr = _self_attr(node) if isinstance(node, ast.Attribute) else None
                if attr is not None:
                    out.setdefault(attr, set()).add(name)
        return out

    def _lock_toggle(self, stmt: ast.stmt) -> Optional[str]:
        """"acquire"/"release" for a bare ``self.<lock>.acquire()`` /
        ``.release()`` expression statement, else None."""
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            f = stmt.value.func
            if (
                isinstance(f, ast.Attribute)
                and f.attr in ("acquire", "release")
                and _self_attr(f.value) in self.lock_attrs
            ):
                return f.attr
        return None

    def mutations_in(self, method: ast.FunctionDef):
        """(attr, line, locked) for every self-attr mutation in the
        method. ``locked`` is True under a ``with self.<lock>:`` OR
        lexically between a bare ``self.<lock>.acquire()`` statement and
        its ``release()`` in the same statement list — the try/finally
        shape host-worker code uses when the critical section spans a
        handler edge the context manager cannot express."""
        out: List[Tuple[str, int, bool]] = []

        def visit_block(stmts, locked: bool):
            # sequential lock tracking: a bare acquire() statement
            # covers the rest of this list (a following try's body and
            # finally included) until the matching release()
            held = locked
            for stmt in stmts:
                toggle = self._lock_toggle(stmt)
                if toggle is not None:
                    held = locked or toggle == "acquire"
                    continue
                visit(stmt, held)

        def visit(node: ast.AST, locked: bool):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                holds = locked or any(
                    (attr := _self_attr(item.context_expr)) is not None
                    and attr in self.lock_attrs
                    for item in node.items
                )
                visit_block(node.body, holds)
                return
            if isinstance(node, ast.Try):
                visit_block(node.body, locked)
                for h in node.handlers:
                    visit_block(h.body, locked)
                visit_block(node.orelse, locked)
                visit_block(node.finalbody, locked)
                return
            if isinstance(node, (ast.If, ast.For, ast.AsyncFor,
                                 ast.While)):
                for field in ("test", "iter", "target"):
                    sub = getattr(node, field, None)
                    if sub is not None:
                        visit(sub, locked)
                visit_block(node.body, locked)
                visit_block(node.orelse, locked)
                return
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    attr = _self_attr(t)
                    if attr is not None:
                        out.append((attr, node.lineno, locked))
                    elif isinstance(t, ast.Subscript):
                        attr = _self_attr(t.value)
                        if attr is not None:
                            out.append((attr, node.lineno, locked))
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                attr = _self_attr(node.target)
                if attr is not None:
                    out.append((attr, node.lineno, locked))
            elif isinstance(node, ast.Call):
                f = node.func
                if (
                    isinstance(f, ast.Attribute)
                    and f.attr in _CONTAINER_MUTATORS
                ):
                    attr = _self_attr(f.value)
                    if attr is not None and attr in self.container_attrs:
                        out.append((attr, node.lineno, locked))
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                     ast.ClassDef),
                ):
                    continue
                visit(child, locked)

        visit_block(method.body, False)
        return out


def _check_class(view: _ClassView, mod: ParsedModule,
                 findings: List[Finding]) -> None:
    entries = view.thread_entry_methods()
    if not entries:
        return
    access = view.attr_access_map()
    for name in sorted(entries):
        method = view.methods[name]
        for attr, line, locked in view.mutations_in(method):
            if locked or attr in view.lock_attrs:
                continue
            # __init__ runs before any Thread exists: its accesses are
            # happens-before the thread by construction, never shared
            outside = access.get(attr, set()) - entries - {"__init__"}
            if not outside:
                continue  # touched only by thread-side methods
            findings.append(Finding(
                "thread-unsynced-mutation", "warning", mod.path, line,
                f"{view.cls.name}.{name} runs on a thread "
                f"(threading.Thread target) and mutates self.{attr} "
                f"with no lock held, while "
                f"{sorted(outside)} also touch it — guard it with the "
                f"class lock, or record why the race is benign",
            ))


# ---- signal handlers -------------------------------------------------------


def _blocking_calls(fn: ast.FunctionDef) -> List[Tuple[int, str]]:
    out: List[Tuple[int, str]] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name) and f.id == "open":
            out.append((node.lineno, "open() — file I/O"))
        elif isinstance(f, ast.Attribute):
            if f.attr in _BLOCKING_ATTRS:
                out.append((node.lineno, f".{f.attr}()"))
            else:
                d = dotted(f)
                if d is not None and any(
                    d == p or d.startswith(p) for p in _BLOCKING_DOTTED_PREFIXES
                ):
                    out.append((node.lineno, f"{d}()"))
    return out


def _check_signal_handlers(mod: ParsedModule, findings: List[Finding]) -> None:
    inv = thread_inventory(mod)
    if not inv["signal_handlers"]:
        return
    # resolve handler names to defs: module-level functions and methods
    defs: Dict[str, ast.FunctionDef] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[node.name] = node
    for h in inv["signal_handlers"]:
        name = h["handler"]
        if name is None:
            continue
        tail = name.rsplit(".", 1)[-1]  # self._on_signal -> _on_signal
        fn = defs.get(tail)
        if fn is None:
            continue
        for line, desc in _blocking_calls(fn):
            findings.append(Finding(
                "thread-blocking-signal", "error", mod.path, line,
                f"{desc} inside signal handler {fn.name!r} (registered "
                f"at line {h['line']}): handlers run between bytecodes "
                f"on the main thread and must only latch a flag and "
                f"return — move the blocking work to the poll side",
            ))


def check_threads(mod: ParsedModule, ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ClassDef):
            _check_class(_ClassView(node), mod, findings)
    _check_signal_handlers(mod, findings)
    return findings


CHECK = check_threads
CROSS_MODULE = False
