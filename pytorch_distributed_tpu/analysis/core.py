"""jaxlint core: findings, suppression comments, module parsing, the runner.

Pure stdlib (ast + re) — importing this module must never require jax, so
the linter can run in a bare CI container. Rules that DO need a live jax
(partition coverage) live in ``partition_coverage.py`` and degrade to a
skip when the import fails.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Set, Tuple

# Mesh axes assumed when the scanned tree declares none (the canonical
# (data, seq, model) grid of parallel/mesh.py); axes declared via module
# level ``<NAME>_AXIS = "<axis>"`` constants are unioned in per run.
DEFAULT_MESH_AXES = ("data", "seq", "model")

_SUPPRESS_RE = re.compile(
    r"#\s*jaxlint:\s*disable=([A-Za-z0-9_,\- ]+?)(?:\s+--\s*(.*))?\s*$"
)
_SUPPRESS_FILE_RE = re.compile(
    r"#\s*jaxlint:\s*disable-file=([A-Za-z0-9_,\- ]+?)(?:\s+--\s*(.*))?\s*$"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint result: ``file:line: rule severity: message``."""

    rule: str
    severity: str  # "error" | "warning"
    path: str  # repo-relative
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.severity}: {self.message}"


@dataclasses.dataclass
class ParsedModule:
    path: str  # repo-relative, forward slashes
    abspath: str
    source: str
    lines: List[str]
    tree: ast.Module
    # line number -> set of suppressed rule names ("all" suppresses any)
    suppressions: Dict[int, Set[str]]
    file_suppressions: Set[str]

    def is_suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_suppressions or "all" in self.file_suppressions:
            return True
        rules = self.suppressions.get(line, ())
        return rule in rules or "all" in rules


@dataclasses.dataclass
class LintContext:
    """Shared state for all rules over one run."""

    modules: List[ParsedModule]
    mesh_axes: Set[str]
    # *_AXIS constant name -> axis string, unioned over all scanned modules
    axis_constants: Dict[str, str]


def _parse_suppressions(lines: Sequence[str]):
    per_line: Dict[int, Set[str]] = {}
    file_level: Set[str] = set()
    for i, text in enumerate(lines, start=1):
        m = _SUPPRESS_FILE_RE.search(text)
        if m:
            file_level.update(r.strip() for r in m.group(1).split(",") if r.strip())
            continue
        m = _SUPPRESS_RE.search(text)
        if m:
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            per_line.setdefault(i, set()).update(rules)
    return per_line, file_level


def parse_file(abspath: str, rel_root: Optional[str] = None) -> ParsedModule:
    with open(abspath, "r", encoding="utf-8") as f:
        source = f.read()
    rel = (
        os.path.relpath(abspath, rel_root) if rel_root else abspath
    ).replace(os.sep, "/")
    lines = source.splitlines()
    per_line, file_level = _parse_suppressions(lines)
    return ParsedModule(
        path=rel,
        abspath=os.path.abspath(abspath),
        source=source,
        lines=lines,
        tree=ast.parse(source, filename=abspath),
        suppressions=per_line,
        file_suppressions=file_level,
    )


def iter_python_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d not in ("__pycache__", ".git", "build")
                )
                out.extend(
                    os.path.join(dirpath, f)
                    for f in sorted(filenames)
                    if f.endswith(".py")
                )
        elif p.endswith(".py"):
            out.append(p)
    return out


def collect_axis_constants(modules: Sequence[ParsedModule]) -> Dict[str, str]:
    """Module-level ``FOO_AXIS = "name"`` assignments across the tree."""
    consts: Dict[str, str] = {}
    for mod in modules:
        for node in mod.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            if not (
                isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)
            ):
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id.endswith("_AXIS"):
                    consts[tgt.id] = node.value.value
    return consts


Rule = Callable[[ParsedModule, LintContext], List[Finding]]


def default_rules() -> List[Rule]:
    from pytorch_distributed_tpu.analysis.rules_collectives import (
        check_collective_axes,
    )
    from pytorch_distributed_tpu.analysis.rules_host_transfer import (
        check_host_transfers,
    )
    from pytorch_distributed_tpu.analysis.rules_precision import (
        check_precision_casts,
    )
    from pytorch_distributed_tpu.analysis.rules_recompile import (
        check_recompile_hazards,
    )

    return [
        check_collective_axes,
        check_recompile_hazards,
        check_host_transfers,
        check_precision_casts,
    ]


def all_rule_ids() -> List[Tuple[str, str, str]]:
    """(rule id, severity, one-line description) for --list-rules."""
    return [
        ("collective-axis", "error",
         "collective uses an axis name no mesh/shard_map declares"),
        ("collective-axis-literal", "warning",
         "collective spells a mesh axis as a string literal instead of the "
         "shared *_AXIS constant"),
        ("collective-axis-inconsistent", "warning",
         "same collective op on the same operand uses two different axis "
         "names in one function"),
        ("recompile-traced-branch", "error",
         "Python if/while on a traced argument of a jit-compiled function"),
        ("recompile-jit-call", "warning",
         "jax.jit(...)(...) invoked immediately inside a function — the "
         "compile cache is discarded every call"),
        ("recompile-mutable-closure", "warning",
         "jit-compiled function closes over a module-level mutable that the "
         "module mutates elsewhere"),
        ("recompile-static-argnums", "error",
         "static_argnums out of range, overlapping donate_argnums, or "
         "marking a non-hashable (list/dict-default) parameter"),
        ("host-transfer", "error",
         "float()/np.asarray()/.item()/device_get reachable from a compiled "
         "train-step body"),
        ("partition-coverage", "error",
         "partition rule table leaves a shardable parameter replicated, or "
         "contains a rule matching no parameter"),
        ("precision-cast", "warning",
         "literal f32/bf16 cast in ops/ outside ops/precision.py policy "
         "helpers"),
    ]


def run_lint(
    paths: Sequence[str],
    rel_root: Optional[str] = None,
    rules: Optional[Sequence[Rule]] = None,
    extra_axes: Sequence[str] = (),
) -> List[Finding]:
    """Parse ``paths`` (files or directories) and run every rule.

    Returns findings with suppression comments already applied, sorted by
    (path, line). Baseline filtering is the caller's job
    (``split_baselined``) so tests can see the raw findings.
    """
    files = iter_python_files(paths)
    modules = [parse_file(f, rel_root) for f in files]
    consts = collect_axis_constants(modules)
    axes = set(DEFAULT_MESH_AXES) | set(consts.values()) | set(extra_axes)
    ctx = LintContext(modules=modules, mesh_axes=axes, axis_constants=consts)
    by_path = {m.path: m for m in modules}
    findings: Dict[Tuple[str, str, int], Finding] = {}
    for rule in rules if rules is not None else default_rules():
        for mod in modules:
            for f in rule(mod, ctx):
                # cross-module rules attribute findings to the file the
                # defect lives in — check suppressions there, and dedupe
                # sites reachable from several roots
                owner = by_path.get(f.path, mod)
                if owner.is_suppressed(f.rule, f.line):
                    continue
                findings.setdefault((f.rule, f.path, f.line), f)
    return sorted(
        findings.values(), key=lambda f: (f.path, f.line, f.rule)
    )


# ---- baseline --------------------------------------------------------------
#
# Pre-existing, reviewed findings live in a JSON baseline so the CLI exits 0
# on the shipped tree while any NEW finding still fails CI. Entries match on
# (rule, file, stripped source line content) — not line numbers — so they
# survive unrelated edits to the same file; every entry carries a human
# reason.


def load_baseline(path: str) -> List[dict]:
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    entries = data["findings"] if isinstance(data, dict) else data
    for e in entries:
        for key in ("rule", "file", "line_content", "reason"):
            if key not in e:
                raise ValueError(f"baseline entry missing {key!r}: {e}")
    return entries


def split_baselined(
    findings: Sequence[Finding],
    entries: Sequence[dict],
    sources: Dict[str, Sequence[str]],
) -> Tuple[List[Finding], List[Finding]]:
    """Partition findings into (new, baselined).

    ``sources`` maps repo-relative path -> source lines, used to compare a
    finding's line content against the baseline entry.
    """
    new: List[Finding] = []
    old: List[Finding] = []
    for f in findings:
        lines = sources.get(f.path, ())
        content = (
            lines[f.line - 1].strip() if 0 < f.line <= len(lines) else ""
        )
        matched = any(
            e["rule"] == f.rule
            and e["file"] == f.path
            and e["line_content"] == content
            for e in entries
        )
        (old if matched else new).append(f)
    return new, old
