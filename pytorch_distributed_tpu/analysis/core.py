"""jaxlint core: findings, suppression comments, module parsing, the runner.

Pure stdlib (ast + re) — importing this module must never require jax, so
the linter can run in a bare CI container. Rules that DO need a live jax
(partition coverage) live in ``partition_coverage.py`` and degrade to a
skip when the import fails.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import os
import re
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Set, Tuple

# Mesh axes assumed when the scanned tree declares none (the canonical
# (data, seq, model) grid of parallel/mesh.py); axes declared via module
# level ``<NAME>_AXIS = "<axis>"`` constants are unioned in per run.
DEFAULT_MESH_AXES = ("data", "seq", "model")

_SUPPRESS_RE = re.compile(
    r"#\s*jaxlint:\s*disable=([A-Za-z0-9_,\- ]+?)(?:\s+--\s*(.*))?\s*$"
)
_SUPPRESS_FILE_RE = re.compile(
    r"#\s*jaxlint:\s*disable-file=([A-Za-z0-9_,\- ]+?)(?:\s+--\s*(.*))?\s*$"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint result: ``file:line: rule severity: message``.

    ``fingerprint`` is a stable identity derived from (rule, file, line
    CONTENT, occurrence index) — not the line number — so it survives
    unrelated edits to the same file; SARIF consumers and the baseline
    both key on content this way. Rules leave it empty; ``run_lint``
    (and the CLI, for runtime rules) fills it in.
    """

    rule: str
    severity: str  # "error" | "warning"
    path: str  # repo-relative
    line: int
    message: str
    fingerprint: str = ""

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.severity}: {self.message}"


@dataclasses.dataclass(frozen=True)
class RuleInfo:
    """Catalogue entry for one rule id.

    ``explain`` is the long-form text behind ``jaxlint --explain RULE``;
    it lives here, next to the implementation, so the CLI help and
    ANALYSIS.md (which defers to ``--explain``) cannot drift from what
    the rule actually checks.
    """

    rule: str
    severity: str
    short: str
    explain: str


def _fingerprint(rule: str, path: str, content: str, occurrence: int) -> str:
    key = f"{rule}|{path}|{content}|{occurrence}"
    return hashlib.sha256(key.encode("utf-8")).hexdigest()[:16]


def with_fingerprints(
    findings: Sequence[Finding], sources: Dict[str, Sequence[str]]
) -> List[Finding]:
    """Fill each finding's stable fingerprint from its line content.

    Two identical lines in one file firing the same rule disambiguate by
    occurrence index (in line order), keeping fingerprints unique and
    deterministic. Findings that already carry a fingerprint pass
    through untouched.
    """
    counts: Dict[Tuple[str, str, str], int] = {}
    out: List[Finding] = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        if f.fingerprint:
            out.append(f)
            continue
        lines = sources.get(f.path, ())
        content = (
            lines[f.line - 1].strip() if 0 < f.line <= len(lines) else f.message
        )
        key = (f.rule, f.path, content)
        n = counts.get(key, 0)
        counts[key] = n + 1
        out.append(dataclasses.replace(
            f, fingerprint=_fingerprint(f.rule, f.path, content, n)
        ))
    return out


@dataclasses.dataclass
class ParsedModule:
    path: str  # repo-relative, forward slashes
    abspath: str
    source: str
    lines: List[str]
    tree: ast.Module
    # line number -> set of suppressed rule names ("all" suppresses any)
    suppressions: Dict[int, Set[str]]
    file_suppressions: Set[str]

    def is_suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_suppressions or "all" in self.file_suppressions:
            return True
        rules = self.suppressions.get(line, ())
        return rule in rules or "all" in rules


@dataclasses.dataclass
class LintContext:
    """Shared state for all rules over one run."""

    modules: List[ParsedModule]
    mesh_axes: Set[str]
    # *_AXIS constant name -> axis string, unioned over all scanned modules
    axis_constants: Dict[str, str]


def _parse_suppressions(lines: Sequence[str]):
    per_line: Dict[int, Set[str]] = {}
    file_level: Set[str] = set()
    for i, text in enumerate(lines, start=1):
        m = _SUPPRESS_FILE_RE.search(text)
        if m:
            file_level.update(r.strip() for r in m.group(1).split(",") if r.strip())
            continue
        m = _SUPPRESS_RE.search(text)
        if m:
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            per_line.setdefault(i, set()).update(rules)
    return per_line, file_level


def parse_file(abspath: str, rel_root: Optional[str] = None) -> ParsedModule:
    with open(abspath, "r", encoding="utf-8") as f:
        source = f.read()
    rel = (
        os.path.relpath(abspath, rel_root) if rel_root else abspath
    ).replace(os.sep, "/")
    lines = source.splitlines()
    per_line, file_level = _parse_suppressions(lines)
    return ParsedModule(
        path=rel,
        abspath=os.path.abspath(abspath),
        source=source,
        lines=lines,
        tree=ast.parse(source, filename=abspath),
        suppressions=per_line,
        file_suppressions=file_level,
    )


def iter_python_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d not in ("__pycache__", ".git", "build")
                )
                out.extend(
                    os.path.join(dirpath, f)
                    for f in sorted(filenames)
                    if f.endswith(".py")
                )
        elif p.endswith(".py"):
            out.append(p)
    return out


def collect_axis_constants(modules: Sequence[ParsedModule]) -> Dict[str, str]:
    """Module-level ``FOO_AXIS = "name"`` assignments across the tree."""
    consts: Dict[str, str] = {}
    for mod in modules:
        for node in mod.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            if not (
                isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)
            ):
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id.endswith("_AXIS"):
                    consts[tgt.id] = node.value.value
    return consts


Rule = Callable[[ParsedModule, LintContext], List[Finding]]

#: bump when any rule's behaviour changes — invalidates incremental caches
RULE_VERSION = "jaxlint-2.2"

# partition-coverage is the one rule whose implementation needs a live
# jax import, so its catalogue entry lives here (stdlib territory), not
# in its module.
_PARTITION_COVERAGE_INFO = RuleInfo(
    "partition-coverage", "error",
    "partition rule table leaves a shardable parameter replicated, or "
    "contains a rule matching no parameter",
    "Runtime cross-check of the partition rule tables in parallel// "
    "train/lm.py against real model parameter trees: every shardable "
    "parameter (ndim >= 2) must be matched by some rule, and every rule "
    "must match at least one parameter. A rule regex that drifts from a "
    "renamed module silently replicates the tensor FSDP was supposed to "
    "shard — this check needs an importable jax and degrades to a "
    "skipped notice without one.",
)


def _rule_modules():
    from pytorch_distributed_tpu.analysis import (
        rules_collectives,
        rules_donation,
        rules_host_transfer,
        rules_lifecycle,
        rules_precision,
        rules_recompile,
        rules_sharding,
        rules_threads,
    )

    return [
        rules_collectives,
        rules_recompile,
        rules_host_transfer,
        rules_precision,
        rules_donation,
        rules_sharding,
        rules_threads,
        rules_lifecycle,
    ]


def rule_catalog() -> List[RuleInfo]:
    """Every shipped rule's catalogue entry, AST rules first."""
    out: List[RuleInfo] = []
    for mod in _rule_modules():
        out.extend(mod.RULES)
    out.append(_PARTITION_COVERAGE_INFO)
    return out


def default_rules() -> List[Rule]:
    return [mod.CHECK for mod in _rule_modules()]


def local_rules() -> List[Rule]:
    """Rules whose findings depend only on one file's content (given the
    run's axis-constant context) — safe to cache per file."""
    return [mod.CHECK for mod in _rule_modules() if not mod.CROSS_MODULE]


def cross_rules() -> List[Rule]:
    """Rules that walk the whole-package call graph; their findings can
    move when ANY file changes, so the incremental cache re-runs them on
    every non-empty change set."""
    return [mod.CHECK for mod in _rule_modules() if mod.CROSS_MODULE]


def all_rule_ids() -> List[Tuple[str, str, str]]:
    """(rule id, severity, one-line description) for --list-rules."""
    return [(r.rule, r.severity, r.short) for r in rule_catalog()]


def explain_rule(rule_id: str) -> Optional[str]:
    """Long-form ``--explain`` text for one rule id, or None."""
    for r in rule_catalog():
        if r.rule == rule_id:
            return (
                f"{r.rule} ({r.severity})\n"
                f"{'=' * (len(r.rule) + len(r.severity) + 3)}\n"
                f"{r.short}\n\n{r.explain}\n\n"
                f"Suppress with '# jaxlint: disable={r.rule} -- <reason>' "
                f"(or disable-file= for a whole file); reviewed "
                f"pre-existing findings live in scripts/jaxlint_baseline.json."
            )
    return None


def run_lint(
    paths: Sequence[str],
    rel_root: Optional[str] = None,
    rules: Optional[Sequence[Rule]] = None,
    extra_axes: Sequence[str] = (),
) -> List[Finding]:
    """Parse ``paths`` (files or directories) and run every rule.

    Returns findings with suppression comments already applied, sorted by
    (path, line). Baseline filtering is the caller's job
    (``split_baselined``) so tests can see the raw findings.
    """
    files = iter_python_files(paths)
    modules = [parse_file(f, rel_root) for f in files]
    consts = collect_axis_constants(modules)
    axes = set(DEFAULT_MESH_AXES) | set(consts.values()) | set(extra_axes)
    ctx = LintContext(modules=modules, mesh_axes=axes, axis_constants=consts)
    by_path = {m.path: m for m in modules}
    findings: Dict[Tuple[str, str, int], Finding] = {}
    for rule in rules if rules is not None else default_rules():
        for mod in modules:
            for f in rule(mod, ctx):
                # cross-module rules attribute findings to the file the
                # defect lives in — check suppressions there, and dedupe
                # sites reachable from several roots
                owner = by_path.get(f.path, mod)
                if owner.is_suppressed(f.rule, f.line):
                    continue
                findings.setdefault((f.rule, f.path, f.line), f)
    sources = {m.path: m.lines for m in modules}
    return with_fingerprints(
        sorted(findings.values(), key=lambda f: (f.path, f.line, f.rule)),
        sources,
    )


# ---- baseline --------------------------------------------------------------
#
# Pre-existing, reviewed findings live in a JSON baseline so the CLI exits 0
# on the shipped tree while any NEW finding still fails CI. Entries match on
# (rule, file, stripped source line content) — not line numbers — so they
# survive unrelated edits to the same file; every entry carries a human
# reason.


def load_baseline(path: str) -> List[dict]:
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    entries = data["findings"] if isinstance(data, dict) else data
    for e in entries:
        for key in ("rule", "file", "line_content", "reason"):
            if key not in e:
                raise ValueError(f"baseline entry missing {key!r}: {e}")
    return entries


def split_baselined(
    findings: Sequence[Finding],
    entries: Sequence[dict],
    sources: Dict[str, Sequence[str]],
) -> Tuple[List[Finding], List[Finding]]:
    """Partition findings into (new, baselined).

    ``sources`` maps repo-relative path -> source lines, used to compare a
    finding's line content against the baseline entry.
    """
    new: List[Finding] = []
    old: List[Finding] = []
    for f in findings:
        lines = sources.get(f.path, ())
        content = (
            lines[f.line - 1].strip() if 0 < f.line <= len(lines) else ""
        )
        matched = any(
            e["rule"] == f.rule
            and e["file"] == f.path
            and e["line_content"] == content
            for e in entries
        )
        (old if matched else new).append(f)
    return new, old


UNREVIEWED_REASON = "UNREVIEWED: justify this entry or fix the finding"


def regenerate_baseline(
    findings: Sequence[Finding],
    old_entries: Sequence[dict],
    sources: Dict[str, Sequence[str]],
) -> dict:
    """``--fix-baseline``: rebuild the baseline from the current findings.

    Deterministic order (file, line content, rule); reasons of surviving
    entries are preserved by (rule, file, line_content) match, entries
    whose finding disappeared are dropped (the baseline shrinks), and a
    finding not previously baselined gets the UNREVIEWED placeholder —
    CI reviewers must replace it or fix the code.
    """
    reasons = {
        (e["rule"], e["file"], e["line_content"]): e["reason"]
        for e in old_entries
    }
    entries = []
    seen = set()
    for f in findings:
        lines = sources.get(f.path, ())
        content = (
            lines[f.line - 1].strip() if 0 < f.line <= len(lines) else ""
        )
        key = (f.rule, f.path, content)
        if key in seen:  # two hits on identical content: one entry covers both
            continue
        seen.add(key)
        entries.append({
            "rule": f.rule,
            "file": f.path,
            "line_content": content,
            "reason": reasons.get(key, UNREVIEWED_REASON),
        })
    entries.sort(key=lambda e: (e["file"], e["line_content"], e["rule"]))
    return {
        "_comment": (
            "Reviewed pre-existing jaxlint findings. Entries match on "
            "(rule, file, stripped line content) so they survive unrelated "
            "edits; delete an entry when its finding is fixed. Regenerate "
            "with scripts/jaxlint.py --fix-baseline after burning findings "
            "down — the baseline must only ever shrink. New findings are "
            "NOT covered and fail CI."
        ),
        "findings": entries,
    }
