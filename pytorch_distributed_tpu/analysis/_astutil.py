"""Shared AST helpers for the jaxlint rules (stdlib only)."""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple


def dotted(node: ast.AST) -> Optional[str]:
    """'jax.lax.psum' for an Attribute/Name chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return dotted(call.func)


def terminal_name(call: ast.Call) -> Optional[str]:
    """Last component of the callee ('psum' for jax.lax.psum / lax.psum)."""
    d = call_name(call)
    return d.rsplit(".", 1)[-1] if d else None


def import_map(tree: ast.Module) -> Dict[str, str]:
    """Local name -> fully-qualified origin for module-level imports.

    ``import jax.numpy as jnp`` -> {'jnp': 'jax.numpy'};
    ``from jax import lax`` -> {'lax': 'jax.lax'};
    ``from x.y import f as g`` -> {'g': 'x.y.f'}.
    """
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
            for a in node.names:
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


def walk_functions(
    tree: ast.Module,
) -> Iterator[Tuple[ast.FunctionDef, List[ast.FunctionDef]]]:
    """Yield (function def, enclosing def stack outermost-first)."""

    def visit(node: ast.AST, stack: List[ast.FunctionDef]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield child, list(stack)
                yield from visit(child, stack + [child])
            else:
                yield from visit(child, stack)

    yield from visit(tree, [])


def param_names(fn: ast.FunctionDef) -> List[str]:
    args = fn.args
    names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
    if args.vararg:
        names.append(args.vararg.arg)
    if args.kwarg:
        names.append(args.kwarg.arg)
    return names


def get_kwarg(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def get_arg(call: ast.Call, pos: int, kwname: str) -> Optional[ast.expr]:
    """Argument by position-or-keyword (how the collective axis args bind)."""
    kw = get_kwarg(call, kwname)
    if kw is not None:
        return kw
    if len(call.args) > pos and not any(
        isinstance(a, ast.Starred) for a in call.args[: pos + 1]
    ):
        return call.args[pos]
    return None


def int_constants(node: ast.expr) -> Optional[List[int]]:
    """[ints] for an int literal or tuple/list of int literals, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                vals.append(e.value)
            else:
                return None
        return vals
    return None


def assigned_name_targets(node: ast.stmt) -> List[str]:
    out: List[str] = []
    if isinstance(node, ast.Assign):
        for t in node.targets:
            if isinstance(t, ast.Name):
                out.append(t.id)
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        if isinstance(node.target, ast.Name):
            out.append(node.target.id)
    return out
