"""Incremental lint: a per-file content-hash cache.

CI re-lints the whole tree on every push; almost every push changes a
handful of files. This cache makes the common case cheap without making
any case wrong:

- **per-file** findings from LOCAL rules (donation, sharding, threads,
  collectives, recompile, precision) depend only on that file's content
  plus the run-wide *context* — the union of ``*_AXIS`` constants and
  mesh axes every module contributes, and the rule version. Each file's
  entry is keyed by its content sha256; the whole cache is keyed by the
  context fingerprint, so an axis constant added anywhere invalidates
  everything (correctly: it can silence or create collective-axis
  findings in any file).
- **cross-module** rules (host-transfer walks the package call graph)
  re-run over the full tree whenever ANY file changed — their findings
  can move when a callee three modules away gains a ``float()``. Their
  results are cached as one block, reused only on a fully-unchanged
  tree.

So: nothing changed → zero parses, zero rule runs. One file changed →
every file is still *parsed* (the cross pass and the context need the
tree) but local rules run only on the changed file. The honest win is
the no-change CI re-run and the long tail of parse-heavy local rules;
``scripts/ci_check.sh --lint-incremental`` wires it up.

The cache file is an implementation detail (gitignored); a corrupt or
version-skewed cache degrades to a full run, never to stale findings.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

from pytorch_distributed_tpu.analysis.core import (
    DEFAULT_MESH_AXES,
    Finding,
    LintContext,
    RULE_VERSION,
    collect_axis_constants,
    cross_rules,
    iter_python_files,
    local_rules,
    parse_file,
    with_fingerprints,
)


def _sha(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _finding_to_dict(f: Finding) -> dict:
    return dataclasses.asdict(f)


def _finding_from_dict(d: dict) -> Finding:
    return Finding(**d)


@dataclasses.dataclass
class IncrementalResult:
    findings: List[Finding]
    linted: int   # files local rules actually ran on
    cached: int   # files served from cache
    full_run: bool  # True when the context change forced a full pass


class LintCache:
    """Load/validate/save the JSON cache file."""

    def __init__(self, path: str):
        self.path = path
        self.context: Optional[str] = None
        self.files: Dict[str, dict] = {}
        self.cross: List[dict] = []
        self._load()

    def _load(self) -> None:
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                data = json.load(f)
            if data.get("version") != RULE_VERSION:
                return
            self.context = data.get("context")
            self.files = dict(data.get("files", {}))
            self.cross = list(data.get("cross_findings", []))
        except (OSError, ValueError, TypeError):
            return  # absent/corrupt cache = full run

    def save(self) -> None:
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({
                "version": RULE_VERSION,
                "context": self.context,
                "cross_findings": self.cross,
                "files": self.files,
            }, f, indent=1, sort_keys=True)
        os.replace(tmp, self.path)


def run_lint_incremental(
    paths: Sequence[str],
    cache_path: str,
    rel_root: Optional[str] = None,
    extra_axes: Sequence[str] = (),
) -> IncrementalResult:
    """``run_lint`` semantics (suppressions applied, sorted, fingerprinted)
    backed by the content-hash cache."""
    cache = LintCache(cache_path)
    files = iter_python_files(paths)

    hashes: Dict[str, str] = {}
    rels: Dict[str, str] = {}
    blobs: Dict[str, bytes] = {}
    for f in files:
        with open(f, "rb") as fh:
            blob = fh.read()
        rel = (
            os.path.relpath(f, rel_root) if rel_root else f
        ).replace(os.sep, "/")
        hashes[rel] = _sha(blob)
        rels[rel] = f
        blobs[rel] = blob

    known = set(cache.files)
    unchanged = {
        rel for rel, h in hashes.items()
        if rel in known and cache.files[rel].get("sha") == h
    }
    changed = [rel for rel in hashes if rel not in unchanged]
    # a deleted file's cached findings must not survive it — and the
    # deletion is itself a change: it can shrink the axis-constant
    # context and remove call-graph nodes the cross rules walked
    deleted = known - set(hashes)
    for rel in deleted:
        del cache.files[rel]

    if not changed and not deleted and cache.context is not None:
        findings = [
            _finding_from_dict(d)
            for rel in sorted(hashes)
            for d in cache.files[rel].get("findings", [])
        ] + [_finding_from_dict(d) for d in cache.cross]
        findings.sort(key=lambda f: (f.path, f.line, f.rule))
        return IncrementalResult(findings, 0, len(hashes), False)

    # something changed: parse the whole tree (the cross pass and the
    # axis-constant context need every module anyway)
    modules = [parse_file(rels[rel], rel_root) for rel in sorted(hashes)]
    by_rel = {m.path: m for m in modules}
    consts = collect_axis_constants(modules)
    axes = set(DEFAULT_MESH_AXES) | set(consts.values()) | set(extra_axes)
    context = _sha(json.dumps(
        [RULE_VERSION, sorted(consts.items()), sorted(axes)],
        separators=(",", ":"),
    ).encode())
    full_run = context != cache.context
    if full_run:
        changed = list(hashes)
        unchanged = set()
    ctx = LintContext(
        modules=modules, mesh_axes=axes, axis_constants=consts
    )

    sources = {m.path: m.lines for m in modules}

    def apply(rule, mod):
        out = []
        for f in rule(mod, ctx):
            owner = by_rel.get(f.path, mod)
            if not owner.is_suppressed(f.rule, f.line):
                out.append(f)
        return out

    # local rules: changed files only
    for rel in changed:
        mod = by_rel[rel]
        file_findings: List[Finding] = []
        for rule in local_rules():
            file_findings.extend(apply(rule, mod))
        uniq: Dict[Tuple[str, str, int], Finding] = {}
        for f in file_findings:
            uniq.setdefault((f.rule, f.path, f.line), f)
        fps = with_fingerprints(
            sorted(uniq.values(), key=lambda f: (f.path, f.line, f.rule)),
            sources,
        )
        cache.files[rel] = {
            "sha": hashes[rel],
            "findings": [_finding_to_dict(f) for f in fps],
        }

    # cross rules: full tree on any change
    cross_findings: List[Finding] = []
    for rule in cross_rules():
        for mod in modules:
            cross_findings.extend(apply(rule, mod))
    uniq = {}
    for f in cross_findings:
        uniq.setdefault((f.rule, f.path, f.line), f)
    cross_fps = with_fingerprints(
        sorted(uniq.values(), key=lambda f: (f.path, f.line, f.rule)),
        sources,
    )
    cache.cross = [_finding_to_dict(f) for f in cross_fps]
    cache.context = context
    cache.save()

    findings = [
        _finding_from_dict(d)
        for rel in sorted(hashes)
        for d in cache.files[rel].get("findings", [])
    ] + list(cross_fps)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return IncrementalResult(
        findings, len(changed), len(hashes) - len(changed), full_run
    )
