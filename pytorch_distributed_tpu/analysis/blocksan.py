"""blocksan — runtime block-lifecycle sanitizer for the paged KV pool.

The serving stack's hardest invariants live in ``serving/kv_pool.py``:
refcounted blocks shared across chains and the prefix index, swap
windows that pin chains mid-flight, disaggregated handoffs that move a
chain between pools. The allocator already raises on the violations it
can see locally (double free, freeing a mid-swap chain); what nothing
checked until this round is the GLOBAL story — a request that retires
while its chain is still held, a table row naming a recycled block, a
handoff source freed before the adopting replica committed. Leaked
blocks are capacity corruption: at fleet scale they surface as mystery
sheds, never as a stack trace. This is the ASan/LeakSanitizer move
applied to paged KV memory.

The sanitizer keeps a **shadow ledger** fully independent of the
allocator's own books: every alloc / incref / decref / free / swap
state change flows through ``BlockAllocator.sanitizer`` hooks (one
attribute test per op when detached — the ``fault_point`` precedent),
and the engine/scheduler annotate the semantic sites (admit, COW,
swap-out, handoff export, retire). Each ledger event records
``(block_id, owner, rid, span_id, site)``; a *span* is one block's
lifetime from fresh allocation to refcount zero.

Violation classes (``Violation.kind``):

====================  =====================================================
``leak-at-retire``    a request retired (or was cancelled / handed off)
                      while the shadow ledger still shows its owner slot
                      holding a chain — blocks the scheduler will never
                      free again
``double-free``       decref of a block the ledger already saw die (the
                      allocator raises too; the sanitizer records WHERE,
                      with rid/site attribution, before it does)
``refcount-underflow``
                      a shadow refcount would cross below zero, or
                      ``verify`` finds a non-positive count in either
                      ledger — someone mutated refcounts outside the API
``use-after-free``    a freed block id observed where only live blocks
                      may appear: a block-table row, an incref, or the
                      free list handing out a block the ledger still
                      holds live
``pinned-block``      freeing a chain pinned by an in-flight swap window
                      or an exported-not-yet-adopted handoff (the swap
                      case also raises in the allocator; the handoff pin
                      is ONLY visible here)
``quiesce-mismatch``  at quiesce the shadow ledger and the allocator
                      disagree: refcounts differ, the free list names a
                      live block, a block is neither free nor live, or
                      the free list holds duplicates
====================  =====================================================

Enablement: ``PDT_BLOCKSAN=1`` in the environment (``maybe_sanitizer``
returns None otherwise, and nothing is installed — the serving hot
path pays one ``is not None`` test per allocator op). Violations are
recorded (``sanitizer.violations``), optionally streamed as
``kind="sanitizer"`` JSONL records, and ``assert_clean()`` turns them
into one loud error — the CI smoke gate. Known boundaries are
documented in ANALYSIS.md ("blocksan" section): the sanitizer watches
block *identity*, not block *contents*, and a replica's shadow is
single-threaded by the same rule as the allocator it mirrors.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
from collections import deque
from typing import Dict, List, Optional

ENV_FLAG = "PDT_BLOCKSAN"

VIOLATION_KINDS = (
    "leak-at-retire",
    "double-free",
    "refcount-underflow",
    "use-after-free",
    "pinned-block",
    "quiesce-mismatch",
)


def enabled(env: str = ENV_FLAG) -> bool:
    """True when the sanitizer is switched on for this process."""
    return os.environ.get(env, "").strip().lower() in ("1", "true", "on")


def maybe_sanitizer(metrics_log=None, replica_id: int = 0):
    """The one enablement gate: a :class:`BlockSanitizer` when
    ``PDT_BLOCKSAN=1``, else ``None`` — callers hold the None and every
    hook site stays a single attribute test."""
    if not enabled():
        return None
    return BlockSanitizer(metrics_log=metrics_log, replica_id=replica_id)


class BlockSanError(RuntimeError):
    """Raised by ``assert_clean`` when the ledger recorded violations."""


@dataclasses.dataclass(frozen=True)
class Violation:
    kind: str          # one of VIOLATION_KINDS
    block: int         # block id (-1 when not block-scoped)
    owner: int         # allocator owner / slot id (-1 unknown)
    rid: Optional[int]  # request id, when the owner resolved to one
    site: str          # semantic site label active when it fired
    detail: str

    def __post_init__(self):
        if self.kind not in VIOLATION_KINDS:
            raise ValueError(
                f"unknown violation kind {self.kind!r}; "
                f"known: {VIOLATION_KINDS}"
            )


@dataclasses.dataclass(frozen=True)
class LedgerEvent:
    """One shadow-ledger entry: what happened to which block, under
    which owner/request, in which span, at which semantic site."""
    seq: int
    shadow: str        # attach name (fleet: "replica3")
    event: str         # alloc/share/incref/decref/dead/free/state/cow/pin
    block: int
    owner: int
    rid: Optional[int]
    span: int          # block-lifetime span id (0 = none)
    site: str


class AllocatorShadow:
    """The per-allocator shadow ledger — installed as
    ``BlockAllocator.sanitizer`` so the allocator's hook sites reach it
    directly. Maintains its OWN refcounts, chains, swap states and pin
    set; agreement with the allocator is asserted, never assumed."""

    def __init__(self, san: "BlockSanitizer", allocator, name: str):
        self.san = san
        self.allocator = allocator
        self.name = name
        self.refs: Dict[int, int] = {}         # live block -> shadow refcount
        self.chains: Dict[int, List[int]] = {}  # owner -> chain
        self.states: Dict[int, str] = {}       # owner -> open swap window
        self.pins: Dict[int, str] = {}         # owner -> pin reason
        self.spans: Dict[int, int] = {}        # live block -> span id
        #: owner slot -> rid resolver (scheduler wires its _slot2rid)
        self.resolve_rid = lambda owner: None
        self._site = "allocator"

    # ---- semantic annotations (engine / scheduler side) ----

    @contextlib.contextmanager
    def site(self, label: str):
        """Label the allocator ops inside the block with a semantic
        site (``admit``, ``cow``, ``swap_out`` …) for the ledger."""
        prev, self._site = self._site, label
        try:
            yield
        finally:
            self._site = prev

    def pin(self, owner: int, reason: str) -> None:
        """Pin ``owner``'s chain (handoff export in flight): a free
        before :meth:`unpin` is a ``pinned-block`` violation even
        though the allocator itself would allow it."""
        self.pins[owner] = reason

    def unpin(self, owner: int) -> None:
        self.pins.pop(owner, None)

    def note_cow(self, owner: int, src: int, dst: int) -> None:
        """Record a copy-on-write duplication: ``dst`` (already in
        ``owner``'s fresh suffix) now carries ``src``'s contents."""
        self._event("cow", dst, owner, detail_block=src)

    # ---- allocator hooks (serving/kv_pool.py call sites) ----

    def on_alloc(self, owner: int, shared: List[int],
                 fresh: List[int]) -> None:
        for b in shared:
            if b not in self.refs:
                self._violate(
                    "use-after-free", b, owner,
                    f"chain for owner {owner} shares block {b} which the "
                    f"ledger saw die",
                )
                self.refs[b] = 0  # resurrect so bookkeeping continues
                self.spans[b] = self.san._next_span()
            self.refs[b] += 1
            self._event("share", b, owner)
        for b in fresh:
            if b in self.refs:
                self._violate(
                    "use-after-free", b, owner,
                    f"free list handed out block {b} which the ledger "
                    f"still holds live (ref {self.refs[b]})",
                )
            self.refs[b] = 1
            self.spans[b] = self.san._next_span()
            self._event("alloc", b, owner)
        self.chains[owner] = list(shared) + list(fresh)

    def on_incref(self, block: int) -> None:
        if block not in self.refs:
            self._violate(
                "use-after-free", block, -1,
                f"incref of block {block} after the ledger saw it die",
            )
            return
        self.refs[block] += 1
        self._event("incref", block, -1)

    def on_decref(self, block: int) -> None:
        n = self.refs.get(block)
        if n is None:
            self._violate(
                "double-free", block, -1,
                f"decref of block {block} after the ledger saw it die",
            )
            return
        if n <= 0:
            self._violate(
                "refcount-underflow", block, -1,
                f"decref would take block {block}'s refcount to {n - 1}",
            )
            del self.refs[block]
            self.spans.pop(block, None)
            return
        n -= 1
        if n == 0:
            del self.refs[block]
            self._event("dead", block, -1)
            self.spans.pop(block, None)
        else:
            self.refs[block] = n
            self._event("decref", block, -1)

    def on_free(self, owner: int, state: Optional[str]) -> None:
        if state is not None:
            self._violate(
                "pinned-block", -1, owner,
                f"free of owner {owner}'s chain inside an open "
                f"{state} swap window",
            )
            return  # the allocator raises; its chain stays
        if owner in self.pins:
            self._violate(
                "pinned-block", -1, owner,
                f"free of owner {owner}'s chain while pinned for "
                f"{self.pins[owner]} — the allocator allows this; the "
                f"peer holding the pin does not",
            )
        chain = self.chains.pop(owner, None)
        if chain is not None:
            self._event("free", -1, owner)
        # the allocator's per-block decrefs follow through on_decref

    def on_state(self, owner: int, state: Optional[str]) -> None:
        if state is None:
            self.states.pop(owner, None)
        else:
            self.states[owner] = state
        self._event("state", -1, owner)

    # ---- checks ----

    def check_retire(self, owner: int, rid: Optional[int] = None,
                     site: str = "retire") -> None:
        """A request just finished on ``owner``'s slot: the ledger must
        show no chain left under that owner (shared blocks legitimately
        survive under OTHER refs; the chain itself must be gone)."""
        chain = self.chains.get(owner)
        if chain is not None:
            self._violate(
                "leak-at-retire", -1, owner,
                f"owner {owner} retired holding blocks {chain} the "
                f"scheduler will never free",
                rid=rid, site=site,
            )
        if owner in self.states:
            self._violate(
                "pinned-block", -1, owner,
                f"owner {owner} retired inside an open "
                f"{self.states[owner]} swap window",
                rid=rid, site=site,
            )

    def check_tables(self, tables, trash_block: int = 0) -> None:
        """Sweep the engine's block tables: every non-trash id must be
        ledger-live — a dead id here is a lookup of recycled memory."""
        import numpy as np

        arr = np.asarray(tables)
        for slot in range(arr.shape[0]):
            for b in np.unique(arr[slot]):
                b = int(b)
                if b != trash_block and b not in self.refs:
                    self._violate(
                        "use-after-free", b, slot,
                        f"table row {slot} names block {b} which the "
                        f"ledger saw die",
                        site="table-sweep",
                    )

    def verify(self, site: str = "quiesce") -> List[Violation]:
        """Ledger ≡ allocator: shadow refcounts match the allocator's,
        the free list is exactly the non-live ids with no duplicates,
        and no count in either book is non-positive. Returns (and
        records) the violations found."""
        a = self.allocator
        before = len(self.san.violations)
        live_a = dict(a._refs)
        for b, n in sorted(live_a.items()):
            if n <= 0:
                self._violate(
                    "refcount-underflow", b, -1,
                    f"allocator holds refcount {n} for block {b}",
                    site=site,
                )
            sn = self.refs.get(b)
            if sn is None:
                self._violate(
                    "quiesce-mismatch", b, -1,
                    f"allocator holds block {b} live (ref {n}); the "
                    f"ledger saw it die",
                    site=site,
                )
            elif sn != n:
                self._violate(
                    "quiesce-mismatch", b, -1,
                    f"refcount disagreement on block {b}: allocator "
                    f"{n}, ledger {sn}",
                    site=site,
                )
        for b in sorted(set(self.refs) - set(live_a)):
            self._violate(
                "quiesce-mismatch", b, -1,
                f"ledger holds block {b} live (ref {self.refs[b]}); "
                f"the allocator freed it",
                site=site,
            )
        free = list(a._free)
        if len(free) != len(set(free)):
            dupes = sorted(b for b in set(free) if free.count(b) > 1)
            self._violate(
                "quiesce-mismatch", dupes[0], -1,
                f"free list holds duplicate block ids {dupes}",
                site=site,
            )
        for b in free:
            if b in live_a:
                self._violate(
                    "use-after-free", b, -1,
                    f"free list offers block {b} which is still live "
                    f"(ref {live_a[b]})",
                    site=site,
                )
        missing = set(range(1, a.n_blocks)) - set(free) - set(live_a)
        for b in sorted(missing):
            self._violate(
                "quiesce-mismatch", b, -1,
                f"block {b} is neither free nor live — dropped from "
                f"both books",
                site=site,
            )
        return self.san.violations[before:]

    def verify_quiesce(self) -> List[Violation]:
        """The end-of-run gate: ledger ≡ allocator AND no owner still
        holds a chain, a swap window, or a pin. (Index-retained blocks
        — refcounted but chainless — are legitimately live.)"""
        before = len(self.san.violations)
        self.verify(site="quiesce")
        for owner, chain in sorted(self.chains.items()):
            self._violate(
                "leak-at-retire", -1, owner,
                f"owner {owner} still holds blocks {chain} at quiesce",
                site="quiesce",
            )
        for owner, state in sorted(self.states.items()):
            self._violate(
                "pinned-block", -1, owner,
                f"owner {owner} still inside an open {state} swap "
                f"window at quiesce", site="quiesce",
            )
        for owner, reason in sorted(self.pins.items()):
            self._violate(
                "pinned-block", -1, owner,
                f"owner {owner} still pinned for {reason} at quiesce",
                site="quiesce",
            )
        found = self.san.violations[before:]
        self.san._log_quiesce(self, found)
        return found

    # ---- internals ----

    def _event(self, event: str, block: int, owner: int,
               detail_block: Optional[int] = None) -> None:
        self.san._record_event(LedgerEvent(
            seq=self.san._next_seq(), shadow=self.name, event=event,
            block=block, owner=owner,
            rid=self.resolve_rid(owner) if owner >= 0 else None,
            span=self.spans.get(detail_block if detail_block is not None
                                else block, 0),
            site=self._site,
        ))

    def _violate(self, kind: str, block: int, owner: int, detail: str,
                 rid: Optional[int] = None,
                 site: Optional[str] = None) -> None:
        if rid is None and owner >= 0:
            rid = self.resolve_rid(owner)
        self.san._record_violation(self, Violation(
            kind=kind, block=block, owner=owner, rid=rid,
            site=site if site is not None else self._site, detail=detail,
        ))


class BlockSanitizer:
    """The process-level sanitizer: one per run, attached to each
    replica's allocator (``attach``). Aggregates violations and the
    bounded event ledger across shadows; streams ``kind="sanitizer"``
    JSONL when given a ``metrics_log``."""

    #: ledger ring size — enough to reconstruct any block's recent
    #: history at test scale without unbounded growth under a long run
    MAX_EVENTS = 20_000

    def __init__(self, metrics_log=None, replica_id: int = 0):
        self.metrics_log = metrics_log
        self.replica_id = replica_id
        self.violations: List[Violation] = []
        self.events: deque = deque(maxlen=self.MAX_EVENTS)
        self.events_total = 0
        self._seq = 0
        self._span = 0
        self.shadows: List[AllocatorShadow] = []

    def attach(self, allocator, name: str = "pool",
               resolve_rid=None) -> AllocatorShadow:
        """Install a shadow on ``allocator`` and return it. Idempotent
        per allocator (re-attach replaces, ledger state reset)."""
        shadow = AllocatorShadow(self, allocator, name)
        if resolve_rid is not None:
            shadow.resolve_rid = resolve_rid
        self.shadows = [
            s for s in self.shadows if s.allocator is not allocator
        ] + [shadow]
        allocator.sanitizer = shadow
        return shadow

    def assert_clean(self) -> None:
        """Raise :class:`BlockSanError` listing every recorded
        violation — the CI smoke leg's one-call gate."""
        if not self.violations:
            return
        lines = [
            f"  [{v.kind}] block={v.block} owner={v.owner} rid={v.rid} "
            f"site={v.site}: {v.detail}"
            for v in self.violations
        ]
        raise BlockSanError(
            f"blocksan recorded {len(self.violations)} violation(s):\n"
            + "\n".join(lines)
        )

    def summary(self) -> dict:
        """Rollup for ``metrics()`` surfaces."""
        by_kind: Dict[str, int] = {}
        for v in self.violations:
            by_kind[v.kind] = by_kind.get(v.kind, 0) + 1
        return {
            "blocksan_violations": len(self.violations),
            "blocksan_events": self.events_total,
            "blocksan_by_kind": by_kind,
        }

    # ---- internals ----

    def _next_seq(self) -> int:
        self._seq += 1
        return self._seq

    def _next_span(self) -> int:
        self._span += 1
        return self._span

    def _record_event(self, ev: LedgerEvent) -> None:
        self.events.append(ev)
        self.events_total += 1

    def _record_violation(self, shadow: AllocatorShadow,
                          v: Violation) -> None:
        self.violations.append(v)
        if self.metrics_log is not None:
            self.metrics_log.log(
                kind="sanitizer", ev="violation", **{"class": v.kind},
                block=v.block, owner=v.owner, rid=v.rid, site=v.site,
                detail=v.detail, shadow=shadow.name,
                replica_id=self.replica_id,
            )

    def _log_quiesce(self, shadow: AllocatorShadow,
                     found: List[Violation]) -> None:
        if self.metrics_log is not None:
            self.metrics_log.log(
                kind="sanitizer", ev="quiesce", ok=not found,
                violations=len(found), live_blocks=len(shadow.refs),
                shadow=shadow.name, replica_id=self.replica_id,
            )
