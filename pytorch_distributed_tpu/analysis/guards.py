"""Runtime companions to the static lint: catch what the AST cannot see.

``no_recompile`` wraps an already-jitted step function and turns the two
silent per-step perf killers into hard failures after a warmup window:

- **recompiles**: the jit cache must stop growing once the step has seen
  its steady-state shapes/dtypes (warmup covers the first trace and a
  donation/layout retrace). Any later cache miss raises
  ``GuardViolation`` naming the step at which it happened.
- **host transfers**: after warmup every call runs under
  ``jax.transfer_guard("disallow")`` — an *implicit* transfer (the
  classic bug: a numpy batch sneaking into the compiled step, re-paying
  H2D every iteration) raises immediately, while explicit
  ``device_put``/``device_get``/``float()`` conversions outside the step
  stay legal (those inside the step's call tree are the static
  ``host-transfer`` rule's jurisdiction).

Usage::

    step = analysis.no_recompile(make_lm_train_step(mesh, ...))
    for batch in loader:
        state, metrics = step(state, batch)   # raises on hazard growth
    step.stats  # GuardStats(calls=..., cache_size=..., recompiles=...)

The multihost capability probe (``backend_supports_multiprocess``) lives
here too: the jaxlib CPU backend cannot compile cross-process collectives
at all ("Multiprocess computations aren't implemented on the CPU
backend"), which is the triaged root cause of the xfail'd
``tests/test_multihost.py`` cases — see ANALYSIS.md.
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
from typing import Any, Callable, Optional


class GuardViolation(AssertionError):
    """A runtime hazard the static lint cannot prove: a recompile or a
    host transfer after the warmup window."""


@dataclasses.dataclass
class GuardStats:
    calls: int = 0
    warmup_steps: int = 2
    cache_size: Optional[int] = None
    recompiles_after_warmup: int = 0


def _jit_cache_size(fn) -> Optional[int]:
    probe = getattr(fn, "_cache_size", None)
    if callable(probe):
        try:
            return int(probe())
        except Exception:
            return None
    return None


def no_recompile(
    step_fn: Callable[..., Any],
    warmup_steps: int = 2,
    guard_transfers: bool = True,
) -> Callable[..., Any]:
    """Wrap a jitted step: assert-fail on cache growth or implicit host
    transfers after ``warmup_steps`` calls.

    ``step_fn`` must be the object ``jax.jit`` returned (it carries the
    compile-cache probe); wrapping an arbitrary Python function would have
    nothing to measure and raises ``TypeError`` up front.
    """
    import jax

    if _jit_cache_size(step_fn) is None:
        raise TypeError(
            "no_recompile needs the jit-compiled callable itself (the "
            "object jax.jit returned); got "
            f"{getattr(step_fn, '__name__', step_fn)!r} with no jit cache "
            "to watch"
        )
    stats = GuardStats(warmup_steps=warmup_steps)

    @functools.wraps(step_fn)
    def guarded(*args, **kwargs):
        stats.calls += 1
        armed = stats.calls > warmup_steps
        guard = (
            jax.transfer_guard("disallow")
            if (armed and guard_transfers)
            else contextlib.nullcontext()
        )
        try:
            with guard:
                out = step_fn(*args, **kwargs)
        except Exception as e:  # re-raise transfer-guard trips as ours
            if "transfer" in type(e).__name__.lower() or "Disallowed" in str(e):
                raise GuardViolation(
                    f"implicit host transfer inside the step at call "
                    f"{stats.calls} (after {warmup_steps} warmup steps): "
                    f"{e}"
                ) from e
            raise
        size = _jit_cache_size(step_fn)
        if size is not None:
            if (
                armed
                and stats.cache_size is not None
                and size > stats.cache_size
            ):
                stats.recompiles_after_warmup += size - stats.cache_size
                raise GuardViolation(
                    f"jit cache grew {stats.cache_size} -> {size} at call "
                    f"{stats.calls} (after {warmup_steps} warmup steps): "
                    f"the step retraced — look for shape/dtype drift in "
                    f"the batch, or Python values baked into the closure"
                )
            stats.cache_size = size
        return out

    guarded.stats = stats
    return guarded


def backend_supports_multiprocess() -> bool:
    """True when the active jax backend can compile multi-process
    computations. The stock jaxlib CPU backend cannot (XlaRuntimeError:
    "Multiprocess computations aren't implemented on the CPU backend"),
    so localhost 2-process rendezvous tests xfail there — probing for
    real requires spawning a second process, so this only rules out the
    known-incapable case."""
    import jax

    try:
        platform = jax.default_backend()
    except Exception:
        return False
    if platform == "cpu":
        return False
    return True
