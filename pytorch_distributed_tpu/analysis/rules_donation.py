"""Rule family: donation — dataflow over donated jit buffers.

``donate_argnums`` is how every hot path in this repo stays at one
allocation (the KV pool, the logits buffer, TrainState, the metrics
ring): the donated input's buffer is reused for the output. The flip
side is a hazard jax only reports lazily (a ``Deleted buffer`` error on
some later access, often far from the donating call) or not at all (on
backends that copy): reading a buffer AFTER donating it. This family
runs an intraprocedural (per-function, per-module) dataflow over every
jit call site whose ``donate_argnums``/``donate_argnames`` the lint can
see:

- ``donation-use-after-donate`` (error): a variable or ``self.attr``
  passed at a donated position is read again after the donating call
  without being rebound (the repo's idiom rebinds it from the result in
  the same statement: ``self.cache, self.logits = fn(..., self.cache,
  self.logits, ...)``). A donating call inside a loop whose donated
  operand is never rebound in the loop body is the same bug one
  iteration later and is flagged at the call.
- ``donation-alias`` (error): the same buffer expression appears at two
  argument positions of one donating call with at least one of them
  donated — the donated buffer is aliased, so the other reference is
  invalidated mid-call (jax raises on some backends, silently copies on
  others).
- ``donation-none-hot-loop`` (warning): a call to a KNOWN jitted
  callable that donates nothing, inside a ``for``/``while`` loop, whose
  result rebinds one of its own arguments — the carry idiom
  (``state = step(state, batch)``) paying a full output allocation per
  iteration that ``donate_argnums`` would eliminate.

Donation signatures are resolved through the repo's builder idioms: a
direct ``fn = jax.jit(body, donate_argnums=...)``, the attribute form
``self._push = jax.jit(...)``, builder functions/methods that *return* a
jitted callable (``def _chunk_fn(...): fn = jax.jit(body, ...); return
fn``), and chained builder calls (``self._import_fn(n)(...)``). Name
resolution is lexically scoped (innermost function first, then module
scope); ``self.X`` signatures are scoped per class.

Known false-negative boundary (ANALYSIS.md "jaxlint v2"): the analysis
is intraprocedural — a donated ``self.cache`` read from a *different*
method, or a jitted callable built in one module and called from
another, is out of static reach. The runtime companions (token-identity
tests, ``no_recompile``) cover those.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from pytorch_distributed_tpu.analysis._astutil import (
    dotted,
    get_kwarg,
    int_constants,
    terminal_name,
)
from pytorch_distributed_tpu.analysis.core import (
    Finding,
    LintContext,
    ParsedModule,
    RuleInfo,
)

RULES = [
    RuleInfo(
        "donation-use-after-donate", "error",
        "buffer read again after being passed at a donated jit position",
        "A buffer passed at a donate_argnums/donate_argnames position is "
        "consumed by the call: its underlying memory becomes the "
        "output's. Reading the old reference afterwards is "
        "use-after-free dressed as numerics — jax raises a deleted-"
        "buffer error on some backends and silently copies on others, "
        "so the bug ships quietly on CPU and detonates on TPU. The fix "
        "is the repo's standard idiom: rebind the donated reference "
        "from the call's result in the same statement (self.cache, "
        "self.logits = fn(..., self.cache, self.logits, ...)). A "
        "donating call in a loop must rebind its donated operand "
        "somewhere in the loop body, or the next iteration re-passes a "
        "dead buffer. The analysis is intraprocedural: reads from other "
        "methods/modules are out of scope (documented false-negative "
        "boundary).",
    ),
    RuleInfo(
        "donation-alias", "error",
        "same buffer passed twice to one donating call (donated alias)",
        "One call passing the same variable/attribute at two argument "
        "positions, at least one donated, aliases the donated buffer: "
        "the callee receives two views of memory the donation is about "
        "to recycle. jax rejects some of these at dispatch and silently "
        "copies others — either way the program is not expressing what "
        "it means. Pass distinct buffers, or drop the donation (see "
        "ops/metrics.py's four-distinct-zeros construction for the "
        "pytree variant of this bug).",
    ),
    RuleInfo(
        "donation-none-hot-loop", "warning",
        "loop-carried jit call donates nothing — one dead allocation "
        "per iteration",
        "A jitted callable invoked in a for/while loop whose result "
        "rebinds one of its own arguments is a carry chain (state = "
        "step(state, batch)). Without donate_argnums the output cannot "
        "reuse the input's buffer, so every iteration allocates a full "
        "new carry and frees the old one — at training-state sizes this "
        "is real HBM churn and allocator pressure on the hot path. Mark "
        "the carried argument donated (and keep rebinding from the "
        "result). Flagged only for callables whose jit construction is "
        "visible in the same module; perf warning, not a correctness "
        "error.",
    ),
]

#: donation signature: (donated positional indices, donated kwarg names);
#: ((), ()) means "known-jitted, donates nothing" — tracked for the
#: hot-loop warning.
Sig = Tuple[Tuple[int, ...], Tuple[str, ...]]

_NONE_SIG: Sig = ((), ())


def _jit_sig(call: ast.Call) -> Optional[Sig]:
    """Donation signature if ``call`` is a jit/pjit construction."""
    if terminal_name(call) not in ("jit", "pjit"):
        return None
    nums_node = get_kwarg(call, "donate_argnums")
    nums = tuple(int_constants(nums_node) or ()) if nums_node is not None else ()
    names_node = get_kwarg(call, "donate_argnames")
    names: Tuple[str, ...] = ()
    if names_node is not None:
        if isinstance(names_node, ast.Constant) and isinstance(
            names_node.value, str
        ):
            names = (names_node.value,)
        elif isinstance(names_node, (ast.Tuple, ast.List)):
            names = tuple(
                e.value for e in names_node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            )
    return (nums, names)


def _self_attr(node: ast.expr) -> Optional[str]:
    """'X' for a ``self.X`` expression, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


class _ModuleSigs:
    """Pass 1: where donation signatures are born in this module.

    ``fn_builders``  function/method name -> Sig, for defs that return a
                     jitted callable (directly or via a local name/attr).
    ``class_attrs``  class name -> {attr -> Sig} for ``self.X = jax.jit``.
    """

    def __init__(self, tree: ast.Module):
        self.fn_builders: Dict[str, Sig] = {}
        self.class_attrs: Dict[str, Dict[str, Sig]] = {}
        self._scan(tree, None)

    def _scan(self, node: ast.AST, class_name: Optional[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                self._scan(child, child.name)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                sig = self._builder_sig(child)
                if sig is not None:
                    self.fn_builders[child.name] = sig
                self._collect_attr_sigs(child, class_name)
                self._scan(child, class_name)
            else:
                self._scan(child, class_name)

    def _collect_attr_sigs(self, fn, class_name: Optional[str]):
        if class_name is None:
            return
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                sig = _jit_sig(node.value)
                if sig is None:
                    continue
                for t in node.targets:
                    attr = _self_attr(t)
                    if attr is not None:
                        self.class_attrs.setdefault(class_name, {})[attr] = sig

    @staticmethod
    def _builder_sig(fn) -> Optional[Sig]:
        """Sig when ``fn`` returns a jitted callable it constructs."""
        own = [n for stmt in fn.body for n in _own_nodes(stmt)]
        local: Dict[str, Sig] = {}
        attr_local: Dict[str, Sig] = {}
        for node in own:
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                sig = _jit_sig(node.value)
                if sig is None:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        local[t.id] = sig
                    else:
                        attr = _self_attr(t)
                        if attr is not None:
                            attr_local[attr] = sig
        for node in own:
            if not isinstance(node, ast.Return) or node.value is None:
                continue
            v = node.value
            if isinstance(v, ast.Call):
                sig = _jit_sig(v)
                if sig is not None:
                    return sig
            elif isinstance(v, ast.Name) and v.id in local:
                return local[v.id]
            else:
                attr = _self_attr(v)
                if attr is not None and attr in attr_local:
                    return attr_local[attr]
        return None


# ---- pass 2: per-scope event analysis --------------------------------------


class _Event:
    """One linearized statement with branch context."""

    __slots__ = ("stmt", "path", "loops", "index")

    def __init__(self, stmt, path, loops, index):
        self.stmt = stmt
        self.path = path    # tuple of (id(If-node), arm) ancestors
        self.loops = loops  # tuple of enclosing For/While nodes
        self.index = index


def _linearize(body: Sequence[ast.stmt]) -> List[_Event]:
    events: List[_Event] = []

    def walk(block, path, loops):
        for stmt in block:
            events.append(_Event(stmt, path, loops, len(events)))
            if isinstance(stmt, ast.If):
                walk(stmt.body, path + ((id(stmt), 0),), loops)
                walk(stmt.orelse, path + ((id(stmt), 1),), loops)
            elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                walk(stmt.body, path, loops + (stmt,))
                walk(stmt.orelse, path, loops)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                walk(stmt.body, path, loops)
            elif isinstance(stmt, ast.Try):
                walk(stmt.body, path, loops)
                for h in stmt.handlers:
                    walk(h.body, path, loops)
                walk(stmt.orelse, path, loops)
                walk(stmt.finalbody, path, loops)

    walk(body, (), ())
    return events


def _own_nodes(stmt: ast.stmt):
    """Walk a statement WITHOUT descending into nested defs/classes/
    lambdas (their bodies execute at some other time — analyzed as their
    own scopes, or deliberately out of reach for lambdas), and WITHOUT
    descending into compound-statement bodies — those are separate
    events of the linearization; this yields only the statement's own
    header (an If's test, a For's target/iter, a With's items)."""
    if isinstance(
        stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
    ):
        return
    if isinstance(stmt, (ast.If, ast.While)):
        roots: List[ast.AST] = [stmt.test]
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        roots = [stmt.target, stmt.iter]
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        roots = [i.context_expr for i in stmt.items]
        roots += [i.optional_vars for i in stmt.items if i.optional_vars]
    elif isinstance(stmt, ast.Try):
        roots = []
    else:
        roots = [stmt]
    stack = list(roots)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda),
            ):
                continue
            stack.append(child)


def _chains_read(stmt: ast.stmt) -> Set[str]:
    out: Set[str] = set()
    for node in _own_nodes(stmt):
        if isinstance(node, (ast.Name, ast.Attribute)) and isinstance(
            getattr(node, "ctx", None), ast.Load
        ):
            d = dotted(node)
            if d is not None:
                out.add(d)
    return out


def _target_chains(t: ast.expr) -> Set[str]:
    if isinstance(t, (ast.Tuple, ast.List)):
        out: Set[str] = set()
        for e in t.elts:
            out |= _target_chains(e)
        return out
    if isinstance(t, ast.Starred):
        return _target_chains(t.value)
    d = dotted(t)
    return {d} if d is not None else set()


def _chains_rebound(stmt: ast.stmt) -> Set[str]:
    out: Set[str] = set()
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return _target_chains(stmt.target)
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            if item.optional_vars is not None:
                out |= _target_chains(item.optional_vars)
        return out
    for node in _own_nodes(stmt):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                out |= _target_chains(t)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            out |= _target_chains(node.target)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                out |= _target_chains(t)
    return out


def _compatible(donate_path, other_path) -> bool:
    """Can control flow reach ``other`` from ``donate`` branch-wise?
    Divergent arms of one If are mutually unreachable."""
    for (if_id, arm) in donate_path:
        for (oid, oarm) in other_path:
            if oid == if_id and oarm != arm:
                return False
    return True


def _is_terminal(stmt: ast.stmt) -> bool:
    return isinstance(stmt, (ast.Return, ast.Raise, ast.Break, ast.Continue))


class _DonationScope:
    """One function (or the module body): resolve callables, then walk
    the linearized statements for the three donation hazards."""

    def __init__(self, mod: ParsedModule, sigs: _ModuleSigs,
                 findings: List[Finding]):
        self.mod = mod
        self.sigs = sigs
        self.findings = findings

    def analyze(self, body: Sequence[ast.stmt], scopes: List[Dict[str, Sig]],
                class_name: Optional[str]):
        local = self._local_names(body, scopes, class_name)
        scopes = scopes + [local]
        events = _linearize(body)
        for ev in events:
            for call in self._donating_calls(ev.stmt, scopes, class_name):
                self._check_call(call[0], call[1], ev, events, class_name)
        # nested defs/classes see this scope's names
        for ev in events:
            stmt = ev.stmt
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.analyze(stmt.body, scopes, class_name)
            elif isinstance(stmt, ast.ClassDef):
                for sub in stmt.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        self.analyze(sub.body, scopes, stmt.name)

    # -- callable resolution ------------------------------------------------

    def _local_names(self, body, scopes, class_name) -> Dict[str, Sig]:
        """Names bound in THIS scope to jitted callables: direct jit
        assignments, builder-call results, and aliases of donating
        self-attrs."""
        local: Dict[str, Sig] = {}
        for ev in _linearize(list(body)):
            stmt = ev.stmt
            for node in _own_nodes(stmt):
                if not isinstance(node, ast.Assign):
                    continue
                sig = self._value_sig(node.value, scopes + [local], class_name)
                if sig is None:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        local[t.id] = sig
        return local

    def _value_sig(self, value: ast.expr, scopes, class_name) -> Optional[Sig]:
        """Donation signature of an assigned value, if it is a jitted
        callable we can see: jax.jit(...), a builder call, or an alias
        of a donating self-attr."""
        if isinstance(value, ast.Call):
            sig = _jit_sig(value)
            if sig is not None:
                return sig
            return self._callee_builder_sig(value, class_name)
        attr = _self_attr(value)
        if attr is not None and class_name is not None:
            return self.sigs.class_attrs.get(class_name, {}).get(attr)
        return None

    def _callee_builder_sig(self, call: ast.Call, class_name) -> Optional[Sig]:
        """Sig when ``call`` invokes a builder that returns a jitted fn."""
        f = call.func
        if isinstance(f, ast.Name):
            return self.sigs.fn_builders.get(f.id)
        attr = _self_attr(f)
        if attr is not None:
            return self.sigs.fn_builders.get(attr)
        return None

    def _resolve_callee(self, call: ast.Call, scopes, class_name) -> Optional[Sig]:
        f = call.func
        if isinstance(f, ast.Name):
            for scope in reversed(scopes):
                if f.id in scope:
                    return scope[f.id]
            return None
        attr = _self_attr(f)
        if attr is not None and class_name is not None:
            return self.sigs.class_attrs.get(class_name, {}).get(attr)
        if isinstance(f, ast.Call):
            # chained builder: self._import_fn(n)(args...)
            return self._callee_builder_sig(f, class_name)
        return None

    def _donating_calls(self, stmt, scopes, class_name):
        out = []
        for node in _own_nodes(stmt):
            if isinstance(node, ast.Call):
                sig = self._resolve_callee(node, scopes, class_name)
                if sig is not None:
                    out.append((node, sig))
        return out

    # -- the checks ---------------------------------------------------------

    def _donated_chains(self, call: ast.Call, sig: Sig) -> List[str]:
        nums, kwnames = sig
        chains: List[str] = []
        for i in nums:
            if 0 <= i < len(call.args) and not any(
                isinstance(a, ast.Starred) for a in call.args[: i + 1]
            ):
                d = dotted(call.args[i])
                if d is not None:
                    chains.append(d)
        for kw in call.keywords:
            if kw.arg in kwnames:
                d = dotted(kw.value)
                if d is not None:
                    chains.append(d)
        return chains

    def _check_call(self, call: ast.Call, sig: Sig, ev: _Event,
                    events: List[_Event], class_name) -> None:
        donated = self._donated_chains(call, sig)
        rebound_here = _chains_rebound(ev.stmt)

        if not donated:
            self._check_hot_loop(call, sig, ev, rebound_here)
            return

        # alias: a donated chain appearing anywhere else in the same call
        all_args = [dotted(a) for a in call.args] + [
            dotted(kw.value) for kw in call.keywords
        ]
        for chain in set(donated):
            count = sum(1 for d in all_args if d == chain)
            if count > 1 or donated.count(chain) > 1:
                self.findings.append(Finding(
                    "donation-alias", "error", self.mod.path, call.lineno,
                    f"{chain!r} is passed {count} times to one donating "
                    f"call with a donated position among them — the "
                    f"donated buffer is aliased; pass distinct buffers "
                    f"or drop the donation",
                ))

        # use-after-donate, linear scan with branch compatibility
        for chain in dict.fromkeys(donated):  # ordered unique
            if chain in rebound_here:
                continue  # consumed correctly at the donating statement
            self._scan_after(chain, call, ev, events)
            self._check_loop_rebind(chain, call, ev, events)

    def _scan_after(self, chain: str, call: ast.Call, ev: _Event,
                    events: List[_Event]) -> None:
        for later in events[ev.index + 1:]:
            if not _compatible(ev.path, later.path):
                continue
            read_here = chain in _chains_read(later.stmt)
            if not read_here:
                if chain in _chains_rebound(later.stmt):
                    return  # rebound before any read we could prove
                # a return/raise in the donate's own arm ends its flow
                if later.path == ev.path and _is_terminal(later.stmt):
                    return
                continue
            if read_here:
                self.findings.append(Finding(
                    "donation-use-after-donate", "error", self.mod.path,
                    later.stmt.lineno,
                    f"{chain!r} was donated to the jit call at line "
                    f"{call.lineno} and is read here without being "
                    f"rebound — its buffer now belongs to that call's "
                    f"output (rebind it from the result: "
                    f"`{chain}, ... = fn(..., {chain}, ...)`)",
                ))
                return  # one finding per donated chain
        return

    def _check_loop_rebind(self, chain: str, call: ast.Call, ev: _Event,
                           events: List[_Event]) -> None:
        if not ev.loops:
            return
        loop = ev.loops[-1]
        for other in events:
            if other.loops and loop in other.loops and chain in _chains_rebound(
                other.stmt
            ):
                return
        self.findings.append(Finding(
            "donation-use-after-donate", "error", self.mod.path, call.lineno,
            f"{chain!r} is donated inside this loop but never rebound in "
            f"the loop body — the next iteration re-passes a buffer the "
            f"previous call already consumed",
        ))

    def _check_hot_loop(self, call: ast.Call, sig: Sig, ev: _Event,
                        rebound_here: Set[str]) -> None:
        if sig != _NONE_SIG or not ev.loops:
            return
        arg_chains = {d for d in (dotted(a) for a in call.args) if d}
        carried = sorted(arg_chains & rebound_here)
        if carried:
            self.findings.append(Finding(
                "donation-none-hot-loop", "warning", self.mod.path,
                call.lineno,
                f"loop-carried jit call rebinds its own argument(s) "
                f"{carried} but donates nothing — every iteration "
                f"allocates a fresh carry; add donate_argnums for the "
                f"carried buffer(s)",
            ))


def check_donation(mod: ParsedModule, ctx: LintContext) -> List[Finding]:
    sigs = _ModuleSigs(mod.tree)
    findings: List[Finding] = []
    scope = _DonationScope(mod, sigs, findings)
    scope.analyze(mod.tree.body, [], None)
    return findings


CHECK = check_donation
CROSS_MODULE = False
