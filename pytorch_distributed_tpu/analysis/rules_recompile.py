"""Rule: recompile hazards around jit/pjit.

Silent recompilation is the stealth perf killer of the pjit stack: the
step "works" but retraces every call, so a 50ms step becomes a 30s one and
nobody gets an error. Four statically-checkable shapes of the bug:

- ``recompile-traced-branch`` (error): a Python ``if``/``while`` on an
  argument of a jit-compiled function. Arguments are tracers; branching on
  one either raises TracerBoolConversionError or — when the value is
  concrete because the arg was marked static — recompiles per value.
- ``recompile-jit-call`` (warning): ``jax.jit(f)(x)`` invoked in one
  expression inside a function body. The returned compiled function is
  dropped on the floor, so every call pays a fresh trace+compile.
- ``recompile-mutable-closure`` (warning): a jit-compiled function reads a
  module-level list/dict/set that the module mutates elsewhere. jit
  captures closures at trace time; later mutations are silently ignored
  (stale constants) or, for hashable wrappers, retrigger tracing.
- ``recompile-static-argnums`` (error): ``static_argnums`` indices out of
  range of the target's signature, overlapping ``donate_argnums``, or
  marking a parameter whose default is a non-hashable list/dict/set —
  every call with such a value raises or recompiles.

jit targets are found through direct decorators (``@jax.jit``,
``@partial(jax.jit, ...)``) and through call chains in the same scope
(``jax.jit(shard_map(_local_step, ...))`` and the two-statement spelling
``sharded = shard_map(_local_step, ...); jax.jit(sharded)``) — the idiom
every step builder in ``train/`` uses.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from pytorch_distributed_tpu.analysis._astutil import (
    assigned_name_targets,
    get_kwarg,
    import_map,
    int_constants,
    param_names,
    terminal_name,
    walk_functions,
)
from pytorch_distributed_tpu.analysis.core import (
    Finding,
    LintContext,
    ParsedModule,
    RuleInfo,
)

RULES = [
    RuleInfo(
        "recompile-traced-branch", "error",
        "Python if/while on a traced argument of a jit-compiled function",
        "Arguments of a jit-compiled function are tracers: a Python "
        "if/while on one either raises TracerBoolConversionError or — "
        "when the argument is marked static — silently compiles once per "
        "value. Use lax.cond/jnp.where, or mark the argument static and "
        "accept one compile per value. Closures over builder parameters "
        "are static at trace time and exempt, as are 'is None' checks "
        "and isinstance/len-style shape predicates. jit targets are "
        "found through decorators and the builder idiom "
        "jax.jit(shard_map(_local_step, ...)).",
    ),
    RuleInfo(
        "recompile-jit-call", "warning",
        "jax.jit(...)(...) invoked immediately inside a function — the "
        "compile cache is discarded every call",
        "jax.jit(f)(x) in one expression inside a function body drops "
        "the compiled callable (and its cache) on the floor after the "
        "call, so every call pays a fresh trace+compile. Hoist the jit "
        "out of the per-call path (module scope or a cached builder).",
    ),
    RuleInfo(
        "recompile-mutable-closure", "warning",
        "jit-compiled function closes over a module-level mutable that "
        "the module mutates elsewhere",
        "jit captures closures at trace time: a module-level list/dict/"
        "set read inside a jitted function is frozen at the first call, "
        "so later mutations are silently ignored (stale constants) or, "
        "for hashable wrappers, retrigger tracing. Pass the value as an "
        "argument instead.",
    ),
    RuleInfo(
        "recompile-static-argnums", "error",
        "static_argnums out of range, overlapping donate_argnums, or "
        "marking a non-hashable (list/dict-default) parameter",
        "static_argnums indices out of range of the target's signature "
        "raise at call time; overlap with donate_argnums is "
        "contradictory (a static argument is part of the jit cache key "
        "and cannot be donated); a static parameter whose default is a "
        "non-hashable list/dict/set raises or recompiles on every call "
        "that uses the default.",
    ),
]

_JIT_NAMES = ("jit", "pjit")
_WRAPPER_NAMES = ("shard_map", "partial", "wraps", "pmap")
_STATIC_TEST_CALLS = {
    "isinstance", "callable", "hasattr", "getattr", "len", "issubclass",
}


def _is_jit_call(call: ast.Call, imports: Dict[str, str]) -> bool:
    name = terminal_name(call)
    if name not in _JIT_NAMES:
        return False
    # accept jax.jit / pjit.pjit / bare jit imported from jax
    d = call.func
    if isinstance(d, ast.Name):
        origin = imports.get(d.id, "")
        return origin in ("jax.jit", "jax.experimental.pjit.pjit", "jit",
                          "pjit") or origin.endswith(f".{name}")
    return True  # attribute form like jax.jit / pjit.pjit


def _jit_target_defs(
    mod: ParsedModule, imports: Dict[str, str]
) -> Dict[int, Tuple[ast.FunctionDef, ast.Call]]:
    """id(def node) -> (def node, jit call) for every local def that ends
    up jitted.

    Resolution follows Name arguments through assignments and wrapper
    calls (shard_map/partial) with real lexical scoping — innermost scope
    first — so two nested helpers sharing a name never cross-resolve.
    """
    out: Dict[int, Tuple[ast.FunctionDef, ast.Call]] = {}

    def scope_tables(body) -> Tuple[Dict[str, ast.FunctionDef], Dict[str, ast.expr]]:
        defs: Dict[str, ast.FunctionDef] = {}
        assigns: Dict[str, ast.expr] = {}
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs[stmt.name] = stmt
            elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                t = stmt.targets[0]
                if isinstance(t, ast.Name):
                    assigns[t.id] = stmt.value
            # scan one level into compound statements (if/try/with/for):
            # assignments there are visible in the same scope
            for sub in ast.iter_child_nodes(stmt):
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                    t = sub.targets[0]
                    if isinstance(t, ast.Name):
                        assigns.setdefault(t.id, sub.value)
        return defs, assigns

    def chase(expr, scopes, depth: int = 0) -> Optional[ast.FunctionDef]:
        if depth > 6 or expr is None:
            return None
        if isinstance(expr, ast.Name):
            for defs, assigns in reversed(scopes):
                if expr.id in defs:
                    return defs[expr.id]
                if expr.id in assigns:
                    return chase(assigns[expr.id], scopes, depth + 1)
            return None
        if isinstance(expr, ast.Call):
            name = terminal_name(expr)
            if name in _WRAPPER_NAMES:
                if expr.args:
                    return chase(expr.args[0], scopes, depth + 1)
                f = get_kwarg(expr, "f") or get_kwarg(expr, "fun")
                if f is not None:
                    return chase(f, scopes, depth + 1)
        return None

    def visit(body, scopes):
        tables = scope_tables(body)
        scopes = scopes + [tables]
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue  # visited via their own scope below
                if (
                    isinstance(node, ast.Call)
                    and _is_jit_call(node, imports)
                    and node.args
                ):
                    target = chase(node.args[0], scopes)
                    if target is not None:
                        out[id(target)] = (target, node)
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                visit(stmt.body, scopes)

    # NB: ast.walk above still descends into nested defs from the outer
    # statement — acceptable: a jit call inside a nested def sees the
    # outer scopes, and name shadowing resolves innermost-first when the
    # nested def is visited with its own scope pushed.
    visit(mod.tree.body, [])

    # decorator form
    for fn, _stack in walk_functions(mod.tree):
        for dec in fn.decorator_list:
            if isinstance(dec, ast.Call) and _is_jit_call(dec, imports):
                out[id(fn)] = (fn, dec)
            elif isinstance(dec, ast.Call) and terminal_name(dec) == "partial":
                if dec.args and isinstance(dec.args[0], (ast.Attribute, ast.Name)):
                    inner = ast.Call(func=dec.args[0], args=[], keywords=dec.keywords)
                    ast.copy_location(inner, dec)
                    if _is_jit_call(inner, imports):
                        out[id(fn)] = (fn, dec)
            elif isinstance(dec, (ast.Attribute, ast.Name)):
                probe = ast.Call(func=dec, args=[], keywords=[])
                ast.copy_location(probe, dec)
                if _is_jit_call(probe, imports):
                    out[id(fn)] = (fn, probe)
    return out


def _static_param_names(fn: ast.FunctionDef, jit_call: ast.Call) -> Set[str]:
    names: Set[str] = set()
    params = param_names(fn)
    nums = get_kwarg(jit_call, "static_argnums")
    if nums is not None:
        for i in int_constants(nums) or []:
            if 0 <= i < len(params):
                names.add(params[i])
    argnames = get_kwarg(jit_call, "static_argnames")
    if argnames is not None:
        if isinstance(argnames, ast.Constant) and isinstance(argnames.value, str):
            names.add(argnames.value)
        elif isinstance(argnames, (ast.Tuple, ast.List)):
            names.update(
                e.value for e in argnames.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            )
    return names


def _names_in_test(test: ast.expr) -> Set[str]:
    """Bare Names the branch condition genuinely depends on as VALUES.

    Excludes attribute/subscript bases (``state.batch_stats`` truthiness is
    a static container check), ``is``/``is not`` comparisons, and arguments
    of shape/type predicates (isinstance, len, ...).
    """
    out: Set[str] = set()

    def visit(node: ast.expr):
        if isinstance(node, ast.Name):
            out.add(node.id)
        elif isinstance(node, ast.BoolOp):
            for v in node.values:
                visit(v)
        elif isinstance(node, ast.UnaryOp):
            visit(node.operand)
        elif isinstance(node, ast.Compare):
            ops_ok = all(
                not isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
            )
            if ops_ok:
                visit(node.left)
                for c in node.comparators:
                    visit(c)
        elif isinstance(node, ast.BinOp):
            visit(node.left)
            visit(node.right)
        elif isinstance(node, ast.Call):
            if terminal_name(node) not in _STATIC_TEST_CALLS:
                for a in node.args:
                    visit(a)
        # Attribute/Subscript: deliberately not descended

    visit(test)
    return out


def _module_mutable_globals(mod: ParsedModule) -> Set[str]:
    """Module-level names bound to mutable literals AND mutated somewhere."""
    mutable: Set[str] = set()
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and isinstance(
            node.value, (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp)
        ):
            mutable.update(assigned_name_targets(node))
    if not mutable:
        return set()
    mutated: Set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            base = node.func.value
            if isinstance(base, ast.Name) and base.id in mutable:
                if node.func.attr in (
                    "append", "extend", "insert", "pop", "update", "clear",
                    "setdefault", "add", "remove", "discard",
                ):
                    mutated.add(base.id)
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for t in targets:
                if isinstance(t, ast.Subscript) and isinstance(t.value, ast.Name):
                    if t.value.id in mutable:
                        mutated.add(t.value.id)
        elif isinstance(node, ast.Global):
            mutated.update(n for n in node.names if n in mutable)
    return mutable & mutated


def check_recompile_hazards(mod: ParsedModule, ctx: LintContext) -> List[Finding]:
    imports = import_map(mod.tree)
    findings: List[Finding] = []
    jitted = _jit_target_defs(mod, imports)
    mutable_globals = _module_mutable_globals(mod)

    # --- per jitted def: traced branches, mutable closures, static args ---
    for fn, jit_call in jitted.values():
        name = fn.name
        params = set(param_names(fn))
        static = _static_param_names(fn, jit_call)
        traced = params - static
        local_binds: Set[str] = set()
        for node in ast.walk(fn):
            local_binds.update(assigned_name_targets(node))

        for node in ast.walk(fn):
            if isinstance(node, (ast.If, ast.While)):
                hits = _names_in_test(node.test) & traced
                if hits:
                    kind = "while" if isinstance(node, ast.While) else "if"
                    findings.append(Finding(
                        "recompile-traced-branch", "error", mod.path,
                        node.lineno,
                        f"Python {kind} on traced argument(s) "
                        f"{sorted(hits)} of jit-compiled {name!r}: tracers "
                        f"cannot drive Python control flow (use lax.cond/"
                        f"jnp.where, or mark the argument static and accept "
                        f"one compile per value)",
                    ))
            elif isinstance(node, ast.Name) and node.id in mutable_globals:
                if node.id not in local_binds:
                    findings.append(Finding(
                        "recompile-mutable-closure", "warning", mod.path,
                        node.lineno,
                        f"jit-compiled {name!r} reads module-level mutable "
                        f"{node.id!r}, which this module mutates elsewhere; "
                        f"jit captures it at trace time, so later mutations "
                        f"are silently ignored — pass it as an argument",
                    ))

        # static_argnums sanity
        nums_node = get_kwarg(jit_call, "static_argnums")
        nums = int_constants(nums_node) if nums_node is not None else None
        donate_node = get_kwarg(jit_call, "donate_argnums")
        donate = int_constants(donate_node) if donate_node is not None else None
        n_params = len(param_names(fn))
        if nums:
            for i in nums:
                if i >= n_params or i < -n_params:
                    findings.append(Finding(
                        "recompile-static-argnums", "error", mod.path,
                        jit_call.lineno,
                        f"static_argnums={i} is out of range for {name!r} "
                        f"({n_params} parameter(s))",
                    ))
            if donate and set(nums) & set(donate):
                findings.append(Finding(
                    "recompile-static-argnums", "error", mod.path,
                    jit_call.lineno,
                    f"static_argnums and donate_argnums overlap on "
                    f"{sorted(set(nums) & set(donate))} for {name!r}: a "
                    f"static argument is part of the cache key and cannot "
                    f"be donated",
                ))
            # non-hashable default on a static parameter
            args = fn.args
            pos = args.posonlyargs + args.args
            offset = len(pos) - len(args.defaults)
            for i in nums:
                if 0 <= i < len(pos) and i >= offset:
                    default = args.defaults[i - offset]
                    if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                        findings.append(Finding(
                            "recompile-static-argnums", "error", mod.path,
                            jit_call.lineno,
                            f"static argument {pos[i].arg!r} of {name!r} "
                            f"defaults to a non-hashable "
                            f"{type(default).__name__.lower()}; static "
                            f"arguments are dict keys of the jit cache and "
                            f"must be hashable",
                        ))

    # --- jit-created-and-called-immediately inside a def ---
    for fn, _stack in walk_functions(mod.tree):
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Call)
                and _is_jit_call(node.func, imports)
            ):
                findings.append(Finding(
                    "recompile-jit-call", "warning", mod.path, node.lineno,
                    "jax.jit(...) built and invoked in one expression "
                    "inside a function: the compiled callable (and its "
                    "cache) is discarded after the call — hoist the jit "
                    "out of the per-call path",
                ))
    return findings


CHECK = check_recompile_hazards
CROSS_MODULE = False
