"""Rule: collective-axis — every lax collective names a declared mesh axis.

A mistyped axis name inside ``shard_map`` is the worst kind of SPMD bug:
jax raises only at trace time IF the axis is unbound, but a name that
happens to bind to the *wrong* axis (e.g. ``"model"`` where the gradient
combine meant ``"data"``) trains on wrong math with no error at all. This
rule checks, fully statically:

- ``collective-axis`` (error): the axis argument of every
  ``jax.lax.psum/pmean/pmax/pmin/psum_scatter/all_gather/ppermute/
  all_to_all/axis_index`` call resolves to a name declared by the mesh
  (``*_AXIS`` constants / ``Mesh(axis_names=...)``), an enclosing
  ``pmap(axis_name=...)``, or a ``shard_map`` in the same module.
- ``collective-axis-literal`` (warning): the axis is spelled as a raw
  string literal where a shared ``*_AXIS`` constant exists — the exact
  situation that lets call sites drift apart across hosts/modules.
- ``collective-axis-inconsistent`` (warning): within one function, the
  same collective op applied to the same operand resolves to two different
  axis sets — the "same logical collective, different axis name" hazard.

Axis arguments that cannot be resolved statically (values threaded through
call chains) are skipped, not guessed at.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from pytorch_distributed_tpu.analysis._astutil import (
    dotted,
    get_arg,
    get_kwarg,
    import_map,
    terminal_name,
)
from pytorch_distributed_tpu.analysis.core import (
    Finding,
    LintContext,
    ParsedModule,
    RuleInfo,
)

RULES = [
    RuleInfo(
        "collective-axis", "error",
        "collective uses an axis name no mesh/shard_map declares",
        "Every jax.lax.psum/pmean/pmax/pmin/psum_scatter/all_gather/"
        "ppermute/all_to_all/axis_index call must name an axis that is "
        "actually declared: a *_AXIS constant (the parallel/mesh.py grid "
        "data/seq/model), a Mesh(axis_names=...)/make_mesh literal, or a "
        "pmap(axis_name=...) in the same module. A mistyped axis that "
        "happens to bind to the WRONG axis trains on wrong math with no "
        "error at all. Axis arguments are resolved through constants, "
        "imports, tuples and parameter defaults; opaque values are "
        "skipped, never guessed.",
    ),
    RuleInfo(
        "collective-axis-literal", "warning",
        "collective spells a mesh axis as a string literal instead of the "
        "shared *_AXIS constant",
        "The axis exists but is spelled as a raw string where a shared "
        "*_AXIS constant is defined. Literal spellings are how call sites "
        "drift apart across modules and hosts — route the name through "
        "parallel.mesh.DATA_AXIS et al. so a rename is one edit.",
    ),
    RuleInfo(
        "collective-axis-inconsistent", "warning",
        "same collective op on the same operand uses two different axis "
        "names in one function",
        "Within one function, the same collective op applied to the same "
        "named operand resolves to two different axis sets — the 'same "
        "logical collective, different axis name' hazard left behind by "
        "mismatched refactors. One of the two sites is combining over "
        "the wrong axis.",
    ),
]

# op name -> (positional index of the axis argument, its keyword name)
COLLECTIVES: Dict[str, Tuple[int, str]] = {
    "psum": (1, "axis_name"),
    "pmean": (1, "axis_name"),
    "pmax": (1, "axis_name"),
    "pmin": (1, "axis_name"),
    "psum_scatter": (1, "axis_name"),
    "all_gather": (1, "axis_name"),
    "ppermute": (1, "axis_name"),
    "all_to_all": (1, "axis_name"),
    "pshuffle": (1, "axis_name"),
    "axis_index": (0, "axis_name"),
}


def _is_lax_collective(call: ast.Call, imports: Dict[str, str]) -> Optional[str]:
    name = terminal_name(call)
    if name not in COLLECTIVES:
        return None
    d = dotted(call.func)
    if d is None:
        return None
    head = d.split(".", 1)[0]
    resolved = d.replace(head, imports.get(head, head), 1)
    if resolved == f"jax.lax.{name}" or resolved.endswith(f".lax.{name}"):
        return name
    return None


def _module_constants(mod: ParsedModule) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Constant):
            if isinstance(node.value.value, str):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = node.value.value
    return out


def _local_declared_axes(mod: ParsedModule) -> set:
    """Axes declared inside this module: pmap(axis_name=...), Mesh/make_mesh
    axis_names=(...) with literal names."""
    axes = set()
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        name = terminal_name(node)
        if name == "pmap":
            v = get_kwarg(node, "axis_name")
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                axes.add(v.value)
        elif name in ("Mesh", "make_mesh"):
            v = get_kwarg(node, "axis_names")
            if v is None and name == "Mesh" and len(node.args) > 1:
                v = node.args[1]
            if isinstance(v, (ast.Tuple, ast.List)):
                for e in v.elts:
                    if isinstance(e, ast.Constant) and isinstance(e.value, str):
                        axes.add(e.value)
    return axes


class _AxisResolver:
    def __init__(self, mod: ParsedModule, ctx: LintContext):
        self.consts = _module_constants(mod)
        self.ctx = ctx

    def resolve(self, node: ast.expr, fn_stack) -> Optional[Tuple[Tuple[str, bool], ...]]:
        """-> tuple of (axis string, was_literal_here) or None if opaque.

        ``was_literal_here`` is True only for a string literal written
        directly at the call site (not one reached through a constant or a
        parameter default).
        """
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return ((node.value, True),)
        if isinstance(node, (ast.Tuple, ast.List)):
            out = []
            for e in node.elts:
                r = self.resolve(e, fn_stack)
                if r is None:
                    return None
                out.extend(r)
            return tuple(out)
        if isinstance(node, ast.Attribute):
            val = self.ctx.axis_constants.get(node.attr)
            return ((val, False),) if val is not None else None
        if isinstance(node, ast.Name):
            if node.id in self.consts:
                return ((self.consts[node.id], False),)
            if node.id in self.ctx.axis_constants:
                return ((self.ctx.axis_constants[node.id], False),)
            # a parameter of an enclosing def: trust its default value
            for fn in reversed(fn_stack):
                args = fn.args
                pos = args.posonlyargs + args.args
                defaults = args.defaults
                offset = len(pos) - len(defaults)
                for i, a in enumerate(pos):
                    if a.arg == node.id:
                        if i >= offset:
                            d = self.resolve(defaults[i - offset], fn_stack[:-1])
                            if d is not None:
                                # defaults are declarations, not call-site
                                # literals — never literal-warn through them
                                return tuple((ax, False) for ax, _ in d)
                        return None
                for a, d in zip(args.kwonlyargs, args.kw_defaults):
                    if a.arg == node.id:
                        if d is not None:
                            r = self.resolve(d, fn_stack[:-1])
                            if r is not None:
                                return tuple((ax, False) for ax, _ in r)
                        return None
        return None


def check_collective_axes(mod: ParsedModule, ctx: LintContext) -> List[Finding]:
    imports = import_map(mod.tree)
    resolver = _AxisResolver(mod, ctx)
    declared = ctx.mesh_axes | _local_declared_axes(mod)
    findings: List[Finding] = []

    # (enclosing fn, op, operand dump) -> (axes frozenset, line of first use)
    seen: Dict[Tuple[int, str, str], Tuple[frozenset, int]] = {}

    def visit(node: ast.AST, fn_stack):
        for child in ast.iter_child_nodes(node):
            child_stack = fn_stack
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                child_stack = fn_stack + [child]
            if isinstance(child, ast.Call):
                op = _is_lax_collective(child, imports)
                if op is not None:
                    handle(child, op, fn_stack)
            visit(child, child_stack)

    def handle(call: ast.Call, op: str, fn_stack):
        pos, kwname = COLLECTIVES[op]
        axis_node = get_arg(call, pos, kwname)
        if axis_node is None:
            return
        resolved = resolver.resolve(axis_node, fn_stack)
        if resolved is None:
            return
        for axis, literal_here in resolved:
            if axis not in declared:
                findings.append(Finding(
                    "collective-axis", "error", mod.path, call.lineno,
                    f"lax.{op} uses axis {axis!r}, which no mesh axis "
                    f"(*_AXIS constant / Mesh axis_names), pmap or "
                    f"shard_map declares — known axes: "
                    f"{sorted(declared)}",
                ))
            elif literal_here and axis in ctx.axis_constants.values():
                const = next(
                    k for k, v in ctx.axis_constants.items() if v == axis
                )
                findings.append(Finding(
                    "collective-axis-literal", "warning", mod.path,
                    call.lineno,
                    f"lax.{op} spells axis {axis!r} as a string literal; "
                    f"use the shared constant {const} so call sites cannot "
                    f"drift apart",
                ))
        # consistency: same op on the same named operand, different axes
        axes_set = frozenset(ax for ax, _ in resolved)
        if len(call.args) > 0 and isinstance(call.args[0], ast.Name):
            key = (
                id(fn_stack[-1]) if fn_stack else 0,
                op,
                call.args[0].id,
            )
            prior = seen.get(key)
            if prior is None:
                seen[key] = (axes_set, call.lineno)
            elif prior[0] != axes_set:
                findings.append(Finding(
                    "collective-axis-inconsistent", "warning", mod.path,
                    call.lineno,
                    f"lax.{op}({call.args[0].id}, ...) uses axes "
                    f"{sorted(axes_set)} here but {sorted(prior[0])} at "
                    f"line {prior[1]} — the same logical collective should "
                    f"name the same axis at every call site",
                ))

    visit(mod.tree, [])
    return findings


CHECK = check_collective_axes
CROSS_MODULE = False
