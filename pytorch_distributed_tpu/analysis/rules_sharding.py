"""Rule family: sharding — symbolic PartitionSpec checks on shard_map.

``shard_map``'s spec arguments are the SPMD contract: ``in_specs`` says
how each operand arrives split over the mesh, ``out_specs`` how results
are reassembled. jax validates them against *array ranks at trace time*,
but two whole classes of mistake survive until much later or forever:

- an axis name that exists on no mesh ("modle" for "model") raises only
  when the program is finally traced against a mesh missing it — or, if
  a mesh somewhere declares the typo'd name, never;
- an ``in_specs`` tuple whose arity drifted from the wrapped function's
  signature after a refactor fails at trace time with a pytree error
  three abstraction layers away from the edit;
- a ``P()`` entry silently replicates its operand onto every device —
  correct for tokens and flags, a capacity bug when the operand is the
  parameter tree or KV pool that sharding exists to split.

This family propagates ``PartitionSpec`` literals symbolically — through
the ``P`` import alias, ``*_AXIS`` constants, and module-level string
constants — and checks them against the declared mesh axes
(``parallel/mesh.py::MESH_AXES`` plus any ``Mesh``/``make_mesh``/
``pmap`` declaration in the scanned tree):

- ``sharding-unknown-axis`` (error): a spec names an axis no mesh
  declares.
- ``sharding-spec-arity`` (error): a literal ``in_specs`` tuple whose
  length differs from the wrapped function's positional signature, or a
  literal ``out_specs`` tuple whose length differs from the function's
  (consistent) tuple-return arity.
- ``sharding-replicated`` (warning): a literal ``P()`` entry in
  ``in_specs`` binding a parameter whose name says large carried state
  (params/state/cache/grads/weights/opt_state/pool/kv) while other
  operands ARE sharded — the "fell through to full replication" smell.

Specs reached through variables (``self._param_specs``) are opaque and
skipped, never guessed — the documented false-negative boundary.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from pytorch_distributed_tpu.analysis._astutil import (
    dotted,
    get_kwarg,
    import_map,
    terminal_name,
)
from pytorch_distributed_tpu.analysis.core import (
    Finding,
    LintContext,
    ParsedModule,
    RuleInfo,
)

RULES = [
    RuleInfo(
        "sharding-unknown-axis", "error",
        "PartitionSpec names a mesh axis no mesh declares",
        "Every axis name inside a PartitionSpec literal must be declared "
        "by the mesh: the parallel/mesh.py grid (data/seq/model via the "
        "*_AXIS constants), a Mesh(axis_names=...)/make_mesh literal, or "
        "a pmap axis in the scanned tree. A typo'd axis fails only when "
        "traced against a mesh that happens to miss it — and binds "
        "silently (to the WRONG axis) when some mesh declares the typo. "
        "Names are resolved through the P/PartitionSpec import alias, "
        "*_AXIS constants and module string constants; opaque values "
        "are skipped.",
    ),
    RuleInfo(
        "sharding-spec-arity", "error",
        "shard_map in_specs/out_specs arity disagrees with the wrapped "
        "function",
        "A literal in_specs tuple must carry exactly one spec per "
        "positional parameter of the wrapped function, and a literal "
        "out_specs tuple one spec per element of its (consistent) tuple "
        "return. Arity drift after a refactor surfaces as a pytree "
        "structure error at trace time, far from the edit; this check "
        "moves it to lint time. Functions resolved through the same "
        "assignment/wrapper chases as the recompile rules; non-literal "
        "specs and non-tuple returns are skipped.",
    ),
    RuleInfo(
        "sharding-replicated", "warning",
        "large carried operand falls to P() full replication in a "
        "sharded program",
        "A bare P() entry in in_specs replicates its operand onto every "
        "device of the mesh. That is correct for token ids, flags and "
        "scalars — and a silent capacity/traffic bug when the operand "
        "is the parameter tree, optimizer state, or KV pool the mesh "
        "exists to split: each device holds a full copy and the "
        "compiler inserts all-gathers nobody asked for. Flagged only "
        "when the bound parameter's name says large carried state "
        "(params/state/cache/grads/weights/opt_state/pool/kv) and at "
        "least one sibling operand IS sharded. Replication that is the "
        "design (TP-replicated logits) gets an inline suppression with "
        "its reason.",
    ),
]

_LARGE_PARAM_NAMES = {
    "params", "state", "cache", "grads", "grad", "weights", "opt_state",
    "pool", "kv",
}


def _spec_ctor_names(mod: ParsedModule) -> Set[str]:
    """Local names that construct PartitionSpec ('P', 'PartitionSpec')."""
    out = set()
    for name, origin in import_map(mod.tree).items():
        if origin.rsplit(".", 1)[-1] == "PartitionSpec":
            out.add(name)
    out.add("PartitionSpec")
    return out


def _module_str_constants(mod: ParsedModule) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Constant):
            if isinstance(node.value.value, str):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = node.value.value
    return out


def _local_declared_axes(mod: ParsedModule) -> Set[str]:
    axes: Set[str] = set()
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        name = terminal_name(node)
        if name == "pmap":
            v = get_kwarg(node, "axis_name")
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                axes.add(v.value)
        elif name in ("Mesh", "make_mesh"):
            v = get_kwarg(node, "axis_names")
            if v is None and name == "Mesh" and len(node.args) > 1:
                v = node.args[1]
            if isinstance(v, (ast.Tuple, ast.List)):
                for e in v.elts:
                    if isinstance(e, ast.Constant) and isinstance(e.value, str):
                        axes.add(e.value)
    return axes


class _SpecReader:
    """Resolve axis names out of PartitionSpec literals, symbolically."""

    def __init__(self, mod: ParsedModule, ctx: LintContext):
        self.ctors = _spec_ctor_names(mod)
        self.consts = _module_str_constants(mod)
        self.ctx = ctx

    def is_spec_call(self, node: ast.expr) -> bool:
        if not isinstance(node, ast.Call):
            return False
        f = node.func
        if isinstance(f, ast.Name):
            return f.id in self.ctors
        return isinstance(f, ast.Attribute) and f.attr == "PartitionSpec"

    def axis_names(self, spec_call: ast.Call) -> List[Tuple[str, int]]:
        """(axis name, line) for each resolvable name in the spec; None
        entries and opaque expressions contribute nothing."""
        out: List[Tuple[str, int]] = []

        def visit(e: ast.expr):
            if isinstance(e, ast.Constant):
                if isinstance(e.value, str):
                    out.append((e.value, e.lineno))
            elif isinstance(e, (ast.Tuple, ast.List)):
                for sub in e.elts:
                    visit(sub)
            elif isinstance(e, ast.Name):
                if e.id in self.consts:
                    out.append((self.consts[e.id], e.lineno))
                elif e.id in self.ctx.axis_constants:
                    out.append((self.ctx.axis_constants[e.id], e.lineno))
            elif isinstance(e, ast.Attribute):
                val = self.ctx.axis_constants.get(e.attr)
                if val is not None:
                    out.append((val, e.lineno))

        for a in spec_call.args:
            visit(a)
        return out

    def is_empty_spec(self, node: ast.expr) -> bool:
        return (
            self.is_spec_call(node)
            and not node.args
            and not node.keywords
        )


def _chase_target(expr, scopes, depth: int = 0):
    """Resolve a shard_map's wrapped callable to a local def — the same
    Name/assignment chase the recompile rules use, minus wrappers."""
    if depth > 6 or expr is None:
        return None
    if isinstance(expr, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return expr
    if isinstance(expr, ast.Name):
        for defs, assigns in reversed(scopes):
            if expr.id in defs:
                return defs[expr.id]
            if expr.id in assigns:
                return _chase_target(assigns[expr.id], scopes, depth + 1)
    return None


def _positional_arity(fn) -> int:
    args = fn.args
    return len(args.posonlyargs) + len(args.args)


def _tuple_return_arity(fn) -> Optional[int]:
    """len(tuple) when every return in ``fn`` (own body, not nested
    defs) returns a tuple literal of one consistent length."""
    arity: Optional[int] = None
    stack: List[ast.AST] = list(fn.body)
    returns = 0
    while stack:
        node = stack.pop()
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)
        ):
            continue
        if isinstance(node, ast.Return):
            returns += 1
            if not isinstance(node.value, ast.Tuple):
                return None
            n = len(node.value.elts)
            if arity is None:
                arity = n
            elif arity != n:
                return None
        stack.extend(ast.iter_child_nodes(node))
    return arity if returns else None


def check_sharding(mod: ParsedModule, ctx: LintContext) -> List[Finding]:
    reader = _SpecReader(mod, ctx)
    declared = ctx.mesh_axes | _local_declared_axes(mod)
    findings: List[Finding] = []

    # ---- every PartitionSpec literal: axis names must exist ----
    for node in ast.walk(mod.tree):
        if not reader.is_spec_call(node):
            continue
        for axis, line in reader.axis_names(node):
            if axis not in declared:
                findings.append(Finding(
                    "sharding-unknown-axis", "error", mod.path, line,
                    f"PartitionSpec names axis {axis!r}, which no mesh "
                    f"declares — known axes: {sorted(declared)}",
                ))

    # ---- shard_map call sites: arity + replication ----
    def scope_tables(body):
        defs, assigns = {}, {}
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs[stmt.name] = stmt
            elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                t = stmt.targets[0]
                if isinstance(t, ast.Name):
                    assigns[t.id] = stmt.value
            for sub in ast.iter_child_nodes(stmt):
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                    t = sub.targets[0]
                    if isinstance(t, ast.Name):
                        assigns.setdefault(t.id, sub.value)
        return defs, assigns

    def visit(body, scopes):
        scopes = scopes + [scope_tables(body)]
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if isinstance(node, ast.Call) and terminal_name(node) == "shard_map":
                    handle(node, scopes)
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                visit(stmt.body, scopes)
            elif isinstance(stmt, ast.ClassDef):
                for sub in stmt.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        visit(sub.body, scopes)

    def handle(call: ast.Call, scopes):
        target_expr = call.args[0] if call.args else (
            get_kwarg(call, "f") or get_kwarg(call, "fun")
        )
        target = _chase_target(target_expr, scopes)
        in_specs = get_kwarg(call, "in_specs")
        out_specs = get_kwarg(call, "out_specs")

        if target is not None and isinstance(in_specs, (ast.Tuple, ast.List)):
            want = _positional_arity(target)
            got = len(in_specs.elts)
            if got != want:
                findings.append(Finding(
                    "sharding-spec-arity", "error", mod.path, call.lineno,
                    f"in_specs has {got} spec(s) but {target.name!r} "
                    f"takes {want} positional parameter(s) — one spec "
                    f"per operand, in order",
                ))
            else:
                _check_replication(call, target, in_specs)
        if target is not None and isinstance(out_specs, (ast.Tuple, ast.List)):
            ret = _tuple_return_arity(target)
            got = len(out_specs.elts)
            if ret is not None and got != ret:
                findings.append(Finding(
                    "sharding-spec-arity", "error", mod.path, call.lineno,
                    f"out_specs has {got} spec(s) but {target.name!r} "
                    f"returns a {ret}-tuple at every return site",
                ))

    def _check_replication(call: ast.Call, target, in_specs):
        params = [a.arg for a in target.args.posonlyargs + target.args.args]
        any_sharded = any(
            not reader.is_empty_spec(e) for e in in_specs.elts
        )
        if not any_sharded:
            return
        for pname, spec in zip(params, in_specs.elts):
            if not reader.is_empty_spec(spec):
                continue
            base = pname.lstrip("_")
            if base in _LARGE_PARAM_NAMES or any(
                base.endswith("_" + s) for s in _LARGE_PARAM_NAMES
            ):
                findings.append(Finding(
                    "sharding-replicated", "warning", mod.path, spec.lineno,
                    f"operand {pname!r} of {target.name!r} falls to P() "
                    f"full replication while sibling operands are "
                    f"sharded — every device holds a complete copy; "
                    f"shard it, or record why replication is the design",
                ))

    visit(mod.tree.body, [])
    return findings


CHECK = check_sharding
CROSS_MODULE = False
