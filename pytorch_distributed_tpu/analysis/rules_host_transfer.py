"""Rule: host-transfer — no host syncs reachable from a compiled step body.

``float(x)``, ``np.asarray(x)``, ``x.item()`` and ``jax.device_get`` on a
device value block until the async dispatch queue drains; inside the train
step's call tree they serialize every step on a device→host round trip
(the reference's per-step ``scaler`` sync is exactly the bug class). Under
``jit`` tracing they fail loudly — but helpers shared between host code
and step code only get traced on the path that imports them, so the lint
walks the whole-package static call graph instead:

roots     the compiled step bodies: functions named ``_local_*`` or nested
          inside a ``make_*`` builder, in modules under ``train/``
edges     calls resolved through same-module defs, package imports
          (``from pkg.mod import f``), module aliases (``mod.f``) and
          imported-class methods (``Cls.method``)
findings  any reachable function whose body calls float()/np.asarray()/
          np.array()/.item()/jax.device_get — reported with the call chain
          from the root so the fix site is obvious

Dynamic dispatch (``state.apply_fn``, method calls on values) is outside
static reach and intentionally unresolved; the runtime companion
(``analysis.guards.no_recompile`` with its transfer guard) covers it.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from pytorch_distributed_tpu.analysis._astutil import (
    import_map,
    terminal_name,
    walk_functions,
)
from pytorch_distributed_tpu.analysis.core import (
    Finding,
    LintContext,
    ParsedModule,
    RuleInfo,
)

RULES = [
    RuleInfo(
        "host-transfer", "error",
        "float()/np.asarray()/.item()/device_get reachable from a "
        "compiled train-step body",
        "float(x), np.asarray(x), x.item() and jax.device_get block "
        "until the async dispatch queue drains; inside the train step's "
        "call tree they serialize every step on a device-to-host round "
        "trip. The lint walks the whole-package static call graph from "
        "the compiled step bodies (_local_* functions and make_* builder "
        "nests in train/), resolving calls through package imports and "
        "class methods, and reports each reachable sync with the call "
        "chain from the root. Dynamic dispatch is outside static reach; "
        "the runtime companion analysis.guards.no_recompile covers it.",
    ),
]

_NUMPY_SYNCS = {"asarray", "array"}


def _module_key(path: str) -> str:
    """Dotted-ish key for matching import origins to scanned files:
    'pytorch_distributed_tpu/ops/losses.py' -> 'pytorch_distributed_tpu.ops.losses'."""
    return path[:-3].replace("/", ".") if path.endswith(".py") else path


class _Program:
    """Whole-run view: defs, classes and imports of every scanned module."""

    def __init__(self, ctx: LintContext):
        self.mods: Dict[str, ParsedModule] = {
            _module_key(m.path): m for m in ctx.modules
        }
        self.defs: Dict[str, Dict[str, ast.FunctionDef]] = {}
        self.classes: Dict[str, Dict[str, ast.ClassDef]] = {}
        self.imports: Dict[str, Dict[str, str]] = {}
        for key, m in self.mods.items():
            self.defs[key] = {
                n.name: n for n in m.tree.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            }
            self.classes[key] = {
                n.name: n for n in m.tree.body if isinstance(n, ast.ClassDef)
            }
            self.imports[key] = import_map(m.tree)

    def find_module(self, origin: str) -> Optional[str]:
        """Scanned-module key for an import origin, tolerating the scan
        root not being the package root (e.g. fixtures)."""
        if origin in self.mods:
            return origin
        for key in self.mods:
            if origin.endswith("." + key) or key.endswith("." + origin):
                return key
        return None

    def resolve_call(
        self, call: ast.Call, mod_key: str
    ) -> Optional[Tuple[str, ast.FunctionDef]]:
        """(module key, def node) for a package-internal call, else None."""
        imports = self.imports.get(mod_key, {})
        func = call.func
        if isinstance(func, ast.Name):
            local = self.defs.get(mod_key, {}).get(func.id)
            if local is not None:
                return (mod_key, local)
            origin = imports.get(func.id)
            if origin and "." in origin:
                omod, _, oname = origin.rpartition(".")
                target = self.find_module(omod)
                if target:
                    d = self.defs.get(target, {}).get(oname)
                    if d is not None:
                        return (target, d)
                    cls = self.classes.get(target, {}).get(oname)
                    if cls is not None:
                        init = next(
                            (n for n in cls.body
                             if isinstance(n, ast.FunctionDef)
                             and n.name == "__init__"),
                            None,
                        )
                        if init is not None:
                            return (target, init)
            return None
        if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
            base, attr = func.value.id, func.attr
            origin = imports.get(base)
            if origin is None:
                # Cls.method on a class defined in this module
                cls = self.classes.get(mod_key, {}).get(base)
                if cls is not None:
                    m = next(
                        (n for n in cls.body
                         if isinstance(n, ast.FunctionDef) and n.name == attr),
                        None,
                    )
                    if m is not None:
                        return (mod_key, m)
                return None
            # module alias: mod.f()
            target = self.find_module(origin)
            if target:
                d = self.defs.get(target, {}).get(attr)
                if d is not None:
                    return (target, d)
            # imported class: Cls.method()
            if "." in origin:
                omod, _, oname = origin.rpartition(".")
                target = self.find_module(omod)
                if target:
                    cls = self.classes.get(target, {}).get(oname)
                    if cls is not None:
                        m = next(
                            (n for n in cls.body
                             if isinstance(n, ast.FunctionDef)
                             and n.name == attr),
                            None,
                        )
                        if m is not None:
                            return (target, m)
        return None


def _hot_roots(mod: ParsedModule) -> List[Tuple[ast.FunctionDef, str]]:
    """Compiled step bodies in this module: (def, qualname)."""
    if "train/" not in mod.path and not os.path.basename(mod.path).startswith(
        "step"
    ):
        return []
    roots = []
    for fn, stack in walk_functions(mod.tree):
        enclosing = stack[-1].name if stack else ""
        if fn.name.startswith("_local_") or (
            stack and enclosing.startswith("make_")
        ):
            qual = ".".join([s.name for s in stack] + [fn.name])
            roots.append((fn, qual))
    return roots


def _violations_in(fn: ast.FunctionDef, imports: Dict[str, str]):
    """(line, description) for every host-sync call in the def's subtree."""
    out: List[Tuple[int, str]] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name) and f.id == "float" and node.args:
            out.append((node.lineno, "float(...) forces a device→host sync"))
        elif isinstance(f, ast.Attribute):
            if f.attr == "item" and not node.args and not node.keywords:
                out.append((node.lineno, ".item() forces a device→host sync"))
            elif f.attr == "device_get":
                out.append((node.lineno, "jax.device_get pulls the value to host"))
            elif f.attr in _NUMPY_SYNCS and isinstance(f.value, ast.Name):
                origin = imports.get(f.value.id, "")
                if origin == "numpy" or origin.startswith("numpy."):
                    out.append((
                        node.lineno,
                        f"np.{f.attr}(...) materializes the array on host",
                    ))
        elif isinstance(f, ast.Name) and f.id == "device_get":
            origin = imports.get("device_get", "")
            if origin.startswith("jax"):
                out.append((node.lineno, "jax.device_get pulls the value to host"))
    return out


def check_host_transfers(mod: ParsedModule, ctx: LintContext) -> List[Finding]:
    roots = _hot_roots(mod)
    if not roots:
        return []
    prog = _Program(ctx)
    mod_key = _module_key(mod.path)
    findings: List[Finding] = []
    # BFS over (module, def), remembering the call chain from the root
    for root, qual in roots:
        seen: Set[Tuple[str, int]] = set()
        queue: List[Tuple[str, ast.FunctionDef, Tuple[str, ...]]] = [
            (mod_key, root, (qual,))
        ]
        while queue:
            key, fn, chain = queue.pop()
            if (key, id(fn)) in seen:
                continue
            seen.add((key, id(fn)))
            target_mod = prog.mods[key]
            imports = prog.imports[key]
            for line, desc in _violations_in(fn, imports):
                if target_mod.is_suppressed("host-transfer", line):
                    continue
                via = " -> ".join(chain)
                findings.append(Finding(
                    "host-transfer", "error", target_mod.path, line,
                    f"{desc}, inside the compiled step's call tree "
                    f"({via} -> {fn.name})",
                ))
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    resolved = prog.resolve_call(node, key)
                    if resolved is not None:
                        tkey, tfn = resolved
                        queue.append((tkey, tfn, chain + (fn.name,)))
    # dedupe (several roots can reach the same sync site)
    unique = {}
    for f in findings:
        unique.setdefault((f.path, f.line, f.message.split(" (")[0]), f)
    return list(unique.values())


CHECK = check_host_transfers
CROSS_MODULE = True  # findings move when any file in the call graph changes
