"""jaxlint — in-tree static analysis for SPMD/jit correctness hazards.

The classic failure modes of the pjit/shard_map stack are silent: a
mistyped collective axis trains on wrong math, a Python branch on a traced
value recompiles every step, a stray ``float()`` in the hot loop syncs the
device each iteration, a partition rule that matches nothing leaves a
parameter replicated. This package catches them before they cost a run:

- ``run_lint`` / ``scripts/jaxlint.py``: AST rules over the package
  (collective-axis, recompile hazards, host transfers, precision casts)
  plus the v2 dataflow families (donation use-after-donate/aliasing,
  shard_map PartitionSpec arity/axis checks, host-thread concurrency);
- ``partition_coverage.check_partition_coverage``: cross-checks the
  partition rule tables in ``parallel/``/``train/lm.py`` against real
  model parameter trees;
- ``guards``: runtime companions (``no_recompile``) that wrap a train step
  and assert-fail on jit cache growth or host transfers after warmup;
- ``blocksan``: the runtime block-lifecycle sanitizer — a shadow ledger
  over the serving stack's paged KV allocator (``PDT_BLOCKSAN=1``) that
  detects leak-at-retire, double-free, refcount underflow,
  use-after-free, pinned-block violations, and ledger/allocator drift
  at quiesce (the static ``lifecycle-*`` rule family is its compile-time
  half);
- ``sarif``/``cache``: SARIF 2.1.0 emission for CI annotation surfaces
  and the content-hash incremental mode behind ``--incremental``.

Rules and the ``# jaxlint: disable=<rule>`` suppression syntax are
documented in ANALYSIS.md at the repo root; ``jaxlint --explain RULE``
prints each rule's long-form text straight from its ``RuleInfo`` — the
single source the docs defer to.
"""

from pytorch_distributed_tpu.analysis.core import (  # noqa: F401
    Finding,
    LintContext,
    ParsedModule,
    RuleInfo,
    all_rule_ids,
    explain_rule,
    load_baseline,
    parse_file,
    regenerate_baseline,
    rule_catalog,
    run_lint,
    split_baselined,
    with_fingerprints,
)
from pytorch_distributed_tpu.analysis.cache import (  # noqa: F401
    run_lint_incremental,
)
from pytorch_distributed_tpu.analysis.sarif import (  # noqa: F401
    to_sarif,
    write_sarif,
)
from pytorch_distributed_tpu.analysis.guards import (  # noqa: F401
    GuardStats,
    GuardViolation,
    no_recompile,
)
from pytorch_distributed_tpu.analysis.blocksan import (  # noqa: F401
    BlockSanError,
    BlockSanitizer,
    Violation,
    maybe_sanitizer,
)
