"""jaxlint — in-tree static analysis for SPMD/jit correctness hazards.

The classic failure modes of the pjit/shard_map stack are silent: a
mistyped collective axis trains on wrong math, a Python branch on a traced
value recompiles every step, a stray ``float()`` in the hot loop syncs the
device each iteration, a partition rule that matches nothing leaves a
parameter replicated. This package catches them before they cost a run:

- ``run_lint`` / ``scripts/jaxlint.py``: AST rules over the package
  (collective-axis, recompile hazards, host transfers, precision casts);
- ``partition_coverage.check_partition_coverage``: cross-checks the
  partition rule tables in ``parallel/``/``train/lm.py`` against real
  model parameter trees;
- ``guards``: runtime companions (``no_recompile``) that wrap a train step
  and assert-fail on jit cache growth or host transfers after warmup.

Rules and the ``# jaxlint: disable=<rule>`` suppression syntax are
documented in ANALYSIS.md at the repo root.
"""

from pytorch_distributed_tpu.analysis.core import (  # noqa: F401
    Finding,
    LintContext,
    ParsedModule,
    all_rule_ids,
    load_baseline,
    parse_file,
    run_lint,
    split_baselined,
)
from pytorch_distributed_tpu.analysis.guards import (  # noqa: F401
    GuardStats,
    GuardViolation,
    no_recompile,
)
