"""The paged serving engine: compiled chunk-prefill + decode programs.

Two program families over one block-pooled KV cache
(``serving.kv_pool``), both donating the pool and the logits buffer so
every update is in place:

- **chunk prefill** (``run_chunks``): processes one fixed-length chunk
  of up to ``k`` requests' prompts in ONE program — the batched
  admission insert. Each job carries its own start position and a slice
  of its block table, so the program's cost is O(k · chunk · prompt
  bucket): independent of the pool size, the slot count, and
  ``max_seq_len`` — the whole point of the paged layout (the dense
  layout's admission wrote a full ``max_seq_len`` row; see ISSUE r6 /
  ANALYSIS.md "Serving engine"). Programs are cached per (padded k,
  table-slice width) — both padded to powers of two to bound compile
  count.
- **decode** (``decode``): one token for every slot, exactly the dense
  ``_step_body`` shape but attending through the block table
  (``ops.attention.paged_attention`` — the dense gather or, with
  ``gather_impl="pallas"``, the fused ``ops.paged_flash`` kernel; with
  ``kv_dtype="int8"`` the pool is quantized with per-row scales).
  Inactive lanes' writes are routed to the trash block by host-side
  table masking, so recycled blocks can never be corrupted by a dead
  lane.

Tensor parallelism reuses the dense serving path's machinery: params
placed by ``models.generate._tp_rules``, the pool head-sharded by
``kv_pool.paged_cache_specs``, programs wrapped in ``shard_map`` over the
model axis with replicated logits/sampling (token streams identical on
every shard).
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
from typing import Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_tpu.serving.kv_pool import (
    SWAPPING_IN,
    SWAPPING_OUT,
    TRASH_BLOCK,
    BlockAllocator,
    HostBlockStore,
    HostChain,
    PrefixIndex,
    blocks_needed,
    blocks_needed_suffix,
    init_paged_cache,
    paged_cache_specs,
)
from pytorch_distributed_tpu.telemetry.overlap import NULL_LEDGER


def _pow2_bucket(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


class ChunkJob(NamedTuple):
    """One prompt chunk to prefill: ``tokens`` is the chunk (padded to
    the engine's chunk length with zeros), ``start`` its absolute
    position, ``last_idx`` the in-chunk index of the prompt's final real
    token (meaningful only when ``is_last``)."""

    slot: int
    tokens: np.ndarray  # [chunk] int32
    start: int
    is_last: bool
    last_idx: int


class PendingSwap(NamedTuple):
    """A swap-out mid-flight: the chain's blocks gathered on-device with
    their async d2h copy started (``swap_out_begin``), awaiting the host
    materialization + store commit (``swap_out_finish``). While one of
    these exists the chain is ``swapping-out`` in the allocator — its
    blocks stay owned and its slot must not be recycled."""

    slot: int
    chain_len: int
    blocks: object  # cache-shaped pytree, [n_pad, block_len, ...] device
    logits_row: object  # [vocab_size] device


class PrefixHit(NamedTuple):
    """One shared-prefix admission (``PagedEngine.admit_shared``):
    ``covered`` tokens ride existing pool blocks (prefill starts there),
    ``shared`` of the chain's blocks are incref'd index blocks, and
    ``cow`` marks the full-cover path that copy-on-write duplicated the
    boundary block before re-prefilling the final prompt token."""

    covered: int
    shared: int
    cow: bool
    evicted: int  # index blocks dropped to make room for this admission


class KVExport(NamedTuple):
    """One request's KV detached from its source pool — the unit of the
    fleet layer's prefill→decode handoff (``fleet/``; ANALYSIS.md
    "Serving fleet").

    ``blocks`` is the pool pytree sliced to the request's chain (each
    leaf ``[n_blocks, block_len, H_kv, D]``, logical positions in chain
    order) and ``logits_row`` the final-chunk logits — the distribution
    of the request's first decoded token, which the importing engine's
    decode tick samples from. Block ids do NOT travel: the importer
    allocates a fresh chain in its own pool and remaps the block table,
    so exporter and importer pools never need to agree on layout — only
    on geometry (``block_len`` and the cache tree structure, both checked
    on import)."""

    blocks: object  # pool pytree sliced to the chain: [n, block_len, ...]
    logits_row: object  # [vocab_size] f32
    n_blocks: int
    block_len: int


class PagedEngine:
    """Device state + compiled programs for paged continuous batching.

    The engine owns the pool cache, the logits buffer, the block
    allocator, and the block tables; it does NOT schedule — the caller
    (``serving.scheduler.Scheduler`` or the rewired
    ``models.generate.ContinuousBatcher``) decides what to admit and
    when to decode, and owns per-slot positions/budgets.

    Every program the engine can compile is enumerable AHEAD of traffic
    (``chunk_buckets`` + the decode tick): ``compilecache.serving_registry``
    builds the AOT/warmup registry from exactly these methods, so the
    registry and the lazy ``run_chunks`` bucketing can never drift — the
    coverage guard (``ProgramRegistry.assert_covers`` over
    ``compiled_program_names()``) fails if a compiled program ever appears
    that the enumeration did not predict.
    """

    #: registry name of the shared decode program
    DECODE_PROGRAM = "decode_tick"
    #: registry name of the copy-on-write block duplication program
    BLOCK_COPY_PROGRAM = "kv_block_copy"

    def __init__(self, config, params, n_slots: int, *,
                 n_blocks: Optional[int] = None, block_len: int = 16,
                 prefill_chunk: int = 128, temperature: float = 0.0,
                 top_k: Optional[int] = None, mesh=None, device=None,
                 handoff: bool = False, swap: bool = False,
                 gather_impl: Optional[str] = None,
                 kv_dtype: Optional[str] = None,
                 prefix_cache: bool = False,
                 split_s: Optional[int] = None,
                 autotune_dir: Optional[str] = None):
        from pytorch_distributed_tpu.models.generate import (
            _validate_sampling,
            _validate_serving_config,
        )
        from pytorch_distributed_tpu.serving.kv_pool import KV_DTYPES

        _validate_serving_config(config, mesh)
        _validate_sampling(config, temperature, top_k)
        if prefill_chunk < 1:
            raise ValueError(f"prefill_chunk must be >= 1, got {prefill_chunk}")
        # KV gather spelling: an explicit gather_impl= overrides the
        # config field (replaced INTO the config so the model, the
        # registry fingerprint, and this engine agree on one value —
        # TransformerConfig validates it). kv_dtype="int8" swaps the
        # pool for the quantized layout (kv_pool.init_paged_cache); the
        # model's scatter path keys off the pool dtype, nothing else.
        if gather_impl is not None and gather_impl != config.gather_impl:
            config = dataclasses.replace(config, gather_impl=gather_impl)
        if kv_dtype not in KV_DTYPES:
            raise ValueError(
                f"kv_dtype {kv_dtype!r} must be one of {KV_DTYPES}"
            )
        self.kv_dtype = kv_dtype
        # Autotuned kernel config (telemetry/autotune.py): if a tuned
        # file exists for this engine's autotune fingerprint — the
        # registry fingerprint with the TUNED knobs (block_len /
        # prefill_chunk / split_s) normalized out, so the key never
        # depends on the values being tuned — load it and let it
        # override the defaults. Explicit caller arguments win over the
        # tuned file (you asked for that value, you get it); a missing,
        # stale, or corrupt tuned file is a clean miss, never an error.
        self.autotune_dir = (
            autotune_dir if autotune_dir is not None
            else os.environ.get("PDT_AUTOTUNE_DIR") or None
        )
        self.tuned = None
        self._tuned_key = None
        if self.autotune_dir:
            from pytorch_distributed_tpu.telemetry.autotune import (
                autotune_fingerprint,
                load_tuned,
            )

            self._tuned_key = autotune_fingerprint(
                config, n_slots, kv_dtype=kv_dtype,
                temperature=temperature, top_k=top_k,
                prefix_cache=prefix_cache, mesh=mesh,
            )
            self.tuned = load_tuned(self.autotune_dir, self._tuned_key)
            if self.tuned is not None:
                if block_len == 16:  # signature default → tunable
                    block_len = self.tuned.block_len
                if prefill_chunk == 128:  # signature default → tunable
                    prefill_chunk = self.tuned.prefill_chunk
                if split_s is None:
                    split_s = self.tuned.split_s
        # The split-S knob lives on the config (like gather_impl) so the
        # model, the registry fingerprint, and this engine agree on one
        # value — programs compiled with different splits never share a
        # cache entry.
        if split_s is not None and split_s != config.split_s:
            config = dataclasses.replace(config, split_s=split_s)
        if mesh is not None and device is not None:
            raise ValueError(
                "pass mesh= (TP sub-mesh) or device= (single-device "
                "replica placement), not both"
            )
        self.config = config
        self.n_slots = n_slots
        self.block_len = block_len
        self.chunk = prefill_chunk
        self.temperature = temperature
        self.top_k = top_k
        # Per-slot table width: enough blocks for a full-capacity request.
        self.table_width = -(-config.max_seq_len // block_len)
        if n_blocks is None:
            # Capacity parity with the dense layout (every slot can hold
            # max_seq_len), plus the trash block.
            n_blocks = n_slots * self.table_width + 1
        self.allocator = BlockAllocator(n_blocks)
        self.tables = np.full((n_slots, self.table_width), TRASH_BLOCK,
                              np.int32)

        tp = config.model_axis is not None
        init_cfg = (
            dataclasses.replace(config, model_axis=None, tp_size=1)
            if tp else config
        )
        self.cache = init_paged_cache(init_cfg, params, n_blocks, block_len,
                                      kv_dtype=kv_dtype)
        self.logits = jnp.zeros((n_slots, config.vocab_size), jnp.float32)

        self._chunk_fns: Dict[Tuple[int, int], callable] = {}
        self._decode_fn = None
        # host–device overlap ledger (round 15; telemetry/overlap.py):
        # every compiled launch below reports its dispatch wall through
        # it. NULL_LEDGER by default; the scheduler arms it and stamps
        # the replica id so fleet timelines attribute per replica.
        self.ledger = NULL_LEDGER
        self.ledger_replica = 0
        # prefill→decode handoff programs (fleet disaggregation), one
        # per pow2 chain-length bucket. Gated by ``handoff=`` so engines
        # that never hand off predict no kv_export/kv_import programs
        # (the registry coverage guard would flag them as rogue).
        self.handoff = handoff
        self._export_fns: Dict[int, callable] = {}
        self._import_fns: Dict[int, callable] = {}
        # host-offload swap programs (round 13 pressure tier), the
        # mirror of the handoff pair but pointed at host RAM instead of
        # another replica's pool: gated by ``swap=`` for the same
        # coverage-guard reason, one program pair per pow2 chain bucket.
        self.swap = swap
        self._swap_out_fns: Dict[int, callable] = {}
        self._swap_in_fns: Dict[int, callable] = {}
        # prefix-sharing tier (round 17): the radix index over full
        # blocks plus the one compiled copy-on-write program, gated by
        # ``prefix_cache=`` for the same coverage-guard reason as
        # handoff/swap — engines that never share predict no
        # kv_block_copy program.
        self.prefix_cache = bool(prefix_cache)
        self.prefix: Optional[PrefixIndex] = (
            PrefixIndex(block_len, self.allocator) if prefix_cache
            else None
        )
        self._copy_fn = None
        self._cow_copies = 0
        self._per_block_bytes: Optional[int] = None
        # buckets whose program has EXECUTED at least once (call path hot:
        # the next call pays zero compile/load) — run_chunks/decode and the
        # execute-mode warmups add to these; AOT-only warmup does not (the
        # first real call still pays a trace + persistent-cache load, so
        # the scheduler's cold-request accounting stays honest)
        self._hot_chunks: set = set()
        self._hot_decode = False
        if tp:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from pytorch_distributed_tpu.models.generate import _tp_rules
            from pytorch_distributed_tpu.parallel.tensor import (
                match_partition_rules,
            )

            self.mesh = mesh
            self._param_specs = match_partition_rules(_tp_rules(config),
                                                      params)
            self._cache_specs = paged_cache_specs(config, self.cache)
            self.params = jax.device_put(
                params,
                jax.tree.map(lambda s: NamedSharding(mesh, s),
                             self._param_specs),
            )
            self.cache = jax.device_put(
                self.cache,
                jax.tree.map(lambda s: NamedSharding(mesh, s),
                             self._cache_specs),
            )
        else:
            self.mesh = None
            self.params = params
        # Fleet replica placement (fleet/router.py): commit this engine's
        # whole working set — params, pool, logits — to one device carved
        # out of jax.devices(), so N single-process replicas each dispatch
        # onto their own sub-mesh and their programs can overlap. The
        # compiled programs follow their committed inputs; host-built
        # operands (tokens, tables) stay uncommitted and are free to land
        # wherever the committed arguments already live.
        self.device = device
        if device is not None:
            self.params = jax.device_put(self.params, device)
            self.cache = jax.device_put(self.cache, device)
            self.logits = jax.device_put(self.logits, device)

    @property
    def gather_impl(self) -> str:
        """The KV gather spelling the engine's programs compile with
        (lives on the config so model, fingerprint, and engine agree)."""
        return self.config.gather_impl

    def tuned_provenance(self) -> Dict[str, object]:
        """Which kernel config actually served: tuned or default.

        Telemetry cost cards carry these keys so forensics
        (``explain_request`` / ``telemetry_report``) can tell whether a
        program ran with an autotuned config and whether that config's
        fingerprint still matches this engine (staleness is a clean
        miss at load time, so ``tuned_match`` is True whenever a tuned
        config applied at all).
        """
        out: Dict[str, object] = {
            "tuned": self.tuned is not None,
            "tuned_block_len": self.block_len,
            "tuned_prefill_chunk": self.chunk,
            "tuned_split_s": self.config.split_s,
        }
        if self._tuned_key is not None:
            out["tuned_fingerprint"] = self._tuned_key
            out["tuned_match"] = self.tuned is not None
        return out

    # ---- program builders (cached per static shape) ----

    def _model(self):
        from pytorch_distributed_tpu.models.transformer import TransformerLM

        return TransformerLM(self.config)

    def _chunk_fn(self, k_pad: int, wp: int):
        key = (k_pad, wp)
        fn = self._chunk_fns.get(key)
        if fn is not None:
            return fn
        model = self._model()
        n_slots = self.n_slots

        def body(params, cache, logits, tokens, starts, tables, slots,
                 is_last, last_idx):
            out, variables = model.apply(
                {"params": params, "cache": cache},
                tokens,
                position_offset=starts,
                prefill=True,
                block_tables=tables,
                mutable=["cache"],
            )
            # logits at each prompt's LAST real token — the distribution
            # for its first decoded token; written only for final chunks.
            # Padding jobs carry slot == n_slots: the scatter drops them.
            row = jnp.take_along_axis(
                out, last_idx[:, None, None], axis=1
            )[:, 0]
            new_logits = logits.at[slots].set(
                jnp.where(is_last[:, None], row, logits[slots])
            )
            return variables["cache"], new_logits

        if self.mesh is not None:
            from jax.sharding import PartitionSpec as P

            from pytorch_distributed_tpu.parallel.mesh import shard_map

            body = shard_map(
                body, mesh=self.mesh,
                in_specs=(self._param_specs, self._cache_specs, P(), P(),
                          P(), P(), P(), P(), P()),
                out_specs=(self._cache_specs, P()),
                check_vma=False,
            )
        fn = jax.jit(body, donate_argnums=(1, 2))
        self._chunk_fns[key] = fn
        return fn

    def _decode(self):
        if self._decode_fn is not None:
            return self._decode_fn
        from pytorch_distributed_tpu.models.generate import _sample

        model = self._model()
        temp, topk = self.temperature, self.top_k

        def body(params, cache, logits, positions, active, tables, rng):
            tokens = _sample(logits, rng, temp, topk)
            out, variables = model.apply(
                {"params": params, "cache": cache},
                tokens[:, None],
                position_offset=positions,
                decode=True,
                block_tables=tables,
                mutable=["cache"],
            )
            # Inactive lanes: cache writes already routed to the trash
            # block (host-masked tables); logits rows are dead state,
            # replaced by the slot's next final prefill chunk before they
            # are read. Positions stay frozen — the caller reads them.
            positions = jnp.where(active, positions + 1, positions)
            return variables["cache"], out[:, 0], positions, tokens

        if self.mesh is not None:
            from jax.sharding import PartitionSpec as P

            from pytorch_distributed_tpu.parallel.mesh import shard_map

            body = shard_map(
                body, mesh=self.mesh,
                in_specs=(self._param_specs, self._cache_specs, P(), P(),
                          P(), P(), P()),
                out_specs=(self._cache_specs, P(), P(), P()),
                check_vma=False,
            )
        self._decode_fn = jax.jit(body, donate_argnums=(1, 2))
        return self._decode_fn

    # ---- program enumeration + warmup (compilecache.serving_registry) ----

    @staticmethod
    def chunk_program_name(k_pad: int, wp: int) -> str:
        """Stable registry identity of one chunk-prefill bucket."""
        return f"chunk_prefill[k={k_pad},w={wp}]"

    def bucket_for(self, jobs: List["ChunkJob"]) -> Tuple[int, int]:
        """The (padded job count, table-slice width) bucket ``run_chunks``
        will compile/run for ``jobs`` — THE bucketing definition; the
        registry enumeration and the scheduler's cold-request accounting
        both read it from here."""
        k_pad = _pow2_bucket(len(jobs))
        max_end = max(j.start + self.chunk for j in jobs)
        wp = min(_pow2_bucket(-(-max_end // self.block_len)),
                 self.table_width)
        return k_pad, wp

    def chunk_buckets(self) -> List[Tuple[int, int]]:
        """Every (k_pad, wp) bucket this engine can ever ask for: job
        counts are 1..n_slots (one chunk job per resident slot, pow2-
        padded) and table-slice widths are the pow2 widths clipped to
        ``table_width`` — exactly the values ``bucket_for`` can produce,
        because admission rejects prompts whose padded length exceeds
        ``max_seq_len`` (so ``max_end`` never needs more than
        ``table_width`` blocks)."""
        ks, k = [], 1
        while k < self.n_slots:
            ks.append(k)
            k <<= 1
        ks.append(_pow2_bucket(self.n_slots))
        ws, w = [], 1
        while w < self.table_width:
            ws.append(w)
            w <<= 1
        ws.append(self.table_width)
        return [(k, w) for k in ks for w in sorted(set(ws))]

    @staticmethod
    def export_program_name(n_pad: int) -> str:
        return f"kv_export[n={n_pad}]"

    @staticmethod
    def import_program_name(n_pad: int) -> str:
        return f"kv_import[n={n_pad}]"

    def handoff_buckets(self) -> List[int]:
        """Every chain-length bucket the handoff programs can compile
        for — pow2 lengths clipped to ``table_width``, the exact range
        ``_chain_bucket`` can produce (admission bounds every chain by
        the table width). Empty unless the engine was built with
        ``handoff=True``, so non-fleet registries predict no handoff
        programs."""
        if not self.handoff:
            return []
        ns, n = [], 1
        while n < self.table_width:
            ns.append(n)
            n <<= 1
        ns.append(self.table_width)
        return sorted(set(ns))

    def warm_export(self, n_pad: int, execute: bool = True):
        """Compile (and inertly run) one export bucket: reading the
        trash block and slot 0's logits row mutates nothing. The
        ``execute=False`` branch returns the ``Compiled`` (cost-card
        statics, ``telemetry.costmodel``); the execute branch None."""
        fn = self._export_fn(n_pad)
        idx = jnp.full((n_pad,), TRASH_BLOCK, jnp.int32)
        slot = jnp.asarray(0, jnp.int32)
        if execute:
            fn(self.cache, self.logits, idx, slot)
            return None
        cache_aval, logits_aval = self._cache_logits_avals()
        return fn.lower(cache_aval, logits_aval, idx, slot).compile()

    def warm_import(self, n_pad: int, execute: bool = True):
        """Compile (and inertly run) one import bucket: every lane
        scatters into the trash block and the logits row targets the
        out-of-bounds ``n_slots`` sentinel (dropped), so live state is
        untouched. ``execute=False`` returns the ``Compiled``."""
        fn = self._import_fn(n_pad)
        blocks = jax.tree.map(
            lambda pool: jnp.zeros((n_pad,) + pool.shape[1:], pool.dtype),
            self.cache,
        )
        idx = jnp.full((n_pad,), TRASH_BLOCK, jnp.int32)
        slot = jnp.asarray(self.n_slots, jnp.int32)
        row = jnp.zeros((self.config.vocab_size,), self.logits.dtype)
        if execute:
            self.cache, self.logits = fn(
                self.cache, self.logits, blocks, idx, slot, row,
            )
            return None
        cache_aval, logits_aval = self._cache_logits_avals()
        return fn.lower(
            cache_aval, logits_aval, blocks, idx, slot, row
        ).compile()

    @staticmethod
    def swap_out_program_name(n_pad: int) -> str:
        return f"kv_swap_out[n={n_pad}]"

    @staticmethod
    def swap_in_program_name(n_pad: int) -> str:
        return f"kv_swap_in[n={n_pad}]"

    def swap_buckets(self) -> List[int]:
        """Every chain-length bucket the swap programs can compile for —
        the same pow2-clipped range as the handoff buckets (both walk
        chains the admission contract bounded by ``table_width``). Empty
        unless the engine was built with ``swap=True``, so pressure-less
        registries predict no swap programs."""
        if not self.swap:
            return []
        ns, n = [], 1
        while n < self.table_width:
            ns.append(n)
            n <<= 1
        ns.append(self.table_width)
        return sorted(set(ns))

    def warm_swap_out(self, n_pad: int, execute: bool = True):
        """Compile (and inertly run) one swap-out gather bucket: reads
        the trash block and slot 0's logits row, mutating nothing — the
        same inert contract as ``warm_export``. ``execute=False``
        returns the ``Compiled`` (cost-card statics)."""
        fn = self._swap_out_fn(n_pad)
        idx = jnp.full((n_pad,), TRASH_BLOCK, jnp.int32)
        slot = jnp.asarray(0, jnp.int32)
        if execute:
            fn(self.cache, self.logits, idx, slot)
            return None
        cache_aval, logits_aval = self._cache_logits_avals()
        return fn.lower(cache_aval, logits_aval, idx, slot).compile()

    def warm_swap_in(self, n_pad: int, execute: bool = True):
        """Compile (and inertly run) one swap-in scatter bucket: every
        lane scatters into the trash block and the logits row targets
        the out-of-bounds ``n_slots`` sentinel (dropped) — live state is
        untouched. ``execute=False`` returns the ``Compiled``."""
        fn = self._swap_in_fn(n_pad)
        blocks = jax.tree.map(
            lambda pool: jnp.zeros((n_pad,) + pool.shape[1:], pool.dtype),
            self.cache,
        )
        idx = jnp.full((n_pad,), TRASH_BLOCK, jnp.int32)
        slot = jnp.asarray(self.n_slots, jnp.int32)
        row = jnp.zeros((self.config.vocab_size,), self.logits.dtype)
        if execute:
            self.cache, self.logits = fn(
                self.cache, self.logits, blocks, idx, slot, row,
            )
            return None
        cache_aval, logits_aval = self._cache_logits_avals()
        return fn.lower(
            cache_aval, logits_aval, blocks, idx, slot, row
        ).compile()

    def _block_copy_fn(self):
        """ONE compiled program duplicating one pool block across every
        cache leaf — the copy-on-write primitive. ``pool.at[dst].set(
        pool[src])`` tree-mapped over the cache, so int8 pools copy
        their fp32 scale siblings in the same program (scales share in
        lockstep by construction). Donates the cache: in place, no pool
        copy."""
        if self._copy_fn is not None:
            return self._copy_fn

        def body(cache, src, dst):
            return jax.tree.map(
                lambda pool: pool.at[dst].set(pool[src]), cache
            )

        if self.mesh is not None:
            from jax.sharding import PartitionSpec as P

            from pytorch_distributed_tpu.parallel.mesh import shard_map

            body = shard_map(
                body, mesh=self.mesh,
                in_specs=(self._cache_specs, P(), P()),
                out_specs=self._cache_specs,
                check_vma=False,
            )
        self._copy_fn = jax.jit(body, donate_argnums=(0,))
        return self._copy_fn

    def _require_prefix(self):
        if not self.prefix_cache:
            raise RuntimeError(
                "this engine was built without prefix_cache=True — its "
                "registry does not predict the kv_block_copy program "
                "(prefix-enabled schedulers set it)"
            )

    def warm_block_copy(self, execute: bool = True):
        """Compile (and inertly run) the COW block copy: trash block
        onto itself — a self-copy of the garbage absorber, live state
        untouched. ``execute=False`` returns the ``Compiled`` (cost-card
        statics)."""
        self._require_prefix()
        fn = self._block_copy_fn()
        src = jnp.asarray(TRASH_BLOCK, jnp.int32)
        dst = jnp.asarray(TRASH_BLOCK, jnp.int32)
        if execute:
            self.cache = fn(self.cache, src, dst)
            return None
        cache_aval, _ = self._cache_logits_avals()
        return fn.lower(cache_aval, src, dst).compile()

    def has_chunk_program(self, k_pad: int, wp: int) -> bool:
        """True when the bucket's call path is hot (executed before)."""
        return (k_pad, wp) in self._hot_chunks

    @property
    def has_decode_program(self) -> bool:
        return self._hot_decode

    def compiled_program_names(self) -> List[str]:
        """Live program inventory for the registry coverage guard."""
        names = [self.chunk_program_name(k, w) for k, w in
                 sorted(self._chunk_fns)]
        if self._decode_fn is not None:
            names.append(self.DECODE_PROGRAM)
        names += [self.export_program_name(n) for n in
                  sorted(self._export_fns)]
        names += [self.import_program_name(n) for n in
                  sorted(self._import_fns)]
        names += [self.swap_out_program_name(n) for n in
                  sorted(self._swap_out_fns)]
        names += [self.swap_in_program_name(n) for n in
                  sorted(self._swap_in_fns)]
        if self._copy_fn is not None:
            names.append(self.BLOCK_COPY_PROGRAM)
        return names

    def _cache_logits_avals(self):
        sds = lambda x: jax.ShapeDtypeStruct(  # noqa: E731
            x.shape, x.dtype, sharding=x.sharding
        )
        return jax.tree.map(sds, self.cache), sds(self.logits)

    def warm_chunk(self, k_pad: int, wp: int, execute: bool = True):
        """Force the (k_pad, wp) chunk program compiled before traffic
        needs it. ``execute=False`` returns the ``Compiled`` (cost-card
        statics); the execute branch returns None.

        ``execute=True`` runs it once with inert inputs — every job is a
        padding job (slot ``n_slots``: the logits scatter drops it) whose
        table points at the trash block, so the pool's live blocks and
        the logits buffer are untouched — leaving the jit call path hot:
        the first real request into this bucket pays nothing. Only safe
        when the caller is not concurrently running programs that donate
        the same cache/logits buffers (i.e. before serving, or from the
        serving thread itself).

        ``execute=False`` AOT-compiles via ``lower(...).compile()`` — no
        buffer is touched, so a background thread can do it mid-traffic;
        it feeds the persistent compilation cache
        (``compilecache.aot.enable_persistent_cache``), turning the
        bucket's eventual first call from an XLA compile into a disk
        load.
        """
        fn = self._chunk_fn(k_pad, wp)
        c = self.chunk
        tokens = jnp.zeros((k_pad, c), jnp.int32)
        starts = jnp.zeros((k_pad,), jnp.int32)
        tables = jnp.full((k_pad, wp), TRASH_BLOCK, jnp.int32)
        slots = jnp.full((k_pad,), self.n_slots, jnp.int32)
        is_last = jnp.zeros((k_pad,), bool)
        last_idx = jnp.zeros((k_pad,), jnp.int32)
        if execute:
            self.cache, self.logits = fn(
                self.params, self.cache, self.logits, tokens, starts,
                tables, slots, is_last, last_idx,
            )
            self._hot_chunks.add((k_pad, wp))
            return None
        cache_aval, logits_aval = self._cache_logits_avals()
        return fn.lower(
            self.params, cache_aval, logits_aval, tokens, starts,
            tables, slots, is_last, last_idx,
        ).compile()

    def warm_decode(self, execute: bool = True):
        """Force the decode tick compiled — same contract (and return
        convention) as ``warm_chunk``. The inert execution decodes with
        every lane inactive: cache writes go to the trash block and the
        logits buffer's garbage rows are rewritten by each slot's final
        prefill chunk before any real decode reads them."""
        fn = self._decode()
        positions = jnp.zeros((self.n_slots,), jnp.int32)
        active = jnp.zeros((self.n_slots,), bool)
        tables = jnp.full((self.n_slots, self.table_width), TRASH_BLOCK,
                          jnp.int32)
        rng = jax.random.key(0)
        if self.device is not None:
            rng = jax.device_put(rng, self.device)
        if execute:
            self.cache, self.logits, _, _ = fn(
                self.params, self.cache, self.logits, positions, active,
                tables, rng,
            )
            self._hot_decode = True
            return None
        cache_aval, logits_aval = self._cache_logits_avals()
        return fn.lower(
            self.params, cache_aval, logits_aval, positions, active,
            tables, rng,
        ).compile()

    # ---- slot-level operations ----

    def blocks_for(self, prompt_len: int, max_new_tokens: int) -> int:
        return blocks_needed(prompt_len, max_new_tokens, self.block_len,
                             self.chunk)

    def _san_site(self, label: str):
        """Label the allocator ops inside the ``with`` block for the
        block-lifecycle sanitizer's ledger (``analysis.blocksan``,
        ``PDT_BLOCKSAN=1``); a no-op context when detached."""
        san = self.allocator.sanitizer
        return san.site(label) if san is not None else contextlib.nullcontext()

    def set_kv_trace(self, observer) -> None:
        """Install ``observer(event, owner, info)`` on this engine's
        block allocator (``BlockAllocator.on_transition``): every chain
        alloc/free and swap-state change — wherever it originates
        (admission, retirement, handoff import, either swap direction) —
        reports through it. The round-14 request-lifecycle traces hang
        their KV chain-identity events off this hook; pass ``None`` to
        detach."""
        self.allocator.on_transition = observer

    def _alloc_evict(self, owner: int, shared: List[int],
                     n_new: int) -> Optional[List[int]]:
        """``alloc_mixed`` with the prefix index as the pressure valve:
        on OOM, evict enough LRU index-only blocks to cover the
        shortfall and retry ONCE. Dropping cache always precedes the
        round-13 pressure tier's preemption — only when the index has
        nothing refcount-1 left does the OOM propagate to the caller's
        queue/preempt ladder."""
        chain = self.allocator.alloc_mixed(owner, shared, n_new)
        if chain is None and self.prefix is not None:
            short = n_new - self.allocator.available
            if short > 0 and self.prefix.evict(short) > 0:
                chain = self.allocator.alloc_mixed(owner, shared, n_new)
        return chain

    def admit(self, slot: int, prompt_len: int, max_new_tokens: int) -> bool:
        """Allocate ``slot``'s block chain and write its table row — the
        O(1)-ish host half of admission (the device half is the chunk
        program). Returns False (state unchanged) when the pool cannot
        serve the chain: the deterministic OOM the scheduler queues on."""
        need = self.blocks_for(prompt_len, max_new_tokens)
        if need > self.table_width:
            raise ValueError(
                f"request needs {need} blocks > table width "
                f"{self.table_width} (max_seq_len {self.config.max_seq_len}"
                f" / block_len {self.block_len})"
            )
        with self._san_site("admit"):
            chain = self._alloc_evict(slot, [], need)
        if chain is None:
            return False
        self.tables[slot] = TRASH_BLOCK
        self.tables[slot, :need] = chain
        return True

    # ---- prefix-sharing admission (round 17; ANALYSIS.md "Prefix
    # sharing & copy-on-write") ----

    def admit_shared(self, slot: int, tokens,
                     max_new_tokens: int) -> Optional[PrefixHit]:
        """Admit through the prefix index: the longest full-block match
        of ``tokens`` rides shared (incref'd) blocks, only the suffix
        allocates fresh, and prefill starts at ``covered`` — admission
        costs O(new tokens), not O(prompt).

        Invariants that keep greedy streams token-identical to the
        no-sharing engine:

        - at least ONE prompt token always re-prefills, so the final
          chunk regenerates the slot's logits row exactly as a cold
          prefill would. On a FULL-cover match that token lives inside
          the last matched block — the copy-on-write case: the boundary
          block is duplicated (compiled ``kv_block_copy``) into a fresh
          block the chain owns exclusively, then position ``L-1`` is
          rewritten with bit-identical KV.
        - ``covered`` is capped so the chunk-padded tail
          (``covered + ceil((L-covered)/chunk)*chunk``) stays within
          ``max_seq_len`` — the same scatter-safety bound cold
          admission's padding obeys, so no table slice ever clips a
          live write.

        Returns the ``PrefixHit`` (``covered == 0`` on a miss — still a
        valid admission), or None on pool OOM with nothing incref'd —
        the same deterministic-OOM contract as ``admit``."""
        self._require_prefix()
        prompt_len = len(tokens)
        need0 = self.blocks_for(prompt_len, max_new_tokens)
        if need0 > self.table_width:
            raise ValueError(
                f"request needs {need0} blocks > table width "
                f"{self.table_width} (max_seq_len {self.config.max_seq_len}"
                f" / block_len {self.block_len})"
            )
        bl, c = self.block_len, self.chunk
        matched = self.prefix.lookup(tokens)
        covered = len(matched) * bl
        cow = False
        if covered >= prompt_len:
            # full cover: re-prefill the final token to regenerate the
            # logits row; with block_len 1 that token IS a whole block
            # (no COW), otherwise the boundary block is COW-duplicated
            covered = prompt_len - 1
            cow = covered % bl != 0
        # scatter-safety cap: the padded tail must fit max_seq_len
        while covered > 0 and (
            covered + -(-(prompt_len - covered) // c) * c
            > self.config.max_seq_len
        ):
            covered = (covered - 1) // bl * bl
            cow = False
        if covered <= 0:
            covered, cow = 0, False
        n_shared = covered // bl
        need = blocks_needed_suffix(covered, prompt_len, max_new_tokens,
                                    bl, c)
        evicted0 = self.prefix.evictions
        with self._san_site("admit-shared"):
            chain = self._alloc_evict(slot, matched[:n_shared],
                                      need - n_shared)
        if chain is None:
            return None
        self.tables[slot] = TRASH_BLOCK
        self.tables[slot, :need] = chain
        if cow:
            # duplicate the boundary block BEFORE any write lands in it:
            # positions [n_shared*bl, L-1) must be readable from a block
            # this chain owns exclusively
            with self.ledger.launch(self.ledger_replica,
                                    self.BLOCK_COPY_PROGRAM):
                self.cache = self._block_copy_fn()(
                    self.cache,
                    jnp.asarray(matched[n_shared], jnp.int32),
                    jnp.asarray(chain[n_shared], jnp.int32),
                )
            self._cow_copies += 1
            if self.allocator.sanitizer is not None:
                self.allocator.sanitizer.note_cow(
                    slot, matched[n_shared], chain[n_shared])
        return PrefixHit(
            covered=covered, shared=n_shared, cow=cow,
            evicted=self.prefix.evictions - evicted0,
        )

    def prefix_insert(self, slot: int, tokens, upto: int) -> int:
        """Index ``slot``'s chain blocks covering ``tokens[:upto]``
        (floored to FULL blocks — every indexed slot holds real
        prefill-written KV). Called as prefill crosses block boundaries,
        so concurrent same-prefix requests hit before the donor even
        retires. Dedup keeps first-writer blocks; returns newly indexed
        blocks."""
        self._require_prefix()
        return self.prefix.insert(tokens, self.allocator.chain(slot), upto)

    def prefix_metrics(self) -> dict:
        """Exact sharing counters for ``Scheduler.metrics()`` — index
        state plus the allocator's shared-block census and the COW
        count."""
        out = {
            "prefix_cache": self.prefix_cache,
            "prefix_cow_copies": self._cow_copies,
            "prefix_shared_blocks": self.allocator.shared_blocks,
            "blocks_fresh_allocated": self.allocator.fresh_allocated,
            "blocks_shared_reused": self.allocator.shared_reused,
        }
        if self.prefix is not None:
            out.update(self.prefix.metrics())
        else:
            out.update(prefix_index_blocks=0, prefix_lookups=0,
                       prefix_hits=0, prefix_hit_rate=0.0,
                       prefix_inserts=0, prefix_evictions=0)
        return out

    def release(self, slot: int) -> None:
        """Free the slot's chain and point its table row at the trash
        block, so the shared decode program's garbage writes for this
        (now inactive) lane can never touch recycled blocks."""
        with self._san_site("release"):
            self.allocator.free(slot)
        self.tables[slot] = TRASH_BLOCK

    def release_all(self) -> None:
        """Free every live chain, drop the prefix index's retained
        blocks, and reset all tables — the scale-down teardown after a
        graceful drain (fleet/; by then every CHAIN is already freed,
        so this is a belt-and-braces reset plus the index teardown, not
        a leak plug). Order matters: chains first, so an index block a
        live chain still shared is decref'd exactly once per holder —
        the drain-with-live-sharers invariant the allocator enforces
        loudly."""
        for owner in self.allocator.owners():
            self.allocator.free(owner)
        if self.prefix is not None:
            self.prefix.clear()
        self.tables[:] = TRASH_BLOCK

    # ---- prefill→decode handoff (fleet/ disaggregation) ----

    def _chain_bucket(self, n: int) -> int:
        """Pow2 chain-length bucket (clipped to ``table_width``) shared
        by export and import so one compiled program pair serves every
        chain of similar length; padding lanes read/write the trash
        block."""
        return min(_pow2_bucket(n), self.table_width)

    def _require_handoff(self):
        if not self.handoff:
            raise RuntimeError(
                "this engine was built without handoff=True — its "
                "registry does not predict kv_export/kv_import programs "
                "(fleet routers enable it on every replica they own)"
            )

    def _export_fn(self, n_pad: int):
        fn = self._export_fns.get(n_pad)
        if fn is not None:
            return fn

        def body(cache, logits, idx, slot):
            blocks = jax.tree.map(lambda pool: pool[idx], cache)
            return blocks, logits[slot]

        fn = jax.jit(body)  # pure read: nothing donated
        self._export_fns[n_pad] = fn
        return fn

    def _import_fn(self, n_pad: int):
        fn = self._import_fns.get(n_pad)
        if fn is not None:
            return fn

        def body(cache, logits, blocks, idx, slot, row):
            cache = jax.tree.map(
                lambda pool, b: pool.at[idx].set(b), cache, blocks
            )
            # out-of-bounds slot (warmup's n_slots sentinel) drops the
            # scatter — same inert trick as the chunk program's padding
            return cache, logits.at[slot].set(row)

        fn = jax.jit(body, donate_argnums=(0, 1))
        self._import_fns[n_pad] = fn
        return fn

    def export_chain(self, slot: int) -> KVExport:
        """Detach ``slot``'s KV for transfer into another engine's pool.

        ONE compiled gather per chain-length bucket pulls the chain's
        blocks from every pool leaf plus the slot's logits row (the
        first decode token's distribution, written by the final prefill
        chunk); padding lanes read the trash block. Pure read — the slot
        stays resident until ``release``; the caller sequences export →
        ``import_chain`` on the target → release, so a failed import
        (target pool OOM) leaves the source intact and retryable."""
        from pytorch_distributed_tpu.resilience.faults import fault_point

        self._require_handoff()
        # replica-death site: before the chain is read out — the decode
        # side sees the failure mid-adopt, the export pin stays on this
        # source until the router's failure plane disposes of it
        fault_point("serve.handoff_export")
        chain = self.allocator.chain(slot)
        if not chain:
            raise ValueError(f"slot {slot} holds no block chain to export")
        n_pad = self._chain_bucket(len(chain))
        idx = np.full((n_pad,), TRASH_BLOCK, np.int32)
        idx[:len(chain)] = chain
        with self.ledger.launch(self.ledger_replica,
                                self.export_program_name(n_pad)) as lt:
            blocks, row = self._export_fn(n_pad)(
                self.cache, self.logits, jnp.asarray(idx),
                jnp.asarray(slot, jnp.int32),
            )
            lt.handle = row  # pure-read output: safe to fence lagged
        return KVExport(
            blocks=blocks,
            logits_row=row,
            n_blocks=len(chain),
            block_len=self.block_len,
        )

    def import_chain(self, slot: int, export: KVExport) -> bool:
        """Adopt an exported chain into ``slot``: allocate a fresh chain,
        ``jax.device_put`` the blocks across meshes/devices onto this
        pool's placement (the only cross-replica data motion in the
        handoff), scatter them in with ONE compiled donated program, and
        remap the block table. Returns False (state unchanged) when the
        pool cannot supply the chain — the caller keeps the export and
        retries, exactly the deterministic-OOM contract of ``admit``."""
        from pytorch_distributed_tpu.resilience.faults import fault_point

        self._require_handoff()
        # replica-death site: before any fresh block is allocated here —
        # a failure leaves the source chain intact and re-exportable
        # (the PR 16 failure-safe handoff contract)
        fault_point("serve.handoff_import")
        if export.block_len != self.block_len:
            raise ValueError(
                f"cannot import block_len={export.block_len} blocks into "
                f"a block_len={self.block_len} pool"
            )
        with self._san_site("handoff-import"):
            chain = self._alloc_evict(slot, [], export.n_blocks)
        if chain is None:
            return False
        n_pad = self._chain_bucket(export.n_blocks)
        idx = np.full((n_pad,), TRASH_BLOCK, np.int32)
        idx[:export.n_blocks] = chain
        try:
            # the explicit block-transfer step (a no-op view when source
            # and target share a device). Padding lanes scatter into the
            # trash block, which absorbs anything.
            blocks = jax.tree.map(
                lambda b, pool: jax.device_put(b, pool.sharding),
                export.blocks, self.cache,
            )
            row = jax.device_put(export.logits_row, self.logits.sharding)
            with self.ledger.launch(self.ledger_replica,
                                    self.import_program_name(n_pad)):
                self.cache, self.logits = self._import_fn(n_pad)(
                    self.cache, self.logits, blocks, jnp.asarray(idx),
                    jnp.asarray(slot, jnp.int32), row,
                )
        except BaseException:
            # the fresh chain was allocated but never committed to the
            # table: free it, or a failed cross-device transfer leaks
            # the whole chain (blocksan: leak-at-retire). The export is
            # untouched — the caller's retry contract holds.
            with self._san_site("handoff-import"):
                self.allocator.free(slot)
            self.tables[slot] = TRASH_BLOCK
            raise
        self.tables[slot] = TRASH_BLOCK
        self.tables[slot, :export.n_blocks] = chain
        return True

    # ---- host-offload swap (round 13 pressure tier) ----

    def _require_swap(self):
        if not self.swap:
            raise RuntimeError(
                "this engine was built without swap=True — its registry "
                "does not predict kv_swap_out/kv_swap_in programs "
                "(offload-enabled schedulers set it)"
            )

    def _swap_out_fn(self, n_pad: int):
        fn = self._swap_out_fns.get(n_pad)
        if fn is not None:
            return fn

        def body(cache, logits, idx, slot):
            blocks = jax.tree.map(lambda pool: pool[idx], cache)
            return blocks, logits[slot]

        fn = jax.jit(body)  # pure read: nothing donated
        self._swap_out_fns[n_pad] = fn
        return fn

    def _swap_in_fn(self, n_pad: int):
        fn = self._swap_in_fns.get(n_pad)
        if fn is not None:
            return fn

        def body(cache, logits, blocks, idx, slot, row):
            cache = jax.tree.map(
                lambda pool, b: pool.at[idx].set(b), cache, blocks
            )
            return cache, logits.at[slot].set(row)

        fn = jax.jit(body, donate_argnums=(0, 1))
        self._swap_in_fns[n_pad] = fn
        return fn

    def chain_bytes(self, n_blocks: int) -> int:
        """Device bytes ``n_blocks`` pool blocks hold across every cache
        leaf (K + V + scale siblings) plus one logits row — the payload
        a swap moves, and the byte side of the swap-vs-recompute
        decision. Pure shape arithmetic on the live pool (computed once,
        cached)."""
        if self._per_block_bytes is None:
            total = sum(
                leaf.size * leaf.dtype.itemsize
                for leaf in jax.tree.leaves(self.cache)
            )
            self._per_block_bytes = total // self.allocator.n_blocks
        row = self.logits.size * self.logits.dtype.itemsize // self.n_slots
        return n_blocks * self._per_block_bytes + row

    def swap_out_begin(self, slot: int) -> PendingSwap:
        """Open a swap-out window on ``slot``'s chain: ONE compiled
        gather (per chain-length bucket) detaches the chain's blocks and
        the slot's logits row, and their async d2h copy starts. The
        chain stays allocated and marked ``swapping-out`` — nothing is
        freed until ``swap_out_finish`` commits the host copy, so a
        failure anywhere in the window leaves the stream resident and
        bit-intact."""
        self._require_swap()
        chain = self.allocator.chain(slot)
        if not chain:
            raise ValueError(f"slot {slot} holds no block chain to swap")
        with self._san_site("swap-out"):
            self.allocator.set_state(slot, SWAPPING_OUT)
        try:
            n_pad = self._chain_bucket(len(chain))
            idx = np.full((n_pad,), TRASH_BLOCK, np.int32)
            idx[:len(chain)] = chain
            with self.ledger.launch(self.ledger_replica,
                                    self.swap_out_program_name(n_pad)) as lt:
                blocks, row = self._swap_out_fn(n_pad)(
                    self.cache, self.logits, jnp.asarray(idx),
                    jnp.asarray(slot, jnp.int32),
                )
                lt.handle = row  # pure-read output: safe to fence lagged
            for leaf in jax.tree.leaves(blocks) + [row]:
                try:
                    leaf.copy_to_host_async()  # overlap d2h with serving
                except AttributeError:
                    pass
        except BaseException:
            # a gather failure must not strand the slot inside an open
            # swap window — the allocator would then refuse every later
            # free of this chain (blocksan: pinned-block at retire)
            with self._san_site("swap-out"):
                self.allocator.clear_state(slot)
            raise
        return PendingSwap(slot=slot, chain_len=len(chain), blocks=blocks,
                           logits_row=row)

    def swap_out_finish(self, pending: PendingSwap, store: HostBlockStore,
                        rid: int) -> HostChain:
        """Close the swap-out window: materialize the d2h copy, commit
        the ``HostChain`` to ``store`` under ``rid``, then — and only
        then — free the device chain and trash the slot's table row.

        Hazard sites (``resilience.faults``): ``kv.swap_out_d2h`` before
        the host materialization, ``kv.host_write`` before the store
        commit. ANY failure up to the commit re-raises with the window
        closed and the chain still resident — the caller re-arms the
        lane and the stream continues as if nothing happened."""
        from pytorch_distributed_tpu.resilience.faults import fault_point

        slot = pending.slot
        try:
            fault_point("kv.swap_out_d2h")
            blocks = jax.tree.map(
                lambda b: np.asarray(
                    jax.device_get(b))[:pending.chain_len],
                pending.blocks,
            )
            row = np.asarray(jax.device_get(pending.logits_row))
            nbytes = row.nbytes + sum(
                b.nbytes for b in jax.tree.leaves(blocks)
            )
            chain = HostChain(blocks=blocks, logits_row=row,
                              n_blocks=pending.chain_len,
                              block_len=self.block_len, nbytes=nbytes)
            fault_point("kv.host_write")
            if not store.put(rid, chain):
                raise OSError(
                    f"host store rejected rid {rid}'s chain "
                    f"({nbytes} bytes over budget)"
                )
        except BaseException:
            # window closed, chain untouched: the stream stays resident
            with self._san_site("swap-out"):
                self.allocator.clear_state(slot)
            raise
        with self._san_site("swap-out"):
            self.allocator.clear_state(slot)
            self.release(slot)
        return chain

    def swap_in_chain(self, slot: int, chain: HostChain) -> bool:
        """Restore a host chain into ``slot``: allocate fresh blocks,
        h2d the payload onto the pool's placement, scatter with ONE
        donated program (per bucket), and remap the table. Returns False
        (state unchanged) when the pool cannot supply the chain — the
        caller keeps the host copy and retries, the ``admit`` contract.

        Hazard site ``kv.swap_in_h2d`` fires before any device write: a
        failure there frees the fresh chain and re-raises with the host
        copy intact — the restore is retryable, never half-applied."""
        from pytorch_distributed_tpu.resilience.faults import fault_point

        self._require_swap()
        if chain.block_len != self.block_len:
            raise ValueError(
                f"cannot swap block_len={chain.block_len} blocks into "
                f"a block_len={self.block_len} pool"
            )
        with self._san_site("swap-in"):
            ids = self._alloc_evict(slot, [], chain.n_blocks)
        if ids is None:
            return False
        self.allocator.set_state(slot, SWAPPING_IN)
        n_pad = self._chain_bucket(chain.n_blocks)
        try:
            fault_point("kv.swap_in_h2d")
            idx = np.full((n_pad,), TRASH_BLOCK, np.int32)
            idx[:chain.n_blocks] = ids

            def _padded(b, pool):
                if n_pad > b.shape[0]:  # padding lanes hit the trash block
                    pad = np.zeros((n_pad - b.shape[0],) + b.shape[1:],
                                   b.dtype)
                    b = np.concatenate([b, pad])
                return jax.device_put(b, pool.sharding)

            blocks = jax.tree.map(_padded, chain.blocks, self.cache)
            row = jax.device_put(chain.logits_row, self.logits.sharding)
            with self.ledger.launch(self.ledger_replica,
                                    self.swap_in_program_name(n_pad)):
                self.cache, self.logits = self._swap_in_fn(n_pad)(
                    self.cache, self.logits, blocks, jnp.asarray(idx),
                    jnp.asarray(slot, jnp.int32), row,
                )
        except BaseException:
            with self._san_site("swap-in"):
                self.allocator.clear_state(slot)
                self.allocator.free(slot)
            self.tables[slot] = TRASH_BLOCK
            raise
        with self._san_site("swap-in"):
            self.allocator.clear_state(slot)
        self.tables[slot] = TRASH_BLOCK
        self.tables[slot, :chain.n_blocks] = ids
        return True

    def run_chunks(self, jobs: List[ChunkJob]) -> None:
        """ONE compiled program prefilling one chunk for each job.

        The job count pads to a power of two and the table slice to the
        narrowest power-of-two block count covering every job's chunk end
        — so the program's shapes (and cost) follow the PROMPT bucket,
        never the pool. Chunks of one prompt must be submitted in order
        (chunk n+1 attends to chunk n's writes through the pool)."""
        if not jobs:
            return
        c = self.chunk
        for j in jobs:
            if len(j.tokens) != c:
                raise ValueError(
                    f"chunk job for slot {j.slot} has {len(j.tokens)} "
                    f"tokens; engine chunk length is {c}"
                )
        k_pad, wp = self.bucket_for(jobs)
        tokens = np.zeros((k_pad, c), np.int32)
        starts = np.zeros((k_pad,), np.int32)
        tables = np.full((k_pad, wp), TRASH_BLOCK, np.int32)
        # padding jobs scatter to slot n_slots — out of bounds, dropped
        slots = np.full((k_pad,), self.n_slots, np.int32)
        is_last = np.zeros((k_pad,), bool)
        last_idx = np.zeros((k_pad,), np.int32)
        for i, j in enumerate(jobs):
            tokens[i] = j.tokens
            starts[i] = j.start
            tables[i] = self.tables[j.slot, :wp]
            slots[i] = j.slot
            is_last[i] = j.is_last
            last_idx[i] = j.last_idx
        fn = self._chunk_fn(k_pad, wp)
        # no fence handle: both outputs are donated into later programs,
        # so completion rides the t1 lower bound tightened by the next
        # sync launch on this replica stream (the decode tick).
        with self.ledger.launch(self.ledger_replica,
                                self.chunk_program_name(k_pad, wp)):
            # ONE batched explicit transfer for the six host-built
            # operands, inside the launch window (dispatch cost; see
            # the decode call's note on the per-operand asarray tax)
            operands = jax.device_put(
                (tokens, starts, tables, slots, is_last, last_idx)
            )
            self.cache, self.logits = fn(
                self.params, self.cache, self.logits, *operands,
            )
        self._hot_chunks.add((k_pad, wp))

    def _decode_call(self, positions, active, rng, sync: bool):
        """One decode-tick launch, shared by the sync and async host
        paths. ``sync=True`` materializes the tokens INSIDE the ledger
        window (t1 is exact completion — the historical ``decode``
        contract); ``sync=False`` returns device arrays plus the launch
        token so the caller can pin completion at its own collect site
        (``DispatchLedger.complete``)."""
        masked = np.where(active[:, None], self.tables, TRASH_BLOCK)
        fn = self._decode()
        if self.device is not None:
            # keys are computed arrays; pin them next to the replica's
            # committed working set so the program has one placement
            rng = jax.device_put(rng, self.device)
        with self.ledger.launch(self.ledger_replica, self.DECODE_PROGRAM,
                                sync=sync) as lt:
            # ONE batched explicit transfer for the host-built
            # operands, inside the launch window — it is dispatch cost.
            # The per-operand eager jnp.asarray spelling paid python
            # bind overhead three times per tick (a third of the serve
            # loop's host wall, round-16 profile), and a bare-np jit
            # call would be an IMPLICIT transfer the no_recompile guard
            # rightly rejects.
            positions, active, masked = jax.device_put(
                (np.asarray(positions, np.int32), active, masked)
            )
            self.cache, self.logits, positions, tokens = fn(
                self.params, self.cache, self.logits,
                positions, active, masked, rng,
            )
            if sync:
                # the token fetch inside the window materializes the
                # program's result, so t1 IS device completion — the
                # exact anchor the chunk launches' lower bounds tighten
                # against
                tokens = np.asarray(tokens)
            else:
                lt.handle = tokens  # non-donated output: fence target
        self._hot_decode = True
        return tokens, positions, lt

    def decode(self, positions: np.ndarray, active: np.ndarray, rng):
        """One decode tick for every slot; samples from the logits
        buffer, writes each active lane's token at its position, returns
        ``(tokens [n_slots], new_positions)``. Inactive lanes compute
        dead garbage routed to the trash block."""
        tokens, positions, _ = self._decode_call(positions, active, rng,
                                                 sync=True)
        return tokens, np.array(positions)

    def decode_launch(self, positions: np.ndarray, active: np.ndarray,
                      rng):
        """The async host path's non-blocking decode tick (round 16):
        dispatches the SAME compiled program as ``decode`` — identical
        shapes, zero new registry entries — and returns
        ``(device_tokens, device_positions, launch_token)`` WITHOUT
        materializing anything. The caller materializes later through
        ``decode_collect`` while this device (or another replica's) is
        already running the next program."""
        return self._decode_call(positions, active, rng, sync=False)

    def decode_collect(self, tokens, positions, launch_token):
        """Materialize a ``decode_launch``'s results: pins the launch's
        completion on the ledger (a collect-site fence — by now the
        work is usually done and the wait is a no-op), then fetches
        tokens and positions to host. Returns the same
        ``(tokens [n_slots], new_positions)`` as ``decode``."""
        self.ledger.complete(launch_token)
        return np.asarray(tokens), np.array(positions)
