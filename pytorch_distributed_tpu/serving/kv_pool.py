"""Block-pooled KV cache: allocator, pool pytree, TP placement.

The paged layout (Kwon et al., SOSP 2023) stores every resident request's
KV in fixed-size blocks drawn from one shared pool
``[n_blocks, block_len, H_kv, D]`` per layer. A request's logical
positions ``[w*block_len, (w+1)*block_len)`` live in the pool block its
block-table row names at column ``w`` — so admission allocates fresh
blocks and writes ONLY the new prompt's KV (O(prompt)), never touching
resident requests' blocks, where the dense layout wrote a full
``max_seq_len`` row per admission (O(per-slot cache)).

Block 0 is the TRASH block: never allocated, it absorbs the scatter
writes of inactive decode lanes (the engine zeroes retired slots' table
rows) so a recycled block can never be corrupted by a dead lane's
garbage write. Gathers through trash entries are masked by the causal
mask — an unallocated entry's logical positions exceed every live query
position.

Allocation is HOST-side and deterministic: a LIFO free list (freshly
freed blocks are reused first — warmer in cache) with an explicit
``None`` on insufficient capacity, so the scheduler queues the request
instead of crashing (the "deterministic OOM → queue" contract).

Round 13 (KV pressure tier; ANALYSIS.md "KV pressure & preemption"):
the pool gains a SECOND tier. A preempted request's chain can leave the
device — a compiled gather pulls its blocks, a d2h copy lands them in a
:class:`HostBlockStore` entry (:class:`HostChain`), and the device
blocks return to the free list — and come back later through h2d + a
donated scatter into a freshly allocated chain. While a chain is in
transit the allocator tracks it through an explicit per-chain state
machine (``resident → swapping-out → host → swapping-in → resident``):
``free``/``release_all`` REFUSE to free a chain mid-swap, so a drain or
teardown racing an in-flight swap is a loud error, never a corrupted
pool.

Round 17 (prefix sharing; ANALYSIS.md "Prefix sharing & copy-on-write"):
blocks gain REFCOUNTS and the pool a radix :class:`PrefixIndex`. A full
immutable block — every slot written with real prompt KV — can be
referenced by several chains at once (``alloc_mixed`` builds a chain
from shared blocks plus fresh suffix blocks) and by the index itself
(one reference per indexed block); ``free`` decrements, and a block
returns to the free list only at refcount zero. That single rule is
what pins a shared block through the round-13 state machine: a
preempted/swapped-out chain's ``free`` can never drag a block another
resident chain (or the index) still references. Chains only ever WRITE
forward of their covered prefix, so shared blocks are read-only by
construction; the one exception — a full-cover hit that must re-prefill
the final prompt token to regenerate its logits row — first duplicates
the boundary block via the engine's compiled ``kv_block_copy`` program
(copy-on-write). int8 pools compose for free: a block id names the same
row range in the int8 pools AND their fp32 scale siblings, so scale
blocks share and refcount in lockstep.
"""

from __future__ import annotations

import math
import threading
from typing import Callable, Dict, List, NamedTuple, Optional

import jax
import jax.numpy as jnp

TRASH_BLOCK = 0

#: chain swap states (``BlockAllocator.state``). A chain with no entry
#: is plain resident; the transit states bracket the d2h/h2d windows.
RESIDENT = "resident"
SWAPPING_OUT = "swapping-out"
SWAPPING_IN = "swapping-in"
SWAP_STATES = (SWAPPING_OUT, SWAPPING_IN)

#: pool dtypes ``init_paged_cache`` accepts: None keeps the model compute
#: dtype (the raw layout); "int8" stores quantized K/V plus per-
#: (block, slot, head) fp32 scales — ~2x the blocks at fixed pool bytes
#: (exactly 2D/(D+4) with fp32 scales); "fp8" (e4m3) / "fp8_e5m2" store
#: fp8 K/V plus per-row int8 power-of-two EXPONENT siblings — 2D/(D+1),
#: 1.97x at the GPT-2 head dim (ANALYSIS.md "Kernel tier 2").
KV_DTYPES = (None, "int8", "fp8", "fp8_e5m2")

#: fp8 storage dtypes by KV_DTYPES name. e4m3 ("fp8") is the default
#: recommendation: 3 mantissa bits halve the rounding error of e5m2's 2,
#: and the per-row exponent sibling supplies all the dynamic range e5m2
#: would otherwise buy.
FP8_DTYPES = {"fp8": jnp.float8_e4m3fn, "fp8_e5m2": jnp.float8_e5m2}


def kv_pool_dtype(kv_dtype: str):
    """Storage jnp dtype for a non-None ``KV_DTYPES`` name."""
    if kv_dtype == "int8":
        return jnp.int8
    if kv_dtype in FP8_DTYPES:
        return FP8_DTYPES[kv_dtype]
    raise ValueError(
        f"kv_dtype {kv_dtype!r} must be one of {KV_DTYPES} (None "
        "keeps the model compute dtype)"
    )


def is_quantized_pool(dtype) -> bool:
    """True iff ``dtype`` is a quantized pool storage dtype (int8 or
    fp8), i.e. the cache tree carries ``key_scale``/``value_scale``
    siblings and the attention read path must dequantize. The pool
    dtype IS the contract — no config flag to drift from it."""
    dt = jnp.dtype(dtype)
    return dt in (jnp.dtype(jnp.int8), jnp.dtype(jnp.float8_e4m3fn),
                  jnp.dtype(jnp.float8_e5m2))


def pool_scale_dtype(pool_dtype):
    """Scale-sibling dtype for a quantized pool dtype: fp32 multipliers
    for int8 pools (the PR 10 layout), int8 power-of-two exponents for
    fp8 pools — 1 byte per row per head, which is where the fp8 layout's
    2D/(D+1) capacity (vs int8's 2D/(D+4)) comes from."""
    return (jnp.float32 if jnp.dtype(pool_dtype) == jnp.dtype(jnp.int8)
            else jnp.int8)


def scale_factors(scales: jax.Array) -> jax.Array:
    """fp32 dequantization multipliers from a scale sibling. int8 scale
    siblings (fp8 pools) hold power-of-two EXPONENTS: the multiplier is
    ``2**e`` — exact in fp32, so the scale multiply itself contributes
    zero rounding error and the fp8 cast is the whole error budget.
    fp32 siblings (int8 pools) are the multiplier already."""
    if scales.dtype == jnp.dtype(jnp.int8):
        return jnp.exp2(scales.astype(jnp.float32))  # jaxlint: disable=precision-cast -- int8 exponents widen to the fp32 dequant-multiplier dtype
    return scales


def quantize_rows(xf: jax.Array, pool_dtype):
    """Row-wise quantization math shared by the jnp spelling
    (``quantize_kv``) and the Pallas quantize-on-scatter kernel
    (``ops.paged_flash.paged_quantize_scatter``) — ONE function, so the
    two spellings are bit-equivalent by construction.

    ``xf`` is fp32 ``[..., H_kv, D]``. int8: symmetric, scale =
    amax/127, fp32 scales. fp8: per-row power-of-two exponent
    ``e = ceil(log2(amax / fmax))`` (row amax maps into the top octave
    of the format's range), values stored as ``x * 2**-e`` cast to fp8,
    exponents as int8. Returns ``(q, scales)``."""
    pool_dtype = jnp.dtype(pool_dtype)
    amax = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1), 1e-8)
    if pool_dtype == jnp.dtype(jnp.int8):
        # Spelled as a reciprocal MULTIPLY, not amax/127: XLA rewrites
        # constant divisions to reciprocal multiplies under jit, so the
        # divide spelling produces 1-ulp-different scales between an
        # eager caller and the jitted Pallas scatter — the multiply is
        # the same op in both, keeping the spellings bit-equivalent.
        scales = amax * jnp.float32(1.0 / 127.0)
        q = jnp.clip(jnp.round(xf / scales[..., None]), -127, 127)
        return q.astype(jnp.int8), scales
    fmax = float(jnp.finfo(pool_dtype).max)
    e = jnp.clip(jnp.ceil(jnp.log2(amax / fmax)), -126.0, 126.0)
    q = (xf * jnp.exp2(-e)[..., None]).astype(pool_dtype)
    return q, e.astype(jnp.int8)


def quantize_kv(x: jax.Array, pool_dtype=jnp.int8):
    """Per-(token, head) quantization of a K or V chunk to a pool
    storage dtype (int8 default — the PR 10 signature; fp8 via
    ``pool_dtype=jnp.float8_e4m3fn``/``e5m2``).

    ``x`` is ``[..., H_kv, D]``; returns ``(q same shape, scales
    [..., H_kv])`` in the ``quantize_rows`` layout — one scale per
    written KV row, the granularity the paged scatter writes at (a
    per-BLOCK scalar cannot be maintained under incremental chunk/
    decode writes without requantizing the block's resident rows).
    Dequantization is ``q * scale_factors(scales)`` (``ops.paged_flash``
    does it in VMEM; the dense gather right after the take)."""
    xf = x.astype(jnp.float32)  # jaxlint: disable=precision-cast -- fp32 quantization statistics regardless of compute dtype
    return quantize_rows(xf, pool_dtype)


def blocks_needed(prompt_len: int, max_new_tokens: int, block_len: int,
                  chunk: int) -> int:
    """Blocks a request must own before admission: enough to hold the
    chunk-PADDED prefill writes (the final chunk's padding garbage lands
    in owned blocks, dead until decode overwrites it — same argument as
    the dense layout's right-padding) and the decode frontier
    ``prompt_len + max_new_tokens``."""
    return blocks_needed_suffix(0, prompt_len, max_new_tokens, block_len,
                                chunk)


def blocks_needed_suffix(covered: int, prompt_len: int,
                         max_new_tokens: int, block_len: int,
                         chunk: int) -> int:
    """``blocks_needed`` generalized to a prefix-cache hit: prefill
    starts at ``covered`` (a block multiple, or prompt_len-1 on the
    copy-on-write full-cover path), so the chunk padding extends from
    THERE — ``covered + ceil((L-covered)/chunk)*chunk`` — not from 0.
    The whole-chain block count (shared prefix blocks included); the
    caller allocates ``need - covered // block_len`` fresh ones."""
    padded_end = covered + math.ceil((prompt_len - covered) / chunk) * chunk
    return math.ceil(max(padded_end, prompt_len + max_new_tokens)
                     / block_len)


class BlockAllocator:
    """Free-list allocator over pool block ids ``1..n_blocks-1`` (0 is
    the trash block) with per-owner chain tracking and per-block
    REFCOUNTS (round 17: prefix sharing).

    ``alloc`` is all-or-nothing: it returns the chain or ``None`` with
    the free list untouched — the deterministic OOM signal the scheduler
    turns into queueing. ``free`` decrements every chained block's
    refcount and returns only the blocks that hit ZERO, LIFO, so the
    next allocation reuses the most recently freed blocks (asserted in
    tests/test_paged_serving.py). ``alloc_mixed`` builds a chain from
    already-referenced SHARED blocks (each incref'd) plus fresh suffix
    blocks — the prefix-cache admission; ``incref``/``decref`` are the
    :class:`PrefixIndex`'s own reference on the blocks it retains.
    Refcount violations (decref of a dead block == double free) are
    loud RuntimeErrors, never silent corruption."""

    def __init__(self, n_blocks: int):
        if n_blocks < 2:
            raise ValueError(
                f"n_blocks must be >= 2 (block 0 is the trash block), "
                f"got {n_blocks}"
            )
        self.n_blocks = n_blocks
        # LIFO: pop from the end; initialized so the FIRST allocations
        # hand out 1, 2, 3, ... (deterministic, test-friendly order).
        self._free: List[int] = list(range(n_blocks - 1, 0, -1))
        self._chains: Dict[int, List[int]] = {}
        # block id -> refcount; a block is live iff it has an entry.
        self._refs: Dict[int, int] = {}
        # exact sharing counters (the bench's pool-blocks-per-request)
        self.fresh_allocated = 0
        self.shared_reused = 0
        # owner -> transit state; absent == resident. The swap windows
        # (engine.swap_out_begin → swap_out_finish, swap_in_chain) set
        # and clear these; free()/release_all() refuse mid-swap owners.
        self._states: Dict[int, str] = {}
        #: optional transition observer ``(event, owner, info)`` fired on
        #: alloc / free / swap-state changes — chain identity for the
        #: round-14 request-lifecycle traces (``telemetry.reqtrace``; the
        #: scheduler installs an adapter mapping owner slot → rid). Must
        #: never raise into the allocator; observers are forensics.
        self.on_transition: Optional[Callable[[str, int, dict], None]] = None
        #: optional block-lifecycle sanitizer shadow
        #: (``analysis.blocksan``; installed by ``BlockSanitizer.attach``
        #: under ``PDT_BLOCKSAN=1``). Unlike ``on_transition`` it also
        #: sees every incref/decref, BEFORE the allocator's own checks,
        #: so a double free / pinned free is recorded even though the
        #: call still raises. ``None`` costs one attribute test per op.
        self.sanitizer = None

    def _notify(self, event: str, owner: int, **info) -> None:
        if self.on_transition is not None:
            self.on_transition(event, owner, info)

    def census_decls(self):
        from pytorch_distributed_tpu.telemetry.census import Decl

        return [
            Decl("_free", "fixed", cap=lambda a: a.n_blocks - 1,
                 why="free list over the fixed pool (block 0 is TRASH)"),
            Decl("_chains", "fixed", cap=lambda a: a.n_blocks - 1,
                 why="one chain per owner, every chain holds ≥ 1 block "
                     "of the fixed pool"),
            Decl("_refs", "fixed", cap=lambda a: a.n_blocks - 1,
                 why="refcount per allocated block of the fixed pool"),
            Decl("_states", "fixed", cap=lambda a: a.n_blocks - 1,
                 why="swap state per owner-with-chain (subset of "
                     "_chains); entries cleared on free/clear_state"),
        ]

    @property
    def available(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        return (self.n_blocks - 1) - len(self._free)

    def chain(self, owner: int) -> List[int]:
        return list(self._chains.get(owner, ()))

    def owners(self) -> List[int]:
        """Owners currently holding a chain (drain accounting / teardown)."""
        return list(self._chains)

    # ---- chain swap states (round 13: the host-offload tier) ----

    def state(self, owner: int) -> str:
        """The chain's swap state — ``resident`` unless a swap window is
        open on it (owners without a chain are resident by definition:
        nothing to protect)."""
        return self._states.get(owner, RESIDENT)

    def set_state(self, owner: int, state: str) -> None:
        """Open a swap window on ``owner``'s chain. Only live chains can
        enter transit — state on a chainless owner is a caller bug."""
        if state not in SWAP_STATES:
            raise ValueError(
                f"state {state!r} must be one of {SWAP_STATES} "
                "(use clear_state to return to resident)"
            )
        if owner not in self._chains:
            raise ValueError(
                f"owner {owner} holds no chain to mark {state}"
            )
        self._states[owner] = state
        if self.sanitizer is not None:
            self.sanitizer.on_state(owner, state)
        self._notify("state", owner, state=state,
                     n_blocks=len(self._chains[owner]))

    def clear_state(self, owner: int) -> None:
        """Close the swap window (back to resident). Idempotent."""
        if self._states.pop(owner, None) is not None:
            if self.sanitizer is not None:
                self.sanitizer.on_state(owner, None)
            self._notify("state", owner, state=RESIDENT,
                         n_blocks=len(self._chains.get(owner, ())))

    def swapping(self) -> List[int]:
        """Owners with an open swap window — the set ``begin_drain``
        must wait on before teardown."""
        return sorted(self._states)

    def alloc(self, owner: int, n: int) -> Optional[List[int]]:
        """Allocate ``n`` fresh blocks for ``owner`` (a slot id). Returns
        the chain, or ``None`` (state unchanged) when fewer than ``n``
        blocks are free."""
        if n < 1:
            raise ValueError(f"alloc needs n >= 1, got {n}")
        return self.alloc_mixed(owner, [], n)

    def alloc_mixed(self, owner: int, shared: List[int],
                    n_new: int) -> Optional[List[int]]:
        """Build ``owner``'s chain from ``shared`` already-live blocks
        (each incref'd — the prefix-cache hit) followed by ``n_new``
        fresh ones. All-or-nothing: ``None`` with NOTHING incref'd when
        the free list cannot supply the fresh suffix. Sharing a block
        that is not currently referenced (evicted index entry, stale id)
        is a caller bug and raises."""
        if n_new < 0 or (n_new == 0 and not shared):
            raise ValueError(
                f"alloc_mixed needs shared blocks or n_new >= 1, got "
                f"shared={len(shared)} n_new={n_new}"
            )
        if owner in self._chains:
            raise ValueError(f"owner {owner} already holds a chain")
        if len(self._free) < n_new:
            return None  # deterministic OOM: the caller queues
        for b in shared:
            if b not in self._refs:
                raise ValueError(
                    f"cannot share block {b}: not live (evicted or "
                    "never allocated)"
                )
        for b in shared:
            self._refs[b] += 1
        fresh = [self._free.pop() for _ in range(n_new)]
        for b in fresh:
            self._refs[b] = 1
        self.fresh_allocated += n_new
        self.shared_reused += len(shared)
        chain = list(shared) + fresh
        self._chains[owner] = chain
        if self.sanitizer is not None:
            self.sanitizer.on_alloc(owner, list(shared), list(fresh))
        self._notify("alloc", owner, n_blocks=len(chain),
                     shared=len(shared), free=len(self._free))
        return list(chain)

    def ref(self, block: int) -> int:
        """The block's live refcount (0 = not allocated/indexed)."""
        return self._refs.get(block, 0)

    def incref(self, block: int) -> None:
        """Add one reference to a LIVE block — the PrefixIndex's claim
        on a block it retains past its chain's free."""
        if self.sanitizer is not None:
            self.sanitizer.on_incref(block)
        if block not in self._refs:
            raise ValueError(f"incref of dead block {block}")
        self._refs[block] += 1

    def decref(self, block: int) -> bool:
        """Drop one reference; at zero the block returns to the free
        list (True). Decref of a dead block is a DOUBLE FREE and raises
        — the invariant that makes shared-block recycling impossible to
        get silently wrong."""
        if self.sanitizer is not None:
            self.sanitizer.on_decref(block)
        n = self._refs.get(block)
        if n is None:
            raise RuntimeError(
                f"double free: block {block} has no live references"
            )
        if n == 1:
            del self._refs[block]
            self._free.append(block)
            return True
        self._refs[block] = n - 1
        return False

    @property
    def shared_blocks(self) -> int:
        """Blocks currently referenced more than once (chains and/or
        the prefix index) — the sharing the capacity A/B measures."""
        return sum(1 for n in self._refs.values() if n > 1)

    def free(self, owner: int) -> None:
        """Decref ``owner``'s chain; blocks reaching refcount zero
        return to the free list (LIFO reuse). Freeing an owner without
        a chain is a no-op — retirement paths may race a request that
        never got blocks. Freeing a chain with an OPEN SWAP WINDOW is
        refused loudly: the d2h/h2d in flight still reads/writes those
        blocks, and recycling them would corrupt whichever stream reuses
        them first (the drain-while-swapping race;
        tests/test_pressure.py). A block another chain or the prefix
        index still references SURVIVES this free — the pinning rule
        that lets a preempted chain leave without dragging shared
        prefix blocks."""
        state = self._states.get(owner)
        if self.sanitizer is not None:
            self.sanitizer.on_free(owner, state)
        if state is not None:
            raise RuntimeError(
                f"owner {owner}'s chain is {state}: finish or abort the "
                "swap before freeing (begin_drain waits on in-flight "
                "swaps for exactly this reason)"
            )
        chain = self._chains.pop(owner, None)
        if chain:
            freed = sum(self.decref(b) for b in reversed(chain))
            self._notify("free", owner, n_blocks=len(chain),
                         freed=freed, free=len(self._free))


def init_paged_cache(config, params, n_blocks: int, block_len: int,
                     kv_dtype: Optional[str] = None):
    """Zero block-pooled KV cache for ``TransformerLM(config)``.

    Shapes come from ``eval_shape`` on the dense decode cache at batch 1
    (nothing is traced into a compiled program), then every
    ``[1, max_seq_len, H_kv, D]`` leaf is re-shaped into a
    ``[n_blocks, block_len, H_kv, D]`` pool — the per-layer head count
    and dtype (GQA narrows H_kv; TP shards it by placement) carry over
    unchanged, so the pool works for every config the dense cache does.

    ``kv_dtype="int8"`` stores the pools quantized: each ``key``/
    ``value`` leaf becomes int8 and gains a ``key_scale``/``value_scale``
    sibling ``[n_blocks, block_len, H_kv]`` fp32 (the ``quantize_kv``
    layout — one scale per written row per head, so quantize-on-scatter
    and TP head-sharding both work unchanged). ``"fp8"`` (e4m3) /
    ``"fp8_e5m2"`` are the same layout at 1-byte values with 1-byte
    int8 EXPONENT siblings (``pool_scale_dtype``) — 2D/(D+1) capacity
    vs bf16 where int8+fp32 scales is 2D/(D+4). The attention read path
    dequantizes (in-VMEM for ``gather_impl="pallas"``, post-take for
    "dense"); ``models.transformer.Attention`` switches to quantize-on-
    scatter off the pool dtype alone, so the cache pytree IS the whole
    contract — no config flag to drift from it.
    """
    from pytorch_distributed_tpu.models.generate import init_cache

    if block_len < 1:
        raise ValueError(f"block_len must be >= 1, got {block_len}")
    if kv_dtype not in KV_DTYPES:
        raise ValueError(
            f"kv_dtype {kv_dtype!r} must be one of {KV_DTYPES} (None "
            "keeps the model compute dtype)"
        )
    shapes = jax.eval_shape(
        lambda p: init_cache(config, p, 1), params
    )
    if kv_dtype is None:
        return jax.tree.map(
            lambda s: jnp.zeros((n_blocks, block_len) + s.shape[2:],
                                s.dtype),
            shapes,
        )

    from collections.abc import Mapping

    pool_dt = kv_pool_dtype(kv_dtype)
    sc_dt = pool_scale_dtype(pool_dt)

    def _quantized(node):
        # each layer's attention cache is a {"key": [1, L, H_kv, D],
        # "value": ...} pair; replace it with quantized pools + scale
        # siblings (fp32 multipliers for int8, int8 exponents for fp8)
        if isinstance(node, Mapping) and set(node) == {"key", "value"}:
            out = {}
            for name in ("key", "value"):
                s = node[name]
                out[name] = jnp.zeros(
                    (n_blocks, block_len) + s.shape[2:], pool_dt
                )
                out[name + "_scale"] = jnp.zeros(
                    (n_blocks, block_len, s.shape[2]), sc_dt
                )
            return out
        if isinstance(node, Mapping):
            return {k: _quantized(node[k]) for k in node}
        raise ValueError(
            f"unexpected cache tree layout for kv_dtype={kv_dtype!r}: "
            "expected nested dicts ending in {'key', 'value'} leaf "
            f"pairs, got {type(node).__name__}"
        )

    return _quantized(shapes)


def pool_block_bytes(config, params, block_len: int,
                     kv_dtype: Optional[str] = None) -> int:
    """HBM bytes ONE pool block costs across every layer (K + V + any
    scale siblings) — the unit the capacity A/B divides a fixed byte
    budget by (``scripts/bench_serving.py --gather-ab``). Pure
    ``eval_shape`` arithmetic; nothing is allocated."""
    shapes = jax.eval_shape(
        lambda p: init_paged_cache(config, p, 2, block_len,
                                   kv_dtype=kv_dtype),
        params,
    )
    total = sum(
        leaf.size * leaf.dtype.itemsize for leaf in jax.tree.leaves(shapes)
    )
    return total // 2


def paged_cache_specs(config, cache):
    """TP placement for the pool: the HEAD dim (axis 2 — same leaf rank
    as the dense cache) shards over the model axis, exactly the slice
    each shard's Attention computes. Reuses the dense serving rule
    (``models.generate._cache_specs``) so the two layouts cannot drift.
    A quantized pool's (int8 or fp8) rank-3 scale leaves
    ``[n_blocks, block_len, H_kv]``
    shard the same head dim (now the LAST axis): their spec is the
    rank-4 rule with its trailing D entry dropped — derived, so it
    cannot drift either."""
    from jax.sharding import PartitionSpec as P

    from pytorch_distributed_tpu.models.generate import _cache_specs

    specs = _cache_specs(config, cache)
    return jax.tree.map(
        lambda leaf, spec: spec if leaf.ndim == 4 else P(*tuple(spec)[:3]),
        cache, specs,
    )


# ---------------------------------------------------------------------------
# prefix index (round 17: radix reuse over the block pool)
# ---------------------------------------------------------------------------


class _PrefixNode:
    """One full block in the radix tree: ``key`` is the block's token
    tuple (the edge from its parent), ``block`` the pool block id."""

    __slots__ = ("key", "block", "parent", "children", "last_used")

    def __init__(self, key, block, parent):
        self.key = key
        self.block = block
        self.parent = parent
        self.children: Dict[tuple, "_PrefixNode"] = {}
        self.last_used = 0


class PrefixIndex:
    """Radix index over FULL immutable pool blocks, keyed by token-ID
    paths (PagedAttention's prefix-sharing story, SOSP'23 §4.3 applied
    block-granular).

    Each node is one block: the edge from its parent is the tuple of
    ``block_len`` token ids written into it, so a path from the root
    spells a prefix in whole blocks. ``lookup`` walks a prompt block by
    block and returns the longest matched chain of block ids —
    admission increfs those via ``BlockAllocator.alloc_mixed`` and
    allocates only the suffix. ``insert`` retains blocks as their
    chains fill past block boundaries (one index reference each, via
    ``incref``); duplicate paths keep the FIRST block (a second chain
    prefilling the same prefix keeps exclusive ownership of its own
    copy, which frees normally at retire). Only full blocks enter:
    every slot holds real prefill-written KV, so an indexed block is
    immutable by the chains-write-forward rule.

    Eviction is LRU over refcount-1 LEAVES only: a block a resident
    chain still shares (ref > 1) is pinned, and an interior node must
    outlive its descendants (a matched path must be physically complete
    — attention reads the whole chain). ``evict`` is the pool-pressure
    valve the engine pulls BEFORE the round-13 pressure tier preempts a
    live chain: dropping cache is always cheaper than parking a
    stream."""

    def __init__(self, block_len: int, allocator: BlockAllocator):
        if block_len < 1:
            raise ValueError(f"block_len must be >= 1, got {block_len}")
        self.block_len = block_len
        self.allocator = allocator
        self._children: Dict[tuple, _PrefixNode] = {}  # root edges
        self._nodes = 0
        self._clock = 0
        # exact counters (Scheduler.metrics / kind="prefix" JSONL)
        self.lookups = 0
        self.hits = 0
        self.inserts = 0
        self.evictions = 0

    def __len__(self) -> int:
        """Indexed blocks (== index-held references)."""
        return self._nodes

    def census_decls(self):
        from pytorch_distributed_tpu.telemetry.census import Decl

        return [
            Decl(".", "fixed", cap=lambda ix: ix.allocator.n_blocks - 1,
                 why="every node holds an incref on a distinct live pool "
                     "block, so the radix tree cannot outgrow the pool — "
                     "the LRU evict path is how it shrinks under "
                     "pressure (the round-21 *proven* bound)"),
            Decl("_children", "fixed",
                 cap=lambda ix: ix.allocator.n_blocks - 1,
                 why="root edges are a subset of nodes"),
        ]

    @staticmethod
    def _key(tokens, start: int, stop: int) -> tuple:
        return tuple(int(t) for t in tokens[start:stop])

    def lookup(self, tokens) -> List[int]:
        """Longest full-block prefix of ``tokens`` present in the index
        — the matched chain of pool block ids, possibly empty. Bumps
        LRU recency along the matched path."""
        self._clock += 1
        self.lookups += 1
        bl = self.block_len
        out: List[int] = []
        children = self._children
        for i in range(len(tokens) // bl):
            node = children.get(self._key(tokens, i * bl, (i + 1) * bl))
            if node is None:
                break
            node.last_used = self._clock
            out.append(node.block)
            children = node.children
        if out:
            self.hits += 1
        return out

    def insert(self, tokens, chain: List[int], upto: int) -> int:
        """Retain the full blocks covering ``tokens[:upto]`` (floored to
        whole blocks) under their token path; ``chain`` maps block index
        to pool block id. New nodes incref their block (the index's own
        reference); an existing node keeps its block — dedup, nothing
        incref'd. Returns the number of newly indexed blocks."""
        self._clock += 1
        bl = self.block_len
        nb = min(upto, len(tokens)) // bl
        if nb > len(chain):
            raise ValueError(
                f"insert upto {upto} needs {nb} blocks but the chain "
                f"has {len(chain)}"
            )
        added = 0
        children = self._children
        parent = None
        for i in range(nb):
            key = self._key(tokens, i * bl, (i + 1) * bl)
            node = children.get(key)
            if node is None:
                self.allocator.incref(chain[i])
                node = _PrefixNode(key, chain[i], parent)
                children[key] = node
                self._nodes += 1
                added += 1
                self.inserts += 1
            node.last_used = self._clock
            children = node.children
            parent = node
        return added

    def _evictable(self) -> List[_PrefixNode]:
        out = []
        stack = list(self._children.values())
        while stack:
            node = stack.pop()
            if not node.children:
                if self.allocator.ref(node.block) == 1:
                    out.append(node)
            else:
                stack.extend(node.children.values())
        return out

    def evict(self, n: int) -> int:
        """Free up to ``n`` blocks: LRU-oldest refcount-1 leaves first,
        cascading into parents as they become leaves. Returns blocks
        actually returned to the free list (0 when everything left is
        pinned by a live chain or is an interior node)."""
        freed = 0
        while freed < n:
            leaves = self._evictable()
            if not leaves:
                break
            node = min(leaves, key=lambda nd: nd.last_used)
            self._remove(node)
            freed += 1
        return freed

    def _remove(self, node: _PrefixNode) -> None:
        siblings = (node.parent.children if node.parent is not None
                    else self._children)
        del siblings[node.key]
        self._nodes -= 1
        self.evictions += 1
        self.allocator.decref(node.block)

    def clear(self) -> int:
        """Drop every index reference (teardown / ``release_all``):
        blocks no chain shares return to the free list. Returns the
        count dropped."""
        dropped = 0
        stack = list(self._children.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            self.allocator.decref(node.block)
            dropped += 1
        self._children = {}
        self._nodes = 0
        return dropped

    def metrics(self) -> dict:
        return {
            "prefix_index_blocks": self._nodes,
            "prefix_lookups": self.lookups,
            "prefix_hits": self.hits,
            "prefix_hit_rate": (
                self.hits / self.lookups if self.lookups else 0.0
            ),
            "prefix_inserts": self.inserts,
            "prefix_evictions": self.evictions,
        }


# ---------------------------------------------------------------------------
# host tier (round 13: pressure offload)
# ---------------------------------------------------------------------------


class HostChain(NamedTuple):
    """One request's KV chain at rest in host RAM: the pool pytree
    sliced to the chain (numpy leaves, logical positions in chain order)
    plus the slot's logits row — the next token's distribution, without
    which a swapped-in decode lane could not resume bit-exact. Block ids
    do NOT travel (same contract as the fleet handoff's ``KVExport``):
    swap-in allocates a fresh chain and remaps the table."""

    blocks: object  # cache-shaped pytree of numpy [n_blocks, block_len, ...]
    logits_row: object  # numpy [vocab_size]
    n_blocks: int
    block_len: int
    nbytes: int


class HostBlockStore:
    """Host-RAM tier for swapped-out chains, keyed by request id.

    Plain pageable host memory stands in for pinned buffers on this
    backend (jax's d2h lands in numpy either way); the store's job is
    bookkeeping with teeth: exact byte accounting, an optional
    ``max_bytes`` budget (``put`` returns False when a chain does not
    fit — the caller's cue to recompute instead), and a lock so a
    future threaded swap path inherits a safe store
    (``analysis/rules_threads.py`` vets the discipline)."""

    def __init__(self, max_bytes: Optional[int] = None):
        if max_bytes is not None and max_bytes < 1:
            raise ValueError(f"max_bytes must be >= 1, got {max_bytes}")
        self.max_bytes = max_bytes
        self._chains: Dict[int, HostChain] = {}
        self._bytes = 0
        self._lock = threading.Lock()

    @property
    def bytes_used(self) -> int:
        with self._lock:
            return self._bytes

    def has_room(self, nbytes: int) -> bool:
        """Whether a chain of ``nbytes`` would fit the budget now — the
        swap-vs-recompute decision consults this BEFORE gathering, so a
        full store steers preemption to recompute instead of failing the
        swap mid-flight."""
        if self.max_bytes is None:
            return True
        with self._lock:
            return self._bytes + nbytes <= self.max_bytes

    def put(self, rid: int, chain: HostChain) -> bool:
        """Store one chain; False (store unchanged) when over budget.
        Storing twice for one rid is a caller bug — a parked request has
        exactly one host copy."""
        with self._lock:
            if rid in self._chains:
                raise ValueError(f"rid {rid} already has a host chain")
            if (self.max_bytes is not None
                    and self._bytes + chain.nbytes > self.max_bytes):
                return False
            self._chains[rid] = chain
            self._bytes += chain.nbytes
            return True

    def get(self, rid: int) -> HostChain:
        with self._lock:
            return self._chains[rid]

    def pop(self, rid: int) -> HostChain:
        """Remove and return — called only AFTER a successful swap-in,
        so a failed h2d leaves the host copy intact and retryable."""
        with self._lock:
            chain = self._chains.pop(rid)
            self._bytes -= chain.nbytes
            return chain

    def __contains__(self, rid: int) -> bool:
        with self._lock:
            return rid in self._chains

    def __len__(self) -> int:
        with self._lock:
            return len(self._chains)

    def rids(self) -> List[int]:
        with self._lock:
            return sorted(self._chains)

    def census_decls(self):
        from pytorch_distributed_tpu.telemetry.census import Decl

        return [
            Decl("_chains", "live",
                 why="one host copy per PARKED request (a strict subset "
                     "of live requests); put() additionally refuses past "
                     "max_bytes when a byte budget is set"),
        ]
