"""Block-pooled KV cache: allocator, pool pytree, TP placement.

The paged layout (Kwon et al., SOSP 2023) stores every resident request's
KV in fixed-size blocks drawn from one shared pool
``[n_blocks, block_len, H_kv, D]`` per layer. A request's logical
positions ``[w*block_len, (w+1)*block_len)`` live in the pool block its
block-table row names at column ``w`` — so admission allocates fresh
blocks and writes ONLY the new prompt's KV (O(prompt)), never touching
resident requests' blocks, where the dense layout wrote a full
``max_seq_len`` row per admission (O(per-slot cache)).

Block 0 is the TRASH block: never allocated, it absorbs the scatter
writes of inactive decode lanes (the engine zeroes retired slots' table
rows) so a recycled block can never be corrupted by a dead lane's
garbage write. Gathers through trash entries are masked by the causal
mask — an unallocated entry's logical positions exceed every live query
position.

Allocation is HOST-side and deterministic: a LIFO free list (freshly
freed blocks are reused first — warmer in cache) with an explicit
``None`` on insufficient capacity, so the scheduler queues the request
instead of crashing (the "deterministic OOM → queue" contract).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

TRASH_BLOCK = 0

#: pool dtypes ``init_paged_cache`` accepts: None keeps the model compute
#: dtype (the raw layout); "int8" stores quantized K/V plus per-
#: (block, slot, head) fp32 scales — ~2x the blocks at fixed pool bytes
#: (exactly 2D/(D+4) with fp32 scales; ANALYSIS.md "Paged attention
#: kernel & quantized KV").
KV_DTYPES = (None, "int8")


def quantize_kv(x: jax.Array):
    """Symmetric per-(token, head) int8 quantization of a K or V chunk.

    ``x`` is ``[..., H_kv, D]``; returns ``(q int8 same shape, scales
    fp32 [..., H_kv])`` with ``q = round(x / scale)`` and
    ``scale = amax(|x|, D) / 127`` — one scale per written KV row, the
    granularity the paged scatter writes at (a per-BLOCK scalar cannot
    be maintained under incremental chunk/decode writes without
    requantizing the block's resident rows). Dequantization is
    ``q * scale`` (``ops.paged_flash`` does it in VMEM; the dense gather
    right after the take)."""
    xf = x.astype(jnp.float32)  # jaxlint: disable=precision-cast -- fp32 quantization statistics regardless of compute dtype
    scales = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1), 1e-8) / 127.0
    q = jnp.clip(jnp.round(xf / scales[..., None]), -127, 127)
    return q.astype(jnp.int8), scales


def blocks_needed(prompt_len: int, max_new_tokens: int, block_len: int,
                  chunk: int) -> int:
    """Blocks a request must own before admission: enough to hold the
    chunk-PADDED prefill writes (the final chunk's padding garbage lands
    in owned blocks, dead until decode overwrites it — same argument as
    the dense layout's right-padding) and the decode frontier
    ``prompt_len + max_new_tokens``."""
    padded_prefill = math.ceil(prompt_len / chunk) * chunk
    return math.ceil(max(padded_prefill, prompt_len + max_new_tokens)
                     / block_len)


class BlockAllocator:
    """Free-list allocator over pool block ids ``1..n_blocks-1`` (0 is
    the trash block) with per-owner chain tracking.

    ``alloc`` is all-or-nothing: it returns the chain or ``None`` with
    the free list untouched — the deterministic OOM signal the scheduler
    turns into queueing. ``free`` returns a chain LIFO, so the next
    allocation reuses the most recently freed blocks (asserted in
    tests/test_paged_serving.py)."""

    def __init__(self, n_blocks: int):
        if n_blocks < 2:
            raise ValueError(
                f"n_blocks must be >= 2 (block 0 is the trash block), "
                f"got {n_blocks}"
            )
        self.n_blocks = n_blocks
        # LIFO: pop from the end; initialized so the FIRST allocations
        # hand out 1, 2, 3, ... (deterministic, test-friendly order).
        self._free: List[int] = list(range(n_blocks - 1, 0, -1))
        self._chains: Dict[int, List[int]] = {}

    @property
    def available(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        return (self.n_blocks - 1) - len(self._free)

    def chain(self, owner: int) -> List[int]:
        return list(self._chains.get(owner, ()))

    def owners(self) -> List[int]:
        """Owners currently holding a chain (drain accounting / teardown)."""
        return list(self._chains)

    def alloc(self, owner: int, n: int) -> Optional[List[int]]:
        """Allocate ``n`` blocks for ``owner`` (a slot id). Returns the
        chain, or ``None`` (state unchanged) when fewer than ``n`` blocks
        are free."""
        if n < 1:
            raise ValueError(f"alloc needs n >= 1, got {n}")
        if owner in self._chains:
            raise ValueError(f"owner {owner} already holds a chain")
        if len(self._free) < n:
            return None  # deterministic OOM: the caller queues
        chain = [self._free.pop() for _ in range(n)]
        self._chains[owner] = chain
        return list(chain)

    def free(self, owner: int) -> None:
        """Release ``owner``'s chain back to the free list (LIFO reuse).
        Freeing an owner without a chain is a no-op — retirement paths
        may race a request that never got blocks."""
        chain = self._chains.pop(owner, None)
        if chain:
            self._free.extend(reversed(chain))


def init_paged_cache(config, params, n_blocks: int, block_len: int,
                     kv_dtype: Optional[str] = None):
    """Zero block-pooled KV cache for ``TransformerLM(config)``.

    Shapes come from ``eval_shape`` on the dense decode cache at batch 1
    (nothing is traced into a compiled program), then every
    ``[1, max_seq_len, H_kv, D]`` leaf is re-shaped into a
    ``[n_blocks, block_len, H_kv, D]`` pool — the per-layer head count
    and dtype (GQA narrows H_kv; TP shards it by placement) carry over
    unchanged, so the pool works for every config the dense cache does.

    ``kv_dtype="int8"`` stores the pools quantized: each ``key``/
    ``value`` leaf becomes int8 and gains a ``key_scale``/``value_scale``
    sibling ``[n_blocks, block_len, H_kv]`` fp32 (the ``quantize_kv``
    layout — one scale per written row per head, so quantize-on-scatter
    and TP head-sharding both work unchanged). The attention read path
    dequantizes (in-VMEM for ``gather_impl="pallas"``, post-take for
    "dense"); ``models.transformer.Attention`` switches to quantize-on-
    scatter off the pool dtype alone, so the cache pytree IS the whole
    contract — no config flag to drift from it.
    """
    from pytorch_distributed_tpu.models.generate import init_cache

    if block_len < 1:
        raise ValueError(f"block_len must be >= 1, got {block_len}")
    if kv_dtype not in KV_DTYPES:
        raise ValueError(
            f"kv_dtype {kv_dtype!r} must be one of {KV_DTYPES} (None "
            "keeps the model compute dtype)"
        )
    shapes = jax.eval_shape(
        lambda p: init_cache(config, p, 1), params
    )
    if kv_dtype is None:
        return jax.tree.map(
            lambda s: jnp.zeros((n_blocks, block_len) + s.shape[2:],
                                s.dtype),
            shapes,
        )

    from collections.abc import Mapping

    def _quantized(node):
        # each layer's attention cache is a {"key": [1, L, H_kv, D],
        # "value": ...} pair; replace it with int8 pools + scale siblings
        if isinstance(node, Mapping) and set(node) == {"key", "value"}:
            out = {}
            for name in ("key", "value"):
                s = node[name]
                out[name] = jnp.zeros(
                    (n_blocks, block_len) + s.shape[2:], jnp.int8
                )
                out[name + "_scale"] = jnp.zeros(
                    (n_blocks, block_len, s.shape[2]), jnp.float32
                )
            return out
        if isinstance(node, Mapping):
            return {k: _quantized(node[k]) for k in node}
        raise ValueError(
            "unexpected cache tree layout for kv_dtype='int8': expected "
            "nested dicts ending in {'key', 'value'} leaf pairs, got "
            f"{type(node).__name__}"
        )

    return _quantized(shapes)


def pool_block_bytes(config, params, block_len: int,
                     kv_dtype: Optional[str] = None) -> int:
    """HBM bytes ONE pool block costs across every layer (K + V + any
    scale siblings) — the unit the capacity A/B divides a fixed byte
    budget by (``scripts/bench_serving.py --gather-ab``). Pure
    ``eval_shape`` arithmetic; nothing is allocated."""
    shapes = jax.eval_shape(
        lambda p: init_paged_cache(config, p, 2, block_len,
                                   kv_dtype=kv_dtype),
        params,
    )
    total = sum(
        leaf.size * leaf.dtype.itemsize for leaf in jax.tree.leaves(shapes)
    )
    return total // 2


def paged_cache_specs(config, cache):
    """TP placement for the pool: the HEAD dim (axis 2 — same leaf rank
    as the dense cache) shards over the model axis, exactly the slice
    each shard's Attention computes. Reuses the dense serving rule
    (``models.generate._cache_specs``) so the two layouts cannot drift.
    An int8 pool's rank-3 scale leaves ``[n_blocks, block_len, H_kv]``
    shard the same head dim (now the LAST axis): their spec is the
    rank-4 rule with its trailing D entry dropped — derived, so it
    cannot drift either."""
    from jax.sharding import PartitionSpec as P

    from pytorch_distributed_tpu.models.generate import _cache_specs

    specs = _cache_specs(config, cache)
    return jax.tree.map(
        lambda leaf, spec: spec if leaf.ndim == 4 else P(*tuple(spec)[:3]),
        cache, specs,
    )
