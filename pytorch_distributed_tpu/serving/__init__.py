"""Paged-KV serving engine (vLLM/PagedAttention + Orca continuous
batching, applied to the TP-capable JAX serving path).

The round-4/5 serving layer (``models.generate.ContinuousBatcher``) kept
one dense ``max_seq_len`` KV row per decode slot; every admission wrote a
full row — O(per-slot cache), the measured ~30% equilibrium throughput
tax at short outputs (BENCH_LM.md r5). This package replaces the dense
rows with a fixed pool of KV *blocks* plus per-slot block tables:

- ``kv_pool``   — the block allocator (free list, per-request chains,
  deterministic OOM → the caller queues instead of crashing) and the
  pooled cache pytree with its TP placement;
- ``engine``    — the compiled programs: k-batched chunk prefill (one
  insert program admits several requests) and the shared decode tick,
  both donating the pool so updates are in place;
- ``scheduler`` — the continuous scheduler: FIFO admission queue,
  chunked prefill interleaved with decode, slot accounting, and exact
  host-side metrics (occupancy, padding waste, admission latency, queue
  depth, tokens/s).

``models.generate.ContinuousBatcher`` delegates here by default
(``cache_layout="paged"``); the dense layout survives as
``cache_layout="dense"`` for parity tests. ANALYSIS.md "Serving engine"
documents the block layout and the admission path.

Round 12: the read path gains its fused Pallas kernel
(``gather_impl="pallas"`` → ``ops.paged_flash``, no materialized
gather) and the pool an int8 quantized variant (``kv_dtype="int8"``,
per-row scales, ~2x blocks at fixed bytes) — ANALYSIS.md "Paged
attention kernel & quantized KV".

Round 16: the async host runtime — ``scheduler`` splits each tick into
a non-blocking ``dispatch_tick`` and a lagged ``collect_tick``
(``engine.decode_launch``/``decode_collect``), and ``host_worker``
provides the thread pool the off-critical-path host work (JSONL, gate
percentile math) runs on; ``fleet.FleetRouter(async_host=True)`` is
the driver — ANALYSIS.md "Async host runtime".
"""

from pytorch_distributed_tpu.serving.kv_pool import (
    KV_DTYPES,
    SWAP_STATES,
    SWAPPING_IN,
    SWAPPING_OUT,
    TRASH_BLOCK,
    BlockAllocator,
    HostBlockStore,
    HostChain,
    PrefixIndex,
    blocks_needed,
    blocks_needed_suffix,
    init_paged_cache,
    paged_cache_specs,
    pool_block_bytes,
    quantize_kv,
)
from pytorch_distributed_tpu.serving.engine import (
    KVExport,
    PagedEngine,
    PendingSwap,
    PrefixHit,
)
from pytorch_distributed_tpu.serving.host_worker import HostWorkerPool
from pytorch_distributed_tpu.serving.scheduler import (
    Request,
    Scheduler,
    TickHandle,
)

__all__ = [
    "KV_DTYPES",
    "SWAP_STATES",
    "SWAPPING_IN",
    "SWAPPING_OUT",
    "TRASH_BLOCK",
    "BlockAllocator",
    "HostBlockStore",
    "HostChain",
    "PrefixIndex",
    "PrefixHit",
    "blocks_needed",
    "blocks_needed_suffix",
    "init_paged_cache",
    "paged_cache_specs",
    "pool_block_bytes",
    "quantize_kv",
    "KVExport",
    "PagedEngine",
    "PendingSwap",
    "HostWorkerPool",
    "Request",
    "Scheduler",
    "TickHandle",
]
