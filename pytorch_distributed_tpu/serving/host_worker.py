"""Worker-thread pool for the async host runtime (round 16).

The one-loop fleet serialized every replica's host work — JSONL
emission, gate-metric percentile math, tokenize — onto the critical
path between device dispatches; ``telemetry/overlap.py`` measured that
serialization as the dominant bubble cause (96% ``other-replica-tick``
at 2 replicas, ``BENCH_r06.json``). The async refactor moves that work
here: a small pool of named daemon threads draining a FIFO queue of
closures, so the main loop's only job between ticks is dispatch and
collect.

Thread-safety contract (the ``rules_threads`` inventory for this round;
ANALYSIS.md "Async host runtime" carries the full table):

- work items may touch ONLY (a) objects with their own locks
  (``MetricsLogger``, ``ReqTracer``, ``DispatchLedger``), (b) data
  copied onto the closure at enqueue time (the retired ``Request``,
  copied latency-series value lists), and (c) caches guarded by a
  dedicated lock (the scheduler's gate-metrics snapshot). Scheduler and
  router internals (``resident``, ``queue``, ``ready``, the
  ``BlockAllocator``, block tables) are MAIN-THREAD-ONLY — no work item
  may reference them;
- pool counters (``submitted``/``completed``/``errors``) mutate only
  under ``self._lock``;
- worker errors never kill the serve loop mid-tick: they latch into
  ``errors`` and re-raise at the next ``flush()`` — the same
  fail-at-the-barrier contract as the async checkpoint writers.

Ordering: one shared FIFO queue, ``n_threads`` consumers — items START
in submission order but may complete out of order across threads.
Every consumer of worker output tolerates that: JSONL records are
independent lines (reports aggregate, never assume adjacency), and the
gate cache keeps only the newest snapshot (a stale refresh overwriting
a newer one loses at most one tick of percentile drift, which the
overlay of live counters in ``Scheduler.gate_metrics`` bounds anyway).
Causal span records (``kind="span"``) stay on the main thread — seq
order is their contract.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, List, Optional

#: sentinel a closing pool feeds each worker
_STOP = object()


class HostWorkerPool:
    """N named daemon threads draining one FIFO queue of closures.

    ``submit(fn)`` enqueues; ``flush()`` blocks until everything
    enqueued so far has run (and re-raises the first worker error);
    ``close()`` flushes and joins the threads. Thread names
    (``pdt-host-0`` ...) are load-bearing: ``DispatchLedger.host``
    stamps them into worker-side host marks, which is how
    ``classify_bubbles`` tells overlapped worker work apart from
    ``idle-no-work``.
    """

    def __init__(self, n_threads: int = 2, name: str = "pdt-host"):
        if n_threads < 1:
            raise ValueError(f"n_threads must be >= 1, got {n_threads}")
        self._q: queue.Queue = queue.Queue()
        self._lock = threading.Lock()
        self._closed = False
        self.submitted = 0
        self.completed = 0
        self.errors: List[BaseException] = []
        self._threads = [
            threading.Thread(target=self._run, name=f"{name}-{i}",
                             daemon=True)
            for i in range(n_threads)
        ]
        for t in self._threads:
            t.start()

    def _run(self) -> None:
        while True:
            fn = self._q.get()
            if fn is _STOP:
                self._q.task_done()
                return
            try:
                fn()
            except BaseException as e:  # latch; re-raised at flush()
                with self._lock:
                    self.errors.append(e)
            finally:
                with self._lock:
                    self.completed += 1
                self._q.task_done()

    def submit(self, fn: Callable[[], None]) -> None:
        """Enqueue one closure (FIFO start order). Raises after
        ``close()`` — a closed pool silently dropping work would lose
        JSONL records."""
        with self._lock:
            if self._closed:
                raise RuntimeError("HostWorkerPool is closed")
            self.submitted += 1
        self._q.put(fn)

    def flush(self) -> None:
        """Block until every submitted item has run; re-raise the first
        worker error (cleared, so a handled failure does not re-fire at
        every later barrier)."""
        self._q.join()
        with self._lock:
            errors, self.errors = self.errors, []
        if errors:
            raise RuntimeError(
                f"{len(errors)} host-worker task(s) failed"
            ) from errors[0]

    @property
    def pending(self) -> int:
        """Items submitted but not yet completed (approximate — racing
        a draining worker — but monotone-consistent enough for tests
        and the top view)."""
        with self._lock:
            return self.submitted - self.completed

    def close(self) -> None:
        """Flush, then stop and join every worker. Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._q.join()
        for _ in self._threads:
            self._q.put(_STOP)
        for t in self._threads:
            t.join()
        with self._lock:
            errors, self.errors = self.errors, []
        if errors:
            raise RuntimeError(
                f"{len(errors)} host-worker task(s) failed"
            ) from errors[0]
